"""The tenant registry: durable JSON config binding edge bearer
tokens to corpus sources and worker pools, plus the journaled
onboarding state.

The config file is the operator's source of truth::

    {
      "version": 1,
      "default_pool": "acme",
      "tenants": {
        "acme": {"token": "tok-acme", "corpus": "vendored",
                 "pool": "acme"},
        "beta": {"token": "tok-beta", "corpus": "spdx"}
      }
    }

``pool`` defaults to the tenant's own name — the common one-pool-per-
tenant topology needs no extra config.  Saves are atomic (tmp +
``os.replace``) so a crash mid-save leaves the previous config intact.

Onboarding rolls are journaled NEXT TO the config file
(``<config>.journal``) through the jobs tier's fsync'd append-only
:class:`~licensee_tpu.jobs.journal.JobJournal`: a ``roll_start``
record lands before the fleet reload begins and a ``roll_done`` /
``roll_failed`` record after, so a SIGKILL mid-roll leaves a dangling
start that :meth:`TenantRegistry.pending_rolls` surfaces for recovery
at the next boot.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field

from licensee_tpu.jobs.journal import JobJournal, JournalError

REGISTRY_VERSION = 1


class RegistryError(Exception):
    """A malformed registry file or tenant definition (fail-closed:
    a fleet must not boot serving the wrong corpus to a token)."""


@dataclass
class Tenant:
    """One org's binding: bearer token -> corpus source -> pool."""

    name: str
    token: str
    corpus: str
    pool: str = ""
    # runtime state, not config: the fingerprint the tenant's pool is
    # currently serving (filled in after boot / after a roll)
    fingerprint: str | None = field(default=None, compare=False)

    def __post_init__(self):
        if not self.pool:
            self.pool = self.name

    def as_dict(self) -> dict:
        row = {"token": self.token, "corpus": self.corpus}
        if self.pool != self.name:
            row["pool"] = self.pool
        return row


def _parse_tenant(name: str, row) -> Tenant:
    if not isinstance(row, dict):
        raise RegistryError(f"tenant {name!r}: want an object, got "
                            f"{type(row).__name__}")
    token = row.get("token")
    corpus = row.get("corpus")
    if not isinstance(token, str) or not token:
        raise RegistryError(f"tenant {name!r}: missing 'token'")
    if not isinstance(corpus, str) or not corpus:
        raise RegistryError(f"tenant {name!r}: missing 'corpus'")
    pool = row.get("pool", "")
    if not isinstance(pool, str):
        raise RegistryError(f"tenant {name!r}: 'pool' must be a string")
    return Tenant(name=name, token=token, corpus=corpus, pool=pool)


class TenantRegistry:
    """The durable tenant table plus its onboarding journal.

    Thread-safe: the edge resolves tokens from its ops threads while
    an onboarding roll rewrites a tenant's corpus binding.
    """

    def __init__(self, path: str, *, create: bool = False):
        self.path = path
        self._lock = threading.Lock()
        self._tenants: dict[str, Tenant] = {}
        self.default_pool: str | None = None
        if create and not os.path.exists(path):
            self._save_locked()
        else:
            self._load()
        self._journal = JobJournal(path + ".journal")

    # -- config file --

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except OSError as exc:
            raise RegistryError(f"cannot read {self.path!r}: {exc}")
        except ValueError as exc:
            raise RegistryError(f"{self.path!r} is not JSON: {exc}")
        if not isinstance(doc, dict):
            raise RegistryError(f"{self.path!r}: want a JSON object")
        version = doc.get("version")
        if version != REGISTRY_VERSION:
            raise RegistryError(
                f"{self.path!r}: unsupported version {version!r} "
                f"(this build speaks {REGISTRY_VERSION})"
            )
        rows = doc.get("tenants")
        if not isinstance(rows, dict):
            raise RegistryError(f"{self.path!r}: missing 'tenants' object")
        tenants = {
            name: _parse_tenant(name, row) for name, row in rows.items()
        }
        tokens: dict[str, str] = {}
        for tenant in tenants.values():
            other = tokens.get(tenant.token)
            if other is not None:
                raise RegistryError(
                    f"token collision: tenants {other!r} and "
                    f"{tenant.name!r} share a bearer token"
                )
            tokens[tenant.token] = tenant.name
        default_pool = doc.get("default_pool")
        if default_pool is not None:
            if not isinstance(default_pool, str):
                raise RegistryError(
                    f"{self.path!r}: 'default_pool' must be a string"
                )
            pools = {t.pool for t in tenants.values()}
            if tenants and default_pool not in pools:
                raise RegistryError(
                    f"{self.path!r}: default_pool {default_pool!r} "
                    f"names no tenant pool (have {sorted(pools)})"
                )
        self._tenants = tenants
        self.default_pool = default_pool

    def _save_locked(self) -> None:
        doc: dict = {"version": REGISTRY_VERSION}
        if self.default_pool is not None:
            doc["default_pool"] = self.default_pool
        doc["tenants"] = {
            name: tenant.as_dict()
            for name, tenant in sorted(self._tenants.items())
        }
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=False)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)

    def save(self) -> None:
        with self._lock:
            self._save_locked()

    # -- lookups --

    def tenants(self) -> dict[str, Tenant]:
        with self._lock:
            return dict(self._tenants)

    def get(self, name: str) -> Tenant | None:
        with self._lock:
            return self._tenants.get(name)

    def by_token(self, token: str) -> Tenant | None:
        with self._lock:
            for tenant in self._tenants.values():
                if tenant.token == token:
                    return tenant
        return None

    def tokens(self) -> dict[str, str]:
        """token -> tenant name, the map the HTTP edge authenticates
        against (the edge's client label IS the tenant name)."""
        with self._lock:
            return {t.token: t.name for t in self._tenants.values()}

    def pools(self) -> dict[str, list[str]]:
        """pool name -> sorted tenant names bound to it."""
        out: dict[str, list[str]] = {}
        with self._lock:
            for tenant in self._tenants.values():
                out.setdefault(tenant.pool, []).append(tenant.name)
        return {pool: sorted(names) for pool, names in sorted(out.items())}

    def set_tenant(self, tenant: Tenant, *, save: bool = True) -> None:
        with self._lock:
            self._tenants[tenant.name] = tenant
            if save:
                self._save_locked()

    def update_corpus(
        self, name: str, corpus: str, fingerprint: str | None,
        *, save: bool = True,
    ) -> Tenant:
        """Rebind a tenant's corpus after a successful roll and persist
        the new binding (the registry file is what the NEXT boot serves
        from, so it must only ever name validated, rolled corpora)."""
        with self._lock:
            tenant = self._tenants.get(name)
            if tenant is None:
                raise RegistryError(f"unknown tenant {name!r}")
            tenant.corpus = corpus
            tenant.fingerprint = fingerprint
            if save:
                self._save_locked()
            return tenant

    # -- onboarding journal --

    def record_roll(self, event: str, tenant: str, **fields) -> None:
        """Append one onboarding lifecycle edge (``roll_start`` /
        ``roll_done`` / ``roll_failed``) — fsync'd before returning,
        so the record survives a SIGKILL of the fleet process."""
        row = {"event": event, "tenant": tenant}
        row.update(fields)
        self._journal.append(row)

    def pending_rolls(self) -> list[dict]:
        """Every journaled ``roll_start`` without a matching terminal
        record — the rolls a crash interrupted, replayed at boot by
        :meth:`CorpusOnboarder.recover`."""
        try:
            records = self._journal.replay()
        except JournalError:
            # a corrupt non-tail record means the journal cannot be
            # trusted for recovery; fail open to "nothing pending"
            # rather than re-rolling from garbage
            return []
        open_rolls: dict[str, dict] = {}
        for row in records:
            event = row.get("event")
            tenant = row.get("tenant")
            if not isinstance(tenant, str):
                continue
            if event == "roll_start":
                open_rolls[tenant] = row
            elif event in ("roll_done", "roll_failed"):
                open_rolls.pop(tenant, None)
        return list(open_rolls.values())

    def close(self) -> None:
        self._journal.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
