"""Multi-tenant serving: tenant registry, per-pool supervision, and
self-serve corpus onboarding.

The subsystem closes the loop from an edge bearer token to a corpus
fingerprint:

- :mod:`licensee_tpu.tenancy.registry` — the durable tenant config
  (token -> tenant -> corpus source -> pool) plus the journaled
  onboarding state that survives a crash mid-roll.
- :mod:`licensee_tpu.tenancy.pools` — heterogeneous worker pools: one
  :class:`~licensee_tpu.fleet.supervisor.Supervisor` per pool behind
  the supervisor surface the router consumes, with a per-pool
  ``reload_fleet``.
- :mod:`licensee_tpu.tenancy.onboard` — the authenticated
  upload -> validate -> roll -> persist pipeline behind the edge's
  ``POST /corpus`` verb.
"""

from licensee_tpu.tenancy.onboard import CorpusOnboarder, OnboardError
from licensee_tpu.tenancy.pools import TenantPools
from licensee_tpu.tenancy.registry import (
    RegistryError,
    Tenant,
    TenantRegistry,
)

__all__ = [
    "CorpusOnboarder",
    "OnboardError",
    "RegistryError",
    "Tenant",
    "TenantRegistry",
]
