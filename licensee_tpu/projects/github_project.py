"""Remote GitHub project backend via the contents API.

Parity target: `lib/licensee/projects/github_project.rb` (octokit).  Only
the repository root is scanned, because every file load is a separate API
request.  Tests stub the HTTP layer (the reference does the same with
WebMock) — no live network access is required for the suite.
"""

from __future__ import annotations

import json
import os
import re
import urllib.error
import urllib.parse
import urllib.request

from licensee_tpu.projects.project import Project

# github_project.rb:19-20 — trailing data (e.g. `.git`) is ignored
GITHUB_REPO_PATTERN = re.compile(
    r"https://github.com/([^/]+/(?:[^/]+(?=\.git)|[^/]+)).*"
)

API_ROOT = "https://api.github.com"


class RepoNotFound(Exception):
    pass


class GitHubProject(Project):
    def __init__(self, github_url: str, ref: str | None = None, **args):
        m = GITHUB_REPO_PATTERN.match(github_url)
        if not m:
            raise ValueError(f"Not a github URL: {github_url}")
        self.repo = m.group(1)
        self.ref = ref
        super().__init__(**args)

    # -- HTTP layer (overridable in tests) --

    def _request(self, path: str, raw: bool = False):
        query = f"?ref={urllib.parse.quote(self.ref)}" if self.ref else ""
        url = f"{API_ROOT}/repos/{self.repo}/contents/{path or ''}{query}"
        headers = {"Accept": "application/vnd.github.v3.raw" if raw else "application/vnd.github.v3+json"}
        token = os.environ.get("OCTOKIT_ACCESS_TOKEN")
        if token:
            headers["Authorization"] = f"token {token}"
        req = urllib.request.Request(url, headers=headers)
        try:
            with urllib.request.urlopen(req) as resp:
                body = resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise
        return body if raw else json.loads(body)

    # -- Project interface --

    def files(self) -> list[dict]:
        cached = self.__dict__.get("_files")
        if cached is None:
            cached = self._dir_files()
            if not cached:
                raise RepoNotFound(
                    f"Could not load GitHub repo {self.repo}, "
                    "it may be private or deleted"
                )
            self.__dict__["_files"] = cached
        return cached

    def load_file(self, file: dict):
        body = self._request(file["path"], raw=True)
        if body is None:
            # a listed file vanishing mid-detection is an API error, not an
            # empty license (github_project.rb:48-53 lets octokit raise)
            raise RepoNotFound(
                f"Could not load {file['path']} from GitHub repo {self.repo}"
            )
        return body

    def _dir_files(self, path: str | None = None) -> list[dict]:
        if path:
            path = path.replace("./", "")
        listing = self._request(path)
        if listing is None:
            return []
        files = [entry for entry in listing if entry.get("type") == "file"]
        for entry in files:
            entry["dir"] = os.path.dirname(entry["path"]) or "."
        return files
