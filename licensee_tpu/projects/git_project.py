"""Git project backend: read the tree of a revision without a checkout.

Parity target: `lib/licensee/projects/git_project.rb` (rugged/libgit2).
This backend reads blobs straight from the git object database via the
native C++ ODB reader (native/gitodb.cpp — loose objects, packfiles v2
with deltas, ref resolution; the equivalent of the reference's libgit2
dependency), falling back to `git` plumbing subprocesses when the native
library can't be built.  Blob loads are capped at ``MAX_LICENSE_SIZE``
bytes like the reference (git_project.rb:53).
"""

from __future__ import annotations

import os
import subprocess

from licensee_tpu.projects.project import Project

MAX_LICENSE_SIZE = 64 * 1024


class InvalidRepository(ValueError):
    pass


def _run_git(repo: str, *args: str) -> bytes:
    result = subprocess.run(
        ["git", "-C", repo, *args],
        capture_output=True,
        check=False,
    )
    if result.returncode != 0:
        raise InvalidRepository(result.stderr.decode("utf-8", errors="ignore"))
    return result.stdout


class _NativeBackend:
    """git_project.rb's rugged usage, over the native ODB reader."""

    def __init__(self, repo: str, revision: str | None):
        from licensee_tpu.native.gitodb import GitODB, GitODBError

        self._files: list[dict] | None = None
        self._odb = None
        try:
            self._odb = GitODB(repo)
            self._commit = self._odb.resolve(revision or "HEAD")
        except GitODBError as exc:
            # don't leave the native handle to the GC on the error path
            self.close()
            raise InvalidRepository(str(exc)) from exc

    def close(self) -> None:
        if self._odb is not None:
            self._odb.close()
            self._odb = None

    def files(self) -> list[dict]:
        if self._files is None:
            from licensee_tpu.native.gitodb import GitODBError

            try:
                entries = self._odb.root_entries(self._commit)
            except (GitODBError, ValueError) as exc:
                raise InvalidRepository(str(exc)) from exc
            # symlinks (mode 120000) are blob-backed and count as blobs,
            # matching rugged's entry typing and `git ls-tree` (both report
            # them as blob)
            self._files = [
                {"name": e["name"], "oid": e["oid"], "dir": "."}
                for e in entries
                if e["type"] in ("blob", "link")
            ]
        return self._files

    def load_file(self, file: dict) -> bytes | None:
        from licensee_tpu.native.gitodb import GitODBError

        try:
            # one byte past the cap detects oversize without a separate
            # size probe: an oversized blob is SKIPPED (None), never
            # truncated-and-scored — a 64 KiB head can match a license
            # the rest of the file contradicts (git_project.rb:53 cap)
            data = self._odb.read_blob(file["oid"], MAX_LICENSE_SIZE + 1)
        except GitODBError as exc:
            raise InvalidRepository(str(exc)) from exc
        return None if len(data) > MAX_LICENSE_SIZE else data


class _SubprocessBackend:
    """`git cat-file`/`ls-tree` plumbing fallback."""

    def __init__(self, repo: str, revision: str | None):
        self.repo = repo
        self.revision = revision
        try:
            # resolves only inside an actual repository; unborn HEAD raises
            git_dir = _run_git(repo, "rev-parse", "--git-dir").strip()
            if not git_dir:
                raise InvalidRepository(repo)
            # Reject repos found by upward discovery from a plain directory:
            # the reference opens the path itself as a repository.  A .git
            # *file* (gitlink: linked worktrees, submodules) is a repository
            # at this path — libgit2 follows it, so we do too.
            absolute_git_dir = os.path.abspath(
                os.path.join(repo, git_dir.decode("utf-8", errors="ignore"))
            )
            repo_abs = os.path.abspath(repo)
            if not (
                absolute_git_dir == repo_abs
                or os.path.dirname(absolute_git_dir) == repo_abs
                or os.path.isfile(os.path.join(repo, ".git"))
            ):
                raise InvalidRepository(repo)
            _run_git(repo, "rev-parse", "--verify", revision or "HEAD")
        except FileNotFoundError as exc:
            raise InvalidRepository(str(exc)) from exc

    def close(self) -> None:
        pass

    def files(self) -> list[dict]:
        rev = self.revision or "HEAD"
        out = _run_git(self.repo, "ls-tree", rev)
        files = []
        for line in out.decode("utf-8", errors="ignore").splitlines():
            if not line:
                continue
            meta, name = line.split("\t", 1)
            _mode, otype, oid = meta.split()
            if otype == "blob":
                files.append({"name": name, "oid": oid, "dir": "."})
        return files

    def load_file(self, file: dict) -> bytes | None:
        data = _run_git(self.repo, "cat-file", "blob", file["oid"])
        # same skip-not-truncate cap semantics as the native backend
        return None if len(data) > MAX_LICENSE_SIZE else data


class GitProject(Project):
    def __init__(self, repo: str, revision: str | None = None, **args):
        self.repo_path = repo
        self.revision = revision

        if not os.path.isdir(repo):
            raise InvalidRepository(repo)

        self._backend = self._open_backend(repo, revision)
        super().__init__(**args)

    @staticmethod
    def _open_backend(repo: str, revision: str | None):
        from licensee_tpu.native.gitodb import NativeUnavailable

        backend = None
        try:
            backend = _NativeBackend(repo, revision)
            # probe the root tree: a repo shape the native reader cannot
            # fully serve (e.g. exotic layouts) falls back to plumbing
            # instead of masquerading as an invalid repository
            backend.files()
            return backend
        except (NativeUnavailable, InvalidRepository):
            if backend is not None:
                backend.close()
            return _SubprocessBackend(repo, revision)

    def close(self) -> None:
        self._backend.close()

    def files(self) -> list[dict]:
        """Root-tree blob entries of the target commit
        (git_project.rb:64-76: only type == :blob, root level)."""
        cached = self.__dict__.get("_files")
        if cached is None:
            cached = self._backend.files()
            self.__dict__["_files"] = cached
        return cached

    def load_file(self, file: dict) -> bytes | None:
        """Blob bytes, or None for a blob past the MAX_LICENSE_SIZE
        cap (skipped, never truncated-and-scored — the Project layer
        drops skipped candidates)."""
        return self._backend.load_file(file)
