"""Git project backend: read the tree of a revision without a checkout.

Parity target: `lib/licensee/projects/git_project.rb` (rugged/libgit2).
This backend reads blobs straight from the git object database via the
native ODB reader in `native/` when built (a C++ equivalent of the
reference's libgit2 dependency), falling back to `git cat-file --batch`
plumbing subprocesses otherwise.  Blob loads are capped at
``MAX_LICENSE_SIZE`` bytes like the reference (git_project.rb:53).
"""

from __future__ import annotations

import os
import subprocess

from licensee_tpu.projects.project import Project

MAX_LICENSE_SIZE = 64 * 1024


class InvalidRepository(ValueError):
    pass


def _run_git(repo: str, *args: str) -> bytes:
    result = subprocess.run(
        ["git", "-C", repo, *args],
        capture_output=True,
        check=False,
    )
    if result.returncode != 0:
        raise InvalidRepository(result.stderr.decode("utf-8", errors="ignore"))
    return result.stdout


class GitProject(Project):
    def __init__(self, repo: str, revision: str | None = None, **args):
        self.repo_path = repo
        self.revision = revision

        if not os.path.isdir(repo):
            raise InvalidRepository(repo)
        try:
            # resolves only inside an actual repository; unborn HEAD raises
            git_dir = _run_git(repo, "rev-parse", "--git-dir").strip()
            if not git_dir:
                raise InvalidRepository(repo)
            # Reject repos found by upward discovery from a plain directory:
            # the reference opens the path itself as a repository.
            absolute_git_dir = os.path.abspath(
                os.path.join(repo, git_dir.decode("utf-8", errors="ignore"))
            )
            repo_abs = os.path.abspath(repo)
            if not (
                absolute_git_dir == repo_abs
                or os.path.dirname(absolute_git_dir) == repo_abs
            ):
                raise InvalidRepository(repo)
            _run_git(repo, "rev-parse", "--verify", self.revision or "HEAD")
        except FileNotFoundError as exc:
            raise InvalidRepository(str(exc)) from exc

        super().__init__(**args)

    def close(self) -> None:
        pass

    def files(self) -> list[dict]:
        """Root-tree blob entries of the target commit
        (git_project.rb:64-76: only type == :blob, root level)."""
        cached = self.__dict__.get("_files")
        if cached is None:
            rev = self.revision or "HEAD"
            out = _run_git(self.repo_path, "ls-tree", rev)
            cached = []
            for line in out.decode("utf-8", errors="ignore").splitlines():
                if not line:
                    continue
                meta, name = line.split("\t", 1)
                _mode, otype, oid = meta.split()
                if otype == "blob":
                    cached.append({"name": name, "oid": oid, "dir": "."})
            self.__dict__["_files"] = cached
        return cached

    def load_file(self, file: dict) -> bytes:
        data = _run_git(self.repo_path, "cat-file", "blob", file["oid"])
        return data[:MAX_LICENSE_SIZE]
