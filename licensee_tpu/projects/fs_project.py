"""Filesystem project backend.

Parity target: `lib/licensee/projects/fs_project.rb` — a directory (glob
`*`) or single file, with an optional ``search_root`` that widens the
search to every directory between the project dir and the root.
"""

from __future__ import annotations

import glob
import os

from licensee_tpu.projects.project import Project


class FSProject(Project):
    def __init__(self, path: str, search_root: str | None = None, **args):
        if os.path.isfile(path):
            self.pattern = os.path.basename(path)
            self.dir = os.path.abspath(os.path.dirname(path))
        else:
            self.pattern = "*"
            self.dir = os.path.abspath(path)

        self.root = os.path.abspath(search_root or self.dir)
        if not self._valid_search_root():
            raise ValueError(
                "Search root must be the project path directory or its ancestor"
            )
        super().__init__(**args)

    def files(self) -> list[dict]:
        cached = self.__dict__.get("_files")
        if cached is None:
            cached = []
            for directory in self._search_directories():
                relative_dir = os.path.relpath(directory, self.dir)
                pattern = os.path.join(glob.escape(directory), self.pattern)
                for file in sorted(glob.glob(pattern)):
                    if os.path.isfile(file):
                        cached.append(
                            {"name": os.path.basename(file), "dir": relative_dir}
                        )
            self.__dict__["_files"] = cached
        return cached

    def load_file(self, file: dict) -> str:
        path = os.path.join(self.dir, file["dir"], file["name"])
        with open(path, "rb") as f:
            raw = f.read()
        return raw.decode("utf-8", errors="ignore")

    def _valid_search_root(self) -> bool:
        # fs_project.rb:60-63: root is dir itself or an ancestor
        return self.dir == self.root or self.dir.startswith(self.root + os.sep)

    def _search_directories(self) -> list[str]:
        """All directories from self.dir up to self.root, inclusive
        (fs_project.rb:66-81)."""
        dirs = []
        current = self.dir
        while True:
            dirs.append(current)
            if current == self.root:
                break
            parent = os.path.dirname(current)
            if parent == current:
                break
            current = parent
        if self.root not in dirs:
            dirs.append(self.root)
        return dirs
