from licensee_tpu.projects.project import Project
from licensee_tpu.projects.fs_project import FSProject
from licensee_tpu.projects.git_project import GitProject, InvalidRepository
from licensee_tpu.projects.github_project import GitHubProject, RepoNotFound

__all__ = [
    "Project",
    "FSProject",
    "GitProject",
    "GitHubProject",
    "InvalidRepository",
    "RepoNotFound",
]
