"""BatchProject: classify a manifest of millions of blobs.

The scale-out ingestion path of SURVEY.md §7 step 5: manifest -> read +
featurize worker threads -> bounded queue of packed feature batches ->
device scoring overlapped with the next batches' featurization -> JSONL
results, with a resumable shard manifest (the checkpoint/resume
subsystem; the reference's closest analog is its pervasive memoization +
golden caches, SURVEY.md §5).

Pipelining model: featurization is dominated by native code that releases
the GIL (native/pipeline.cpp), so a thread pool gives real host
parallelism on multi-core machines; device dispatch is asynchronous under
JAX, so batch k's device scoring runs while batches k+1..k+inflight
featurize.  Results are written strictly in manifest order, preserving the
line-count == completed-prefix resume invariant.

Host pre-filters (Copyright regex, Exact wordset hash) short-circuit blobs
before they are packed for HBM, mirroring the first-match-wins chain
(project_files/project_file.rb:69-71).

ADR — the measured host scaling model (bench.py bench_host_model, r4)
---------------------------------------------------------------------
Per ~11KB unique blob (min-of-N solo runs, 1-core VM, 2026-07-30):
read 11us, sha1-dedupe 9us, native featurize crossing 258us, Python
bookkeeping in prepare_batch ~1us, JSONL row 1.7us.  The round-3
"unexplained ~100us over the native floor" is resolved: the native
crossing itself measures ~258us/blob for 11KB blobs on this VM's
shared core (the ~150us floor was a 10KB best case on a quiet core) —
there is no hidden Python gap (bookkeeping ~1us).

Pipeline split per blob: parallel (worker threads: read+featurize)
~403us; serial (main thread: dispatch+finish+write loop) ~27us —
serial fraction 6.4%.  Amdahl: one process caps at ~37k files/s no
matter the core count, so 10M files / 60s (167k files/s) is NOT a
single-process target: it takes >=5 manifest-striped processes
(parallel/distributed.py stripes the writer too — each process
carries its own serial section).  Processes share one machine:
the north-star v5e-8 host runs 5 processes x ~14 cores (~70 of the
ct5lp-hightpu-8t's 224 vCPUs), chips split across processes via
LICENSEE_TPU_COORDINATOR=localhost plus per-rank
LICENSEE_TPU_VISIBLE_CHIPS (parallel/distributed.py
apply_visible_chips).  Status r5: EXERCISED (CPU rehearsal) — the
2-process cluster test gives each child its own chip subset and a
real 2-device local data mesh through the sharded scorer
(tests/test_distributed.py); README documents the v5e-8 launch line
incl. the libtpu co-location vars (exported per contract; real
multi-chip hardware is not available to this build env).  bench.py prints the live
model (serial_fraction, amdahl ceiling, striped-process count) under
details.host_model on every run.

Update r6: the JSONL finish/write loop moved off the main thread onto a
bounded writer thread (see run() — order preserved by sequence numbers,
resume invariant unchanged), so the per-process serial section is now
dispatch+finish only and the Amdahl ceiling rises accordingly; and the
manifest-striping contract became a one-command launcher
(`licensee-tpu batch-detect --stripes N`, parallel/stripes.py) that
spawns co-located stripe processes under a supervisor and merges their
shards/stats/expositions deterministically.

Update r8: the run loop is an explicit bounded software pipeline over
the non-blocking device seam (`dispatch_chunks_async` -> DeviceFuture,
kernels/batch.py): up to ``pipeline_depth`` dispatched groups stay in
flight while the workers featurize ahead and the writer thread drains
behind, groups are awaited strictly FIFO (output bit-identical at
every depth, resume invariant untouched), and per-lane occupancy
(featurize | device | writer) + the in-flight-chunks gauge surface
through obs/pipeline.py — at-scale files/s now tracks
``1/max(featurize_lane, writer_lane)`` with the device term invisible
(the overlap row of bench.py's host model).  ``--device-lanes`` adds
in-stripe multi-chip scoring: whole chunks round-robin across the
stripe's visible chips, K device lanes behind one featurize lane.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field, replace

import numpy as np

import licensee_tpu
from licensee_tpu.kernels.batch import BlobResult

# The produce-stage core (route + read + dedupe-key + prefilter +
# featurize + row rendering) is shared with the online serving path —
# serve/featurize.py holds the one implementation, so the offline and
# online chains cannot drift.  The private aliases keep this module's
# long-standing names (tests and the _mp_* workers use them).
from licensee_tpu.serve.featurize import (
    IN_BATCH_DUP as _IN_BATCH_DUP,
    UNROUTED as _UNROUTED,
    json_str as _json_str,
    jsonl_row as _jsonl_row,
    produce_batch as _produce_batch,
    read_capped as _read_capped,
)

__all__ = ["BatchProject", "BatchStats", "ResumeConfigError"]


class ResumeConfigError(ValueError):
    """A resume whose row-shaping config (mode/corpus/threshold/closest/
    attribution) differs from the run that wrote the output file."""


# -- process-pool featurization (--featurize-procs) --
#
# GIL insurance: the thread pipeline's scaling argument rests on the
# native batch crossing dropping the GIL; on hosts where that
# disappoints (or the pure-Python fallback pipeline runs), worker
# PROCESSES featurize instead.  Workers build a host-only classifier
# (device=False — no jax backend init, no TPU contention) from the
# parent's pickled CompiledCorpus; batches come back as plain numpy +
# dataclasses.  The cross-batch dedupe cache stays in the parent and is
# applied on receipt: a cache-hit row still pays worker featurization
# (the price of process isolation) but skips device scoring.  Output is
# bit-identical to the thread path; the resume invariant (in-order
# writes) is untouched because only the produce stage moves.
#
# Crossover guidance: spawn + per-worker corpus build costs seconds up
# front and each batch pays ~2 MB of array pickling (plus, with
# --attribution, the raw bytes of rows still in the running for the
# attribution regex — up to 64 KiB each, trimmed in _produce_batch);
# threads win whenever the native pipeline is up (its crossing releases
# the GIL), processes win on the pure-Python pipeline beyond ~2 cores.
#
# Container manifests compose with --featurize-procs: each worker
# re-opens the containers from the expansion's picklable descriptor
# (_mp_init) — fresh per-process handles, never inherited fds — and
# reads positionally by the chunk's span offset, so duplicate member
# names across containers still cannot cross wires.

_MP_STATE: dict = {}


def _mp_init(corpus, mode, batch_size, ingest_desc=None):
    from licensee_tpu.kernels.batch import BatchClassifier

    _MP_STATE["clf"] = BatchClassifier(
        corpus=corpus,
        mode=mode,
        pad_batch_to=batch_size,
        mesh=None,
        device=False,
    )
    # container manifests: the worker RE-OPENS the containers from the
    # parent's picklable descriptor (entries + span + fingerprint) —
    # container handles hold fds/odb objects that must never cross the
    # spawn boundary, and the fingerprint check refuses if an archive
    # changed between the parent's expansion and this worker's
    if ingest_desc is not None:
        from licensee_tpu.ingest.sources import ManifestExpansion

        _MP_STATE["ingest"] = ManifestExpansion.from_descriptor(
            ingest_desc
        )


def _mp_produce(chunk, mode, dedupe, attribution, start=None):
    exp = _MP_STATE.get("ingest")
    read = filenames = None
    if exp is not None and start is not None:
        # positional reads through the worker's OWN container handles:
        # `start` is the chunk's offset into this rank's span, exactly
        # the thread path's _read_hook contract
        read_at = exp.read_at
        read = lambda _path, i: read_at(start + i)  # noqa: E731
        filenames = exp.filenames[start : start + len(chunk)]
    return (chunk, *_produce_batch(
        _MP_STATE["clf"], chunk, mode, dedupe, attribution, cache=None,
        read=read, filenames=filenames,
    ))


@dataclass
class BatchStats:
    total: int = 0
    prefiltered_copyright: int = 0
    prefiltered_exact: int = 0
    dice_matched: int = 0
    reference_matched: int = 0
    package_matched: int = 0
    unmatched: int = 0
    read_errors: int = 0
    featurize_errors: int = 0
    dedupe_hits: int = 0
    # blobs past the MAX_LICENSE_SIZE 64 KiB cap: skipped, never
    # truncated-and-scored (their rows carry error="oversized")
    skipped_oversized: int = 0
    # --mode auto: rows per dispatched chain ("license" / "readme" /
    # "package" / "none" for filenames no table scores) — the per-mode
    # stats split of a mixed-manifest run
    routed: dict = field(default_factory=dict)
    # per-stage wall-clock seconds (the observability surface of
    # SURVEY.md §5; read+featurize accumulate across worker threads, so
    # they can exceed elapsed on multi-core hosts)
    stage_seconds: dict = field(default_factory=dict)
    # the run's lane-occupancy snapshot (obs/pipeline.py PipelineLanes
    # .occupancy()): busy fraction per featurize/device/writer lane —
    # the overlap proof a bench or operator reads off a finished run
    pipeline: dict = field(default_factory=dict)

    def add_stage(self, stage: str, seconds: float) -> None:
        self.stage_seconds[stage] = self.stage_seconds.get(stage, 0.0) + seconds

    def add_route(self, route: str | None) -> None:
        route = route or "none"
        self.routed[route] = self.routed.get(route, 0) + 1

    def as_dict(self) -> dict:
        d = dict(self.__dict__)
        if not d["routed"]:
            del d["routed"]  # fixed-mode runs keep their old stats shape
        if not d["pipeline"]:
            del d["pipeline"]  # unpipelined paths keep their old shape
        if not d["skipped_oversized"]:
            del d["skipped_oversized"]  # capped runs keep their old shape
        d["stage_seconds"] = {
            k: round(v, 4) for k, v in self.stage_seconds.items()
        }
        return d


class BatchProject:
    """Classify every path in a manifest against the compiled corpus.

    Results stream to ``<output>`` as JSON lines; a run interrupted at any
    point resumes from the last completed batch (line count == completed
    prefix of the manifest)."""

    def __init__(
        self,
        manifest_paths: list[str],
        corpus=None,
        method: str = "auto",
        batch_size: int = 4096,
        threshold: float | None = None,
        workers: int | None = None,
        inflight: int = 3,
        mesh="auto",
        classifier=None,
        process_index: int | None = None,
        process_count: int | None = None,
        mode: str = "license",
        dedupe: bool = True,
        dedupe_cap: int = 1 << 20,
        closest: int = 0,
        attribution: bool = False,
        featurize_procs: int = 0,
        progress_every: float = 0,
        already_striped: bool = False,
        coalesce_batches: int = 32,
        tracer=None,
        corpus_source: str | None = None,
        pipeline_depth: int = 2,
        device_lanes: int | str | None = None,
    ):
        from licensee_tpu.kernels.batch import BatchClassifier

        # Multi-host: this process owns a contiguous stripe of the global
        # manifest and writes its own output shard (see
        # parallel/distributed.py for the DCN placement rationale).
        # Explicit kwargs win; otherwise the jax.distributed world (if
        # initialized) decides; otherwise single-process.
        if (process_index is None) != (process_count is None):
            raise ValueError(
                "process_index and process_count must be given together"
            )
        if process_count is None:
            try:
                import jax

                process_count = jax.process_count()
                process_index = jax.process_index()
            except Exception:
                process_count, process_index = 1, 0
        self.process_index = process_index
        self.process_count = process_count
        paths = list(manifest_paths)
        # -- streaming container ingestion (ingest/sources.py) --
        #
        # Manifest entries may address tar/zip/git containers
        # (`archive.tar::member`, `archive.tar::*`, `repo.git::HEAD`);
        # whole-container forms expand here into one work item per
        # member blob, read straight out of the container by the
        # produce workers — no extraction to disk.  Expansion is
        # deterministic, so the blob-level resume invariant (line
        # count == completed prefix) holds unchanged; the expansion
        # fingerprint joins the resume sidecar so a rewritten archive
        # refuses to resume instead of appending foreign rows.
        #
        # Striping over containers is denominated in EXPANDED blob
        # counts: every rank expands the same full manifest (metadata
        # only — member tables, central directories, git root trees)
        # and restricts itself to its span of the expanded rows, so
        # the supervisor (parallel/stripes.py expanded_layout) and the
        # workers agree on span arithmetic by construction, and a
        # single million-member tarball splits across stripes.
        self.ingest = None
        from licensee_tpu.ingest.sources import (
            expand_manifest,
            is_container_entry,
        )

        has_containers = any(is_container_entry(p) for p in paths)
        if self.process_count > 1 and not already_striped and (
            not has_containers
        ):
            from licensee_tpu.parallel.distributed import manifest_stripe

            lo, hi = manifest_stripe(
                len(paths), self.process_index, self.process_count
            )
            paths = paths[lo:hi]
        if has_containers:
            if already_striped and self.process_count > 1:
                # the caller pre-sliced raw entries; expanded-count
                # spans need the FULL manifest on every rank
                raise ValueError(
                    "container manifests stripe by expanded blob "
                    "count; pass the full manifest to every rank "
                    "(already_striped does not apply)"
                )
            self.ingest = expand_manifest(paths)
            if self.process_count > 1:
                from licensee_tpu.parallel.distributed import (
                    manifest_stripe,
                )

                lo, hi = manifest_stripe(
                    self.ingest.total,
                    self.process_index,
                    self.process_count,
                )
                self.ingest.restrict(lo, hi)
            paths = self.ingest.paths
        self.paths = paths
        # a caller-supplied classifier (pad_batch_to must equal batch_size)
        # reuses its compiled scorer across runs — e.g. a warmed-up one
        self.classifier = classifier or BatchClassifier(
            corpus=corpus,
            method=method,
            pad_batch_to=batch_size,
            mesh=mesh,
            mode=mode,
            closest=closest,
            # --device-lanes: round-robin whole chunks across this
            # stripe's visible chips (K device lanes behind one
            # featurize lane); overrides mesh sharding when set
            lanes=device_lanes,
        )
        if self.classifier.pad_batch_to != batch_size:
            raise ValueError(
                f"classifier pad_batch_to={self.classifier.pad_batch_to} "
                f"!= batch_size={batch_size}"
            )
        self.batch_size = batch_size
        self.threshold = (
            licensee_tpu.confidence_threshold() if threshold is None else threshold
        )
        self.workers = workers or min(32, (os.cpu_count() or 1))
        self.inflight = max(1, inflight)
        # cross-batch device coalescing: how many produced batches may
        # wait in the buffer while their sparse todo rows accumulate
        # toward a full pad_batch_to device chunk.  Bounds both the
        # write-latency burst and the buffered-path memory (a dedupe-hit
        # batch holds its paths/results; its dense feature arrays are
        # compacted away on entry).  1 disables coalescing.
        if coalesce_batches < 1:
            raise ValueError(
                f"coalesce_batches must be >= 1, got {coalesce_batches!r}"
            )
        self.coalesce_batches = int(coalesce_batches)
        # --pipeline-depth: how many dispatched device GROUPS may be in
        # flight at once.  1 = the synchronous path (dispatch, await,
        # write — the bit-identical baseline); >= 2 = the software
        # pipeline, where the host featurizes chunk N+1 and the writer
        # drains chunk N-1 while the device scores chunk N.  Output is
        # identical at every depth: groups are awaited strictly FIFO
        # and rows carry sequence numbers into the writer.
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth!r}"
            )
        self.pipeline_depth = int(pipeline_depth)
        self.stats = BatchStats()
        # Content-dedupe: real license corpora are dominated by verbatim
        # copies of a few hundred texts, so a content-hash -> result
        # cache short-circuits featurization AND device scoring for every
        # repeat.  Classification is a pure function of the content plus
        # the filename-dependent dispatch — the HTML gate in license/
        # readme mode, the matcher table in package mode — so the key
        # carries exactly that dispatch and a hit is exact, not
        # approximate.  FIFO-bounded; workers only read (GIL-atomic dict
        # ops), the writer thread inserts after device finish.
        self.dedupe = dedupe
        self.dedupe_cap = dedupe_cap
        self._dedupe_cache: dict = {}
        self.mode = self.classifier.mode
        # the --corpus source string ("vendored" / "spdx" / a dir / an
        # artifact path), recorded in the resume sidecar so a corpus-
        # fingerprint refusal can NAME the corpus that wrote the output
        self.corpus_source = corpus_source
        # --attribution: extract the copyright line per matched blob
        # (post-match host regex; with dedupe, once per unique content).
        # Raw contents ride the pipeline tuples only when enabled.
        self.attribution = attribution
        # --featurize-procs N: produce batches in N worker PROCESSES
        # instead of threads (see the _mp_* machinery above).  Validate
        # BEFORE int() truncation: -0.9 must not slip through as 0.
        if not (featurize_procs is None or featurize_procs >= 0):
            raise ValueError(
                f"featurize_procs must be >= 0, got {featurize_procs!r}"
            )
        self.featurize_procs = int(featurize_procs or 0)
        # --progress SECS: emit a JSON progress line to stderr at most
        # every SECS seconds while run() streams (a 50M-file scan should
        # not be a black box for an hour); 0 disables
        self.progress_every = float(progress_every or 0)
        if not (self.progress_every >= 0):  # rejects negatives AND NaN
            raise ValueError(
                f"progress_every must be >= 0, got {progress_every!r}"
            )
        # per-chunk observability: every produced batch gets a trace in
        # the PROCESS-WIDE tracer (obs/tracing.py get_tracer) with
        # read / featurize / device / write spans — the offline twin of
        # the serve path's per-request traces, at one trace per
        # batch_size files (negligible against a multi-second chunk).
        # Pass tracer=False to opt out, or a Tracer to isolate.
        if tracer is False:
            self._tracer = None
        else:
            from licensee_tpu.obs import get_tracer

            self._tracer = get_tracer() if tracer is None else tracer

    @classmethod
    def from_manifest_file(cls, manifest_file: str, **kwargs) -> "BatchProject":
        """Build a project from a one-path-per-line manifest.

        In a multi-host world this materializes ONLY this process's
        stripe: a 50M-line manifest (BASELINE.md config 5) costs each of
        N hosts ~1/N of the path memory instead of the whole list — the
        first pass counts lines, the second collects the [lo, hi) span.
        """
        process_count = kwargs.get("process_count")
        process_index = kwargs.get("process_index")
        if (process_index is None) != (process_count is None):
            # same contract as the constructor: both or neither
            raise ValueError(
                "process_index and process_count must be given together"
            )
        if process_count is None:
            try:
                import jax

                process_count = jax.process_count()
                process_index = jax.process_index()
            except Exception:
                process_count, process_index = 1, 0
        if process_count > 1:
            from licensee_tpu.ingest.sources import is_container_entry
            from licensee_tpu.parallel.distributed import (
                count_manifest_entries,
                manifest_stripe,
            )

            # container manifests stripe by EXPANDED blob counts: the
            # constructor needs the FULL entry list on every rank to
            # enumerate the container spans, so no raw-line slicing
            # happens here (the expansion's metadata pass replaces it)
            with open(manifest_file, encoding="utf-8") as f:
                has_containers = any(
                    is_container_entry(line.strip()) for line in f
                )
            if has_containers:
                with open(manifest_file, encoding="utf-8") as f:
                    paths = [
                        line.strip() for line in f if line.strip()
                    ]
                kwargs["process_index"] = process_index
                kwargs["process_count"] = process_count
                return cls(paths, **kwargs)
            # the SHARED counter (also the stripe runner's span
            # denominator for loose manifests): supervisor and worker
            # must agree on what an entry is, or the merge's row-count
            # check fails
            n = count_manifest_entries(manifest_file)
            lo, hi = manifest_stripe(n, process_index, process_count)
            paths = []
            k = 0
            with open(manifest_file, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if k >= hi:
                        break
                    if k >= lo:
                        paths.append(line)
                    k += 1
            kwargs["process_index"] = process_index
            kwargs["process_count"] = process_count
            kwargs["already_striped"] = True
            return cls(paths, **kwargs)
        with open(manifest_file, encoding="utf-8") as f:
            paths = [line.strip() for line in f if line.strip()]
        return cls(paths, **kwargs)

    def _read(self, path: str):
        """bytes, None (unreadable), or a SkippedBlob marker (the
        64 KiB cap) — read_capped's contract."""
        return _read_capped(path)

    def _read_hook(self, start: int):
        """The produce-stage read hook for the chunk at ``start``:
        loose manifests read by path; expanded manifests read BY
        GLOBAL INDEX through the container sources (display names are
        not unique across containers)."""
        if self.ingest is None:
            return None  # produce_batch's loose-file default
        read_at = self.ingest.read_at
        return lambda _path, i: read_at(start + i)

    @staticmethod
    def _resume_point(output: str) -> int:
        """Count completed records, discarding a torn tail.

        A crash mid-write can leave a final line without its newline (or
        truncated); only newline-terminated lines count as done, and the
        file is truncated back to the last complete record so the resumed
        run rewrites the torn row instead of leaving it corrupt."""
        done = 0
        good_end = 0
        with open(output, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break
                done += 1
                good_end += len(line)
        if good_end < os.path.getsize(output):
            with open(output, "r+b") as f:
                f.truncate(good_end)
        return done

    # -- the pipeline stages --

    def _produce(self, start: int):
        """Worker-thread stage: route + read + dedupe + prefilter +
        featurize (the shared ``_produce_batch`` core, with the live
        cross-batch dedupe cache)."""
        chunk = self.paths[start : start + self.batch_size]
        return (chunk, *_produce_batch(
            self.classifier,
            chunk,
            self.mode,
            self.dedupe,
            self.attribution,
            cache=self._dedupe_cache if self.dedupe else None,
            read=self._read_hook(start),
            filenames=(
                self.ingest.filenames[start : start + self.batch_size]
                if self.ingest is not None
                else None
            ),
        ))

    def _run_config(self) -> dict:
        """Everything that changes the CONTENT of an output row.

        Written beside the output as ``<output>.meta.json`` so a resumed
        run can prove it is appending rows of the same shape — resuming a
        ``--mode license`` file with ``--mode package`` (or a different
        corpus, threshold, closest-K, or attribution setting) would
        silently mix incompatible rows in one file otherwise."""
        import hashlib

        corpus = self.classifier.corpus
        corpus_id = None
        if corpus is not None:  # package mode is host-only, corpus-free
            corpus_id = {
                "templates": corpus.n_templates,
                "vocab": len(corpus.vocab),
                "keys_sha1": hashlib.sha1(
                    "\n".join(corpus.keys).encode(), usedforsecurity=False
                ).hexdigest(),
                # per-template normalized-CONTENT hashes folded in
                # (ADVICE r5): an edited vendored template with unchanged
                # keys and vocab size must refuse to resume — the rows it
                # would append score against different template text
                "content_sha1": hashlib.sha1(
                    "\n".join(
                        sorted(
                            f"{key}:{h}"
                            for h, key in corpus.content_hashes.items()
                        )
                    ).encode(),
                    usedforsecurity=False,
                ).hexdigest(),
            }
        return {
            "mode": self.mode,
            "corpus": corpus_id,
            "threshold": self.threshold,
            "closest": self.classifier.closest,
            "attribution": self.attribution,
            # the container-expansion fingerprint (None for loose-only
            # manifests): a resumed run must expand to the SAME rows —
            # an archive rewritten between runs changes the sha and
            # refuses instead of appending rows of a different
            # container after a completed prefix of the old one
            "ingest": (
                self.ingest.fingerprint() if self.ingest is not None else None
            ),
            # descriptive only (never compared): names the corpus in
            # refusal messages — "the output was written with X"
            "corpus_source": self.corpus_source,
        }

    def _check_resume_config(self, output: str, resume: bool) -> dict:
        """Refuse a resume whose config would produce different rows.

        Returns the config dict; the caller writes it to the sidecar
        AFTER the output file is opened (so a crash can never leave a
        fresh sidecar describing stale rows — at worst an empty/truncated
        output sits beside the previous sidecar, and the stale sidecar
        then refuses in the safe direction)."""
        meta_path = f"{output}.meta.json"
        config = self._run_config()
        if resume and os.path.exists(output) and os.path.exists(meta_path):
            with open(meta_path, encoding="utf-8") as f:
                try:
                    prior = json.load(f)
                except json.JSONDecodeError:
                    prior = None  # torn sidecar: rewritten by this run
            if prior is not None:
                # compare key-by-key over THIS version's fields: a
                # sidecar from a newer version with extra keys must not
                # refuse a resume whose tracked settings all match.
                # corpus_source is descriptive (it names a path/alias,
                # not content) — the corpus_id fingerprints decide.
                diffs = [
                    k
                    for k in config
                    if k != "corpus_source" and prior.get(k) != config[k]
                ]
                if diffs:
                    detail = ""
                    if "corpus" in diffs:
                        # name BOTH corpora: the fingerprints that
                        # disagree and where each came from — an opaque
                        # "corpus changed" costs the operator a
                        # spelunking session at 3am
                        prior_c = prior.get("corpus") or {}
                        cur_c = config.get("corpus") or {}
                        prior_src = prior.get("corpus_source")
                        detail = (
                            "; corpus fingerprint mismatch: the output "
                            f"was written with corpus "
                            f"{prior_src or 'unknown source'} "
                            f"(content_sha1 "
                            f"{prior_c.get('content_sha1')}, "
                            f"{prior_c.get('templates')} templates), "
                            f"this run uses "
                            f"{self.corpus_source or 'unknown source'} "
                            f"(content_sha1 {cur_c.get('content_sha1')}, "
                            f"{cur_c.get('templates')} templates)"
                        )
                    raise ResumeConfigError(
                        f"cannot resume {output!r}: this run's "
                        "configuration differs from the one that wrote "
                        f"it ({', '.join(diffs)} changed — {meta_path})"
                        f"{detail}; rerun with matching settings, a "
                        "fresh --output, or --no-resume"
                    )
        return config

    def run(self, output: str, resume: bool = True) -> BatchStats:
        if self.process_count > 1:
            from licensee_tpu.parallel.distributed import shard_output_path

            output = shard_output_path(
                output, self.process_index, self.process_count
            )
        run_config = self._check_resume_config(output, resume)
        done = 0
        if resume and os.path.exists(output):
            done = self._resume_point(output)
        if done and self.ingest is not None:
            # the completed prefix is never re-read: sequential-window
            # containers skip it instead of caching it (and the procs
            # descriptor below carries the same narrowing)
            self.ingest.mark_done_prefix(done)
        mode = "a" if done else "w"

        starts = deque(range(done, len(self.paths), self.batch_size))
        t_run = time.perf_counter()
        t_progress = t_run
        # lane-occupancy clocks (obs/pipeline.py): featurize (produce
        # workers), device (submit -> future resolution), writer (the
        # writer thread's loop body), plus the in-flight-chunks gauge —
        # registered on the process registry so --prom-file carries the
        # overlap proof of this run
        from licensee_tpu.obs import PipelineLanes, get_registry

        lanes = PipelineLanes().register(get_registry())
        use_procs = self.featurize_procs > 0
        if use_procs:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            # spawn, not fork: the parent holds a live TPU backend and
            # forked XLA runtime threads are undefined behavior; spawned
            # workers build a device=False classifier and never
            # initialize a backend at all
            pool = ProcessPoolExecutor(
                max_workers=self.featurize_procs,
                mp_context=multiprocessing.get_context("spawn"),
                initializer=_mp_init,
                initargs=(
                    self.classifier.corpus, self.mode, self.batch_size,
                    # container manifests ride a picklable re-open
                    # descriptor — never the parent's live handles
                    self.ingest.descriptor()
                    if self.ingest is not None
                    else None,
                ),
            )
        else:
            pool = ThreadPoolExecutor(max_workers=self.workers)
        with pool, open(output, mode, encoding="utf-8") as out:
            # sidecar AFTER the output open/truncate: see
            # _check_resume_config for the crash-window rationale
            with open(f"{output}.meta.json", "w", encoding="utf-8") as f:
                json.dump(run_config, f)
                f.write("\n")
            futures: deque = deque()

            def produce_traced(start: int):
                # the featurize lane is busy while >= 1 produce worker
                # is inside (read + featurize, the parallel stage)
                with lanes.lane("featurize"):
                    return self._produce(start)

            def submit_next() -> None:
                if not starts:
                    return
                start = starts.popleft()
                if use_procs:
                    # worker processes: the lane clock cannot reach into
                    # the children, so featurize occupancy reads 0 under
                    # --featurize-procs (stats.stage_seconds still
                    # carries the thread-seconds)
                    futures.append(
                        pool.submit(
                            _mp_produce,
                            self.paths[start : start + self.batch_size],
                            self.mode,
                            self.dedupe,
                            self.attribution,
                            start,
                        )
                    )
                else:
                    futures.append(pool.submit(produce_traced, start))

            for _ in range(self.inflight):
                submit_next()

            # gather: produced batches whose (possibly sparse) device rows
            # are coalesced across batches into full pad_batch_to chunks —
            # a dedupe-heavy stream leaves each batch a handful of todo
            # rows, and dispatching those per-batch pays a full padded
            # chunk + device round trip each (78% of elapsed on the 1M
            # dup-heavy run).  pending: ASYNC-dispatched groups in
            # flight, bounded by pipeline_depth — the software pipeline:
            # the device scores group N while the workers featurize
            # N+1..N+depth and the writer thread drains N-1.  Writes
            # stay in manifest order: groups are awaited strictly FIFO
            # and keep their batches in arrival order, so the resume
            # invariant (rows n written => rows 0..n-1 written) is
            # untouched at every depth.
            pending: deque = deque()
            gather: list = []
            gather_todo = 0
            chunk_no = done // self.batch_size  # resume keeps ids stable

            def dispatch_gathered() -> None:
                nonlocal gather_todo
                if not gather:
                    return
                batches = list(gather)
                gather.clear()
                gather_todo = 0
                t0 = time.perf_counter()
                prepareds = [b[6] for b in batches]
                if any(p.todo for p in prepareds):
                    # non-blocking submit: the future resolves in the
                    # FIFO await below, never here
                    merged = self.classifier.merge_prepared(prepareds)
                    device_fut = self.classifier.dispatch_chunks_async(
                        merged
                    )
                    lanes.enter("device")
                    lanes.chunk_inflight(len(device_fut))
                else:
                    merged, device_fut = None, None
                dt = time.perf_counter() - t0
                self.stats.add_stage("dispatch", dt)
                if merged is not None:
                    for b in batches:
                        if b[9] is not None:
                            # the group's device dispatch, shared by
                            # every coalesced batch riding it
                            b[9].add_span(
                                "dispatch", dt, t0=t0,
                                note=f"group={len(batches)}",
                            )
                pending.append((batches, merged, device_fut))

            # -- the writer thread --
            #
            # The finish/write loop (dup resolution, attribution, stats,
            # dedupe-cache fills, row rendering, the JSONL write) used
            # to run on the main thread, where it was part of the
            # pipeline's SERIAL section — Amdahl's ceiling for one
            # process (the scaling-model ADR above) included every one
            # of those microseconds.  It now runs on a dedicated writer
            # thread behind a BOUNDED handoff queue: the main thread
            # only coalesces/dispatches/finishes device chunks and hands
            # each batch over tagged with a sequence number; the writer
            # asserts the numbers arrive contiguous, so rows land in
            # manifest order and the resume invariant (line count ==
            # completed prefix of the stripe) is untouched.  The queue
            # bound keeps memory flat when scoring outruns the disk.
            #
            # Sharing notes: the writer is the ONLY mutator of the
            # result counters and the only INSERTER into the dedupe
            # cache; the main thread's cache re-probe and the produce
            # workers' reads are GIL-atomic dict ops, and a fill that is
            # still in the queue merely costs a duplicate device score
            # with a bit-identical result.
            write_q: queue.Queue = queue.Queue(maxsize=8)
            writer_err: list[BaseException] = []
            next_seq = 0

            def write_loop() -> None:
                nonlocal t_progress
                expect_seq = 0
                stats = self.stats

                if self.ingest is not None:
                    from licensee_tpu.ingest.sources import split_entry
                else:
                    split_entry = None

                def route_name(p: str) -> str:
                    # the attribution filename gate must see the
                    # MEMBER's basename for an explicit
                    # `container::member` entry (display string stays
                    # as written); whole-container rows already
                    # display the member itself
                    if split_entry is not None:
                        parsed = split_entry(p)
                        if parsed is not None:
                            return os.path.basename(parsed[1])
                    return os.path.basename(p)

                cache = self._dedupe_cache
                dedupe = self.dedupe
                dedupe_cap = self.dedupe_cap
                attribution = self.attribution
                attribution_for = self.classifier.attribution_for
                count = self._count
                while True:
                    item = write_q.get()
                    if item is None:
                        return
                    if writer_err:
                        continue  # drain: the producer must never block
                    lanes.enter("writer")
                    try:
                        seq, batch = item
                        if seq != expect_seq:
                            raise RuntimeError(
                                f"writer sequence gap: got {seq}, "
                                f"expected {expect_seq} — manifest order "
                                "(the resume invariant) would break"
                            )
                        expect_seq += 1
                        (chunk, read_errs, keys, preset, dup_of, routes,
                         prepared, contents, pre_rows, trace) = batch
                        results = prepared.results
                        for i, j in dup_of.items():
                            results[i] = results[j]
                        t1 = time.perf_counter()
                        read_errors = featurize_errors = dedupe_hits = 0
                        skipped_oversized = 0
                        lines: list[str] = []
                        append = lines.append
                        for k, (path, is_err, result) in enumerate(
                            zip(chunk, read_errs, results)
                        ):
                            error = None
                            if is_err:
                                # is_err carries the read disposition
                                # code: "read_error" ("could not read"
                                # vs "no license") or "oversized" (the
                                # 64 KiB cap: skipped, never
                                # truncated-and-scored)
                                error = is_err
                                if is_err == "oversized":
                                    skipped_oversized += 1
                                else:
                                    read_errors += 1
                            elif result.error:
                                # poisoned blob: contained per-row, run
                                # continues
                                error = result.error
                                featurize_errors += 1
                            else:
                                if (
                                    attribution
                                    and preset[k] is None
                                    and result.key is not None
                                ):
                                    result.attribution = attribution_for(
                                        contents[k],
                                        route_name(path),
                                        result,
                                        route=(
                                            routes[k]
                                            if routes is not None
                                            else None
                                        ),
                                    )
                                count(result)
                                if (
                                    routes is not None
                                    and routes[k] is None
                                ):
                                    pass  # unrecognized name: no cache
                                elif preset[k] is not None:
                                    dedupe_hits += 1
                                elif dedupe and keys[k] is not None:
                                    if len(cache) >= dedupe_cap:
                                        # FIFO bound
                                        cache.pop(next(iter(cache)))
                                    # snapshot, not alias: the cached
                                    # result will be handed out as a
                                    # preset row many times — a copy
                                    # with a tuple closest list means no
                                    # later batch-finishing (or future
                                    # per-row annotation) can reach back
                                    # and corrupt it
                                    cache[keys[k]] = replace(
                                        result,
                                        closest=(
                                            tuple(result.closest)
                                            if result.closest is not None
                                            else None
                                        ),
                                    )
                            if routes is not None:
                                stats.add_route(routes[k])
                            # preset rows were rendered on the produce
                            # worker (_produce_batch pre_rows);
                            # everything else renders here, after
                            # finish/attribution
                            if (
                                pre_rows is not None
                                and pre_rows[k] is not None
                                and error is None  # insurance; see above
                            ):
                                append(pre_rows[k])
                            else:
                                append(_jsonl_row(path, result, error))
                        append("")
                        out.write("\n".join(lines))
                        out.flush()
                        # batched bookkeeping: one counter update per
                        # batch instead of one per row
                        stats.total += len(chunk)
                        stats.read_errors += read_errors
                        stats.featurize_errors += featurize_errors
                        stats.dedupe_hits += dedupe_hits
                        stats.skipped_oversized += skipped_oversized
                        t2 = time.perf_counter()
                        stats.add_stage("write", t2 - t1)
                        if trace is not None:
                            trace.add_span("write", t2 - t1, t0=t1)
                            self._tracer.finish(trace)
                        if (
                            self.progress_every
                            and t2 - t_progress >= self.progress_every
                        ):
                            t_progress = t2
                            print(
                                json.dumps(
                                    {
                                        "progress": stats.total,
                                        "of": len(self.paths) - done,
                                        "files_per_sec": round(
                                            stats.total / (t2 - t_run), 1
                                        ),
                                        "dedupe_hits": stats.dedupe_hits,
                                    }
                                ),
                                file=sys.stderr,
                                flush=True,
                            )
                    except BaseException as exc:  # noqa: BLE001
                        writer_err.append(exc)
                    finally:
                        lanes.exit_("writer")

            writer = threading.Thread(
                target=write_loop, name="batch-writer", daemon=True
            )
            writer.start()

            try:
                while futures or pending or gather:
                    if writer_err:
                        break  # the writer's failure is raised below
                    # pull produced batches into the coalescing buffer;
                    # keep up to pipeline_depth dispatched groups in
                    # flight before draining the oldest
                    while futures and len(pending) < self.pipeline_depth:
                        (chunk, read_errs, keys, preset, dup_of, routes,
                         prepared, contents, pre_rows,
                         (t_read, t_feat)) = futures.popleft().result()
                        submit_next()
                        self.stats.add_stage("read", t_read)
                        self.stats.add_stage("featurize", t_feat)
                        trace = None
                        if self._tracer is not None:
                            chunk_no += 1
                            trace = self._tracer.start(
                                request_id=f"chunk-{chunk_no}"
                            )
                            # the produce stages ran on a worker BEFORE
                            # the trace existed: rebase t_start so their
                            # spans sit at t>=0 and the trace duration
                            # covers the chunk's whole pipeline residency
                            trace.t_start -= t_read + t_feat
                            trace.add_span(
                                "read", t_read, t0=trace.t_start
                            )
                            trace.add_span(
                                "featurize", t_feat,
                                t0=trace.t_start + t_read,
                            )
                        if self.dedupe:
                            # re-probe the cross-batch cache on the main
                            # thread: rows produced during the pipeline/
                            # coalescing lag (and, in process mode,
                            # every row — the worker can't see the
                            # parent's cache) pick up results finished
                            # since their produce
                            cache = self._dedupe_cache
                            hit = False
                            for i, k in enumerate(keys):
                                if k is not None and preset[i] is None:
                                    cached = cache.get(k)
                                    if cached is not None:
                                        preset[i] = cached
                                        prepared.results[i] = cached
                                        hit = True
                            if hit:
                                prepared.todo = [
                                    i
                                    for i, r in enumerate(prepared.results)
                                    if r is None
                                ]
                        if len(prepared.todo) < len(prepared.results):
                            # free the dense feature arrays while the
                            # batch waits in the buffer; merge becomes a
                            # concat
                            prepared.compact_features()
                        gather.append(
                            (chunk, read_errs, keys, preset, dup_of,
                             routes, prepared, contents, pre_rows, trace)
                        )
                        gather_todo += len(prepared.todo)
                        if (
                            gather_todo >= self.classifier.pad_batch_to
                            or len(gather) >= self.coalesce_batches
                            or gather_todo == 0
                        ):
                            # a group with no device rows finishes
                            # instantly — holding it back would only
                            # delay its writes (and the dedupe-cache
                            # fills they produce)
                            dispatch_gathered()

                    if not pending:
                        # stream tail (or an under-filled group with
                        # nothing else in flight): dispatch what we have
                        dispatch_gathered()
                    # await the OLDEST group (FIFO keeps manifest
                    # order): by now the device has had the whole
                    # featurize/coalesce interval to finish it, so the
                    # await is usually a no-op resolve
                    batches, merged, device_fut = pending.popleft()
                    t0 = time.perf_counter()
                    if merged is not None:
                        outs = device_fut.result()
                        lanes.exit_("device")
                        lanes.chunk_inflight(-len(device_fut))
                        self.classifier.finish_chunks(
                            merged, outs, self.threshold
                        )
                        self.classifier.scatter_merged(
                            [b[6] for b in batches], merged
                        )
                    dt_score = time.perf_counter() - t0
                    self.stats.add_stage("score", dt_score)
                    if merged is not None:
                        for b in batches:
                            if b[9] is not None:
                                b[9].add_span("score", dt_score, t0=t0)
                    # hand the finished batches to the writer, in
                    # manifest order, tagged for the sequence check
                    for b in batches:
                        write_q.put((next_seq, b))
                        next_seq += 1
            finally:
                write_q.put(None)
                writer.join()
            if writer_err:
                raise writer_err[0]
        self.stats.pipeline = lanes.occupancy()
        if (
            self.ingest is not None
            and self.process_count == 1
            and (self.ingest.spans or self.ingest.subsets)
        ):
            # container-level verdicts (the reference's Project#license
            # algebra over this run's finished rows) — derived purely
            # from the completed per-blob output and replaced
            # atomically, so any interrupted run regenerates identical
            # rows on its resumed completion: resume safety at
            # container granularity rides on the blob-level invariant.
            # Striped ranks (process_count > 1) skip this: a container
            # may span shards, so the stripe runner derives the ONE
            # sidecar from the merged output instead — exactly one row
            # per container, never one per stripe fragment.
            from licensee_tpu.ingest.verdict import write_container_verdicts

            t0 = time.perf_counter()
            write_container_verdicts(
                output, self.ingest.spans, self.ingest.subsets
            )
            self.stats.add_stage("containers", time.perf_counter() - t0)
        self.stats.add_stage("elapsed", time.perf_counter() - t_run)
        return self.stats

    def close(self) -> None:
        """Release container handles (open archive fds, git ODB
        handles) held by an expanded manifest; a loose-manifest
        project holds nothing."""
        if self.ingest is not None:
            self.ingest.close()

    def classify_paths(self, paths: list[str]):
        """Route, read, classify and (optionally) attribute paths in one
        unpipelined pass — the small-manifest twin of run(), used by the
        CLI's no---output mode.  Returns (contents, results); a row's
        content is None when the read failed, a SkippedBlob when the
        reader refused it (the 64 KiB cap; the caller decides how to
        surface both), b"" when auto routing skipped the read."""
        from licensee_tpu.kernels.batch import BatchClassifier

        if self.ingest is not None and paths is self.paths:
            filenames = list(self.ingest.filenames)
        else:
            filenames = [os.path.basename(p) for p in paths]
        routes = None
        if self.mode == "auto":
            routes = [BatchClassifier.route_for(f) for f in filenames]
            for r in routes:
                self.stats.add_route(r)
        if self.ingest is not None and paths is self.paths:
            # container reads are positional (display names may repeat
            # across containers); only the project's own expanded path
            # list carries that alignment
            contents = [
                self.ingest.read_at(i)
                if routes is None or routes[i] is not None
                else b""
                for i in range(len(paths))
            ]
        else:
            contents = [
                self._read(p)
                if routes is None or routes[i] is not None
                else b""
                for i, p in enumerate(paths)
            ]
        results = self.classifier.classify_blobs(
            [c if isinstance(c, (bytes, str)) else b"" for c in contents],
            threshold=self.threshold,
            filenames=filenames,
            routes=routes,
        )
        if self.attribution:
            for i, r in enumerate(results):
                if (
                    isinstance(contents[i], (bytes, str))
                    and not r.error
                    and r.key is not None
                ):
                    r.attribution = self.classifier.attribution_for(
                        contents[i],
                        filenames[i],
                        r,
                        route=routes[i] if routes is not None else None,
                    )
        return contents, results

    def classify_contents(
        self,
        contents: list[bytes | str],
        filenames: list[str | None] | None = None,
    ) -> list:
        results = self.classifier.classify_blobs(
            contents, threshold=self.threshold, filenames=filenames
        )
        for result in results:
            if result.error:
                self.stats.featurize_errors += 1
            else:
                self._count(result)
        self.stats.total += len(contents)
        return results

    def _count(self, result) -> None:
        if result.matcher == "copyright":
            self.stats.prefiltered_copyright += 1
        elif result.matcher == "exact":
            self.stats.prefiltered_exact += 1
        elif result.matcher == "dice":
            self.stats.dice_matched += 1
        elif result.matcher == "reference":
            self.stats.reference_matched += 1
        elif result.matcher is not None:
            # package mode: gemspec/npmbower/cabal/cargo/cran/distzilla/
            # nuget/spdx filename-dispatched matchers
            self.stats.package_matched += 1
        else:
            self.stats.unmatched += 1
