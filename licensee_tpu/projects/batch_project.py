"""BatchProject: classify a manifest of millions of blobs.

The scale-out ingestion path of SURVEY.md §7 step 5: manifest -> featurize
workers -> fixed-width packed batches -> (double-buffered) device feed ->
JSONL results, with a resumable shard manifest (the checkpoint/resume
subsystem; the reference's closest analog is its pervasive memoization +
golden caches, SURVEY.md §5).

Host pre-filters (Copyright regex, Exact wordset hash) short-circuit blobs
before they are packed for HBM, mirroring the first-match-wins chain
(project_files/project_file.rb:69-71).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

import numpy as np

import licensee_tpu


@dataclass
class BatchStats:
    total: int = 0
    prefiltered_copyright: int = 0
    prefiltered_exact: int = 0
    dice_matched: int = 0
    unmatched: int = 0
    read_errors: int = 0

    def as_dict(self) -> dict:
        return dict(self.__dict__)


class BatchProject:
    """Classify every path in a manifest against the compiled corpus.

    Results stream to ``<output>`` as JSON lines; a run interrupted at any
    point resumes from the last completed batch (line count == completed
    prefix of the manifest)."""

    def __init__(
        self,
        manifest_paths: list[str],
        corpus=None,
        method: str = "popcount",
        batch_size: int = 4096,
        threshold: float | None = None,
    ):
        from licensee_tpu.kernels.batch import BatchClassifier

        self.paths = list(manifest_paths)
        self.classifier = BatchClassifier(
            corpus=corpus, method=method, pad_batch_to=batch_size
        )
        self.batch_size = batch_size
        self.threshold = (
            licensee_tpu.confidence_threshold() if threshold is None else threshold
        )
        self.stats = BatchStats()

    @classmethod
    def from_manifest_file(cls, manifest_file: str, **kwargs) -> "BatchProject":
        with open(manifest_file, encoding="utf-8") as f:
            paths = [line.strip() for line in f if line.strip()]
        return cls(paths, **kwargs)

    def _read(self, path: str) -> bytes | None:
        try:
            with open(path, "rb") as f:
                return f.read(64 * 1024)  # MAX_LICENSE_SIZE cap (git_project.rb:53)
        except OSError:
            self.stats.read_errors += 1
            return None

    @staticmethod
    def _resume_point(output: str) -> int:
        """Count completed records, discarding a torn tail.

        A crash mid-write can leave a final line without its newline (or
        truncated); only newline-terminated lines count as done, and the
        file is truncated back to the last complete record so the resumed
        run rewrites the torn row instead of leaving it corrupt."""
        done = 0
        good_end = 0
        with open(output, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break
                done += 1
                good_end += len(line)
        if good_end < os.path.getsize(output):
            with open(output, "r+b") as f:
                f.truncate(good_end)
        return done

    def run(self, output: str, resume: bool = True) -> BatchStats:
        done = 0
        if resume and os.path.exists(output):
            done = self._resume_point(output)
        mode = "a" if done else "w"

        with open(output, mode, encoding="utf-8") as out:
            for start in range(done, len(self.paths), self.batch_size):
                chunk = self.paths[start : start + self.batch_size]
                contents = [self._read(p) for p in chunk]
                results = self.classifier.classify_blobs(
                    [c if c is not None else b"" for c in contents],
                    threshold=self.threshold,
                )
                for path, content, result in zip(chunk, contents, results):
                    row = {"path": path, **result.as_dict()}
                    if content is None:
                        # distinguish "could not read" from "no license"
                        row["error"] = "read_error"
                    else:
                        self._count(result)
                    self.stats.total += 1
                    out.write(json.dumps(row) + "\n")
                out.flush()
        return self.stats

    def classify_contents(self, contents: list[bytes | str]) -> list:
        results = self.classifier.classify_blobs(contents, threshold=self.threshold)
        for result in results:
            self._count(result)
        self.stats.total += len(contents)
        return results

    def _count(self, result) -> None:
        if result.matcher == "copyright":
            self.stats.prefiltered_copyright += 1
        elif result.matcher == "exact":
            self.stats.prefiltered_exact += 1
        elif result.matcher == "dice":
            self.stats.dice_matched += 1
        else:
            self.stats.unmatched += 1
