"""Abstract project: orchestrates candidate-file collection and detection.

Parity target: `lib/licensee/projects/project.rb` — single-license
resolution with the LGPL dual-file exception, filename scoring/sorting,
LGPL prioritization, and the license/readme/package file set.
"""

from __future__ import annotations

_UNSET = object()


class Project:
    def __init__(self, detect_packages: bool = False, detect_readme: bool = False, **_ignored):
        self.detect_packages = detect_packages
        self.detect_readme = detect_readme

    # -- detection results (project.rb:24-52) --

    @property
    def license(self):
        """The single detected license; `other` when multiple conflicting
        licenses match (with the LGPL dual-file exception)."""
        cached = self.__dict__.get("_license", _UNSET)
        if cached is _UNSET:
            from licensee_tpu.corpus.license import License

            without = self.licenses_without_copyright
            if len(without) == 1 or self.is_lgpl:
                cached = without[0]
            elif len(without) > 1:
                cached = License.find("other")
            else:
                cached = None
            self.__dict__["_license"] = cached
        return cached

    @property
    def licenses(self) -> list:
        cached = self.__dict__.get("_licenses")
        if cached is None:
            cached = _uniq(f.license for f in self.matched_files)
            self.__dict__["_licenses"] = cached
        return cached

    @property
    def matched_file(self):
        if len(self.matched_files) == 1 or self.is_lgpl:
            return self.matched_files[0] if self.matched_files else None
        return None

    @property
    def matched_files(self) -> list:
        cached = self.__dict__.get("_matched_files")
        if cached is None:
            cached = [f for f in self.project_files if f.license]
            self.__dict__["_matched_files"] = cached
        return cached

    @property
    def license_file(self):
        if len(self.license_files) == 1 or self.is_lgpl:
            return self.license_files[0] if self.license_files else None
        return None

    @property
    def license_files(self) -> list:
        cached = self.__dict__.get("_license_files")
        if cached is None:
            from licensee_tpu.project_files.license_file import LicenseFile

            files = self.files()
            if not files:
                cached = []
            else:
                found = self._find_files(LicenseFile.name_score)
                loaded = []
                for f in found:
                    content = self.load_file(f)
                    if content is None:
                        # a backend refusing a blob (the 64 KiB
                        # MAX_LICENSE_SIZE cap, git_project.py): the
                        # file is skipped outright, never scored on a
                        # truncated head
                        continue
                    loaded.append(LicenseFile(content, f))
                cached = self._prioritize_lgpl(loaded)
            self.__dict__["_license_files"] = cached
        return cached

    @property
    def readme_file(self):
        if not self.detect_readme:
            return None
        cached = self.__dict__.get("_readme", _UNSET)
        if cached is _UNSET:
            from licensee_tpu.project_files.project_file import sanitize_content
            from licensee_tpu.project_files.readme_file import ReadmeFile

            cached = None
            result = self._find_file(ReadmeFile.name_score)
            if result is not None:
                content, file = result
                if content is not None:
                    content = sanitize_content(content)
                content = ReadmeFile.license_content(content)
                if content and file:
                    cached = ReadmeFile(content, file)
            self.__dict__["_readme"] = cached
        return cached

    readme = readme_file

    @property
    def package_file(self):
        if not self.detect_packages:
            return None
        cached = self.__dict__.get("_package_file", _UNSET)
        if cached is _UNSET:
            from licensee_tpu.project_files.package_manager_file import (
                PackageManagerFile,
            )

            cached = None
            result = self._find_file(PackageManagerFile.name_score)
            if result is not None:
                content, file = result
                if content is not None and file:
                    cached = PackageManagerFile(content, file)
            self.__dict__["_package_file"] = cached
        return cached

    # -- internals --

    @property
    def is_lgpl(self) -> bool:
        """LGPL lives in COPYING.lesser alongside a GPL COPYING
        (project.rb:102-106)."""
        if not (len(self.licenses) == 2 and len(self.license_files) == 2):
            return False
        return self.license_files[0].is_lgpl and self.license_files[1].is_gpl

    def _find_files(self, score_fn) -> list[dict]:
        files = self.files()
        if not files:
            return []
        found = []
        for file in files:
            score = score_fn(file["name"])
            if score > 0:
                found.append({**file, "score": score})
        # project.rb:111-117: sort by score descending (stable on input order)
        found.sort(key=lambda f: -f["score"])
        return found

    def _find_file(self, score_fn):
        found = self._find_files(score_fn)
        if not found:
            return None
        file = found[0]
        return (self.load_file(file), file)

    def _prioritize_lgpl(self, files: list) -> list:
        # project.rb:137-145
        if not files:
            return files
        first_license = files[0].license
        if not (first_license and first_license.gpl_q):
            return files
        lesser = next((i for i, f in enumerate(files) if f.is_lgpl), None)
        if lesser is not None:
            files.insert(0, files.pop(lesser))
        return files

    @property
    def project_files(self) -> list:
        cached = self.__dict__.get("_project_files")
        if cached is None:
            cached = list(self.license_files)
            if self.readme_file:
                cached.append(self.readme_file)
            if self.package_file:
                cached.append(self.package_file)
            self.__dict__["_project_files"] = cached
        return cached

    @property
    def licenses_without_copyright(self) -> list:
        """Matched licenses excluding COPYRIGHT-only files
        (project.rb:153-155)."""
        cached = self.__dict__.get("_licenses_without_copyright")
        if cached is None:
            cached = _uniq(
                f.license for f in self.matched_files if not f.is_copyright
            )
            self.__dict__["_licenses_without_copyright"] = cached
        return cached

    def files(self) -> list[dict]:
        raise NotImplementedError

    def load_file(self, file: dict):
        raise NotImplementedError

    def to_h(self) -> dict:
        # project.rb:16 HASH_METHODS
        return {
            "licenses": [lic.to_h() for lic in self.licenses],
            "matched_files": [f.to_h() for f in self.matched_files],
        }


def _uniq(iterable) -> list:
    out = []
    for item in iterable:
        if item not in out:
            out.append(item)
    return out
