"""Native featurizer parity selftest + microbench (the cibuild smoke).

Asserts, over the full vendored corpus plus adversarial blobs, that the
fused single-pass native featurizer is BIT-IDENTICAL to the pure-Python
pipeline on every surface a score can depend on: normalized text,
content hash, packed wordset bits, |wordset|, normalized length, and the
prefilter outcomes.  Then reports the featurize crossing in us/blob.

Run as ``python -m licensee_tpu.native.selftest`` (script/cibuild does):
exit 0 on parity (or when the native library is unavailable — there is
nothing to diverge from then), exit 1 on any mismatch.
"""

from __future__ import annotations

import hashlib
import sys
import time

import numpy as np


def adversarial_blobs() -> list[bytes]:
    """Edge-case blobs: the shapes that historically diverge pipelines."""
    mit = (
        b"MIT License\n\nCopyright (c) 2026 Example\n\nPermission is "
        b"hereby granted, free of charge, to any person obtaining a copy "
        b"of this software and associated documentation files (the "
        b'"Software"), to deal in the Software without restriction.\n'
    )
    return [
        b"",
        b" \t\r\n ",
        mit,
        mit.replace(b"\n", b"\r\n"),  # CRLF universal-newline preamble
        mit.replace(b"\n", b"\r"),  # bare-CR
        b"\xef\xbb\xbf" + mit,  # BOM (non-ASCII: two-crossing path)
        # unicode dashes/quotes (non-ASCII fallback + folds)
        "em—dash – en, ‘curly’ “quotes”".encode(),
        "MITライセンス".encode(),  # MITライセンス
        b"<html><body><p>Licensed under the MIT license.</p></body></html>",
        b"Copyright (c) 2001 Someone\nAll rights reserved.",
        b"- bullet one\n\n- bullet two\n\n  3. numbered\n\n(a) lettered\n",
        b"a" * 70000,  # one huge line (beyond the 64 KiB read cap)
        (b"word " * 2000) + b"\n\n" + (b"term " * 2000),
        b"it's the boss' licence, sub-license per cent non-commercial",
        b"s's' 'quote' can't won't\n",
        b"=== \n*** bordered ***\n> quoted\n## heading\n[link](http://x)\n",
        b"http://example.com & http://other.example\n\nEND OF TERMS AND "
        b"CONDITIONS\n\ntrailing text",
        b"// comment line one\n// comment line two\n// comment line three",
        b"version 2.0\nhttps://spdx.org/licenses/MIT\nreal content here",
        b"\x00embedded\x00nuls\x00",
    ]


def corpus_blobs() -> list[bytes]:
    """Every vendored template's raw text — the full-corpus parity set."""
    from licensee_tpu.corpus.license import License

    return [
        lic.content.encode("utf-8")
        for lic in License.all(hidden=True, pseudo=False)
        if lic.content
    ]


def run_parity(classifier=None) -> dict:
    """Raises AssertionError on any native/Python divergence."""
    from licensee_tpu.kernels.batch import BatchClassifier, NormalizedBlob
    from licensee_tpu.project_files.project_file import sanitize_content
    from licensee_tpu.rubytext import ruby_strip

    clf = classifier or BatchClassifier(mesh=None, device=False)
    if clf._nat is None:
        return {"skipped": "native pipeline unavailable"}
    blobs = adversarial_blobs() + corpus_blobs()
    B = len(blobs)
    W = clf.corpus.n_lanes

    prepared = clf.prepare_batch(list(blobs))

    bits2 = np.zeros((B, W), dtype=np.uint32)
    n_words2 = np.zeros(B, dtype=np.int32)
    lengths2 = np.zeros(B, dtype=np.int32)
    cc2 = np.zeros(B, dtype=bool)
    results2: list = [None] * B
    for i, raw in enumerate(blobs):
        clf._prepare_one_python(
            raw, results2, bits2, n_words2, lengths2, cc2, i
        )

    mismatches = []
    for i in range(B):
        r1, r2 = prepared.results[i], results2[i]
        if (r1 is None) != (r2 is None) or (
            r1 is not None
            and (r1.key, r1.matcher, r1.confidence)
            != (r2.key, r2.matcher, r2.confidence)
        ):
            mismatches.append((i, "result", r1, r2))
            continue
        if r1 is None:
            if not np.array_equal(prepared.bits[i], bits2[i]):
                mismatches.append((i, "bits", None, None))
            if prepared.n_words[i] != n_words2[i]:
                mismatches.append(
                    (i, "n_words", prepared.n_words[i], n_words2[i])
                )
            if prepared.lengths[i] != lengths2[i]:
                mismatches.append(
                    (i, "length", prepared.lengths[i], lengths2[i])
                )
            if prepared.cc_fp[i] != cc2[i]:
                mismatches.append((i, "cc_fp", prepared.cc_fp[i], cc2[i]))

    # normalized text + content hash, via the two-crossing surface
    text_checked = 0
    for raw in blobs:
        content = sanitize_content(raw)
        stripped = ruby_strip(content)
        s1, _flags = clf._nat.stage1(stripped)
        s2 = clf._nat.stage2(s1.lower())
        blob = NormalizedBlob(raw)
        want = blob.content_normalized()
        if s2 != want:
            mismatches.append((raw[:40], "normalized_text", s2[:80], want[:80]))
        elif (
            hashlib.sha1(s2.encode("utf-8")).hexdigest() != blob.content_hash
        ):
            mismatches.append((raw[:40], "content_hash", None, None))
        text_checked += 1

    assert not mismatches, (
        f"native/python featurizer divergence ({len(mismatches)} rows): "
        f"{mismatches[:3]}"
    )
    return {"blobs": B, "text_checked": text_checked}


def bench_crossing(classifier=None, n: int = 256, reps: int = 3) -> float:
    """min us/blob for the whole-batch native crossing on ~10KB blobs."""
    from licensee_tpu.kernels.batch import BatchClassifier

    clf = classifier or BatchClassifier(mesh=None, device=False)
    if clf._nat is None:
        return float("nan")
    # ASCII-only seeds: a non-ASCII blob exits the crossing after the
    # all_ascii scan (status 2, near-free) and would understate us/blob
    seeds = [
        b
        for b in corpus_blobs()
        if len(b) > 512 and all(x < 0x80 for x in b)
    ][:16] or [b"some license words " * 64]
    blobs = [
        (seeds[i % len(seeds)] * (1 + 10000 // max(1, len(seeds[i % len(seeds)]))))[
            :10000
        ]
        for i in range(n)
    ]
    W = clf.corpus.n_lanes
    bits = np.zeros((n, W), dtype=np.uint32)
    meta = np.zeros((n, 3), dtype=np.int32)
    hashes = np.zeros((n, 16), dtype=np.uint8)
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        status = clf._nat.featurize_batch(
            clf._nat_vocab, blobs, bits, meta, hashes
        )
        dt = (time.perf_counter() - t0) / n * 1e6
        assert (status == 0).all(), "bench blobs must take the fast path"
        best = dt if best is None or dt < best else best
    return round(best, 1)


def _profile_main(n: int) -> int:
    """The ``--profile-json`` child: one featurize_batch over the bench
    blobs with the native pass profiler live, stage split as JSON on
    stdout.  Must run in its OWN process — PassProf caches the
    ``LICENSEE_TPU_PIPE_PROFILE`` env at its first call, so the parent
    cannot flip profiling on after the fact."""
    import json

    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(mesh=None, device=False)
    if clf._nat is None:
        print(json.dumps({"skipped": "native pipeline unavailable"}))
        return 0
    seeds = [
        b
        for b in corpus_blobs()
        if len(b) > 512 and all(x < 0x80 for x in b)
    ][:16] or [b"some license words " * 64]
    blobs = [
        (
            seeds[i % len(seeds)]
            * (1 + 10000 // max(1, len(seeds[i % len(seeds)])))
        )[:10000]
        for i in range(n)
    ]
    W = clf.corpus.n_lanes
    bits = np.zeros((n, W), dtype=np.uint32)
    meta = np.zeros((n, 3), dtype=np.int32)
    hashes = np.zeros((n, 16), dtype=np.uint8)
    clf._nat.profile_reset()
    clf._nat.featurize_batch(clf._nat_vocab, blobs, bits, meta, hashes)
    dump = clf._nat.profile_dump()
    us = {
        key: round(seconds / n * 1e6, 2)
        for key, seconds in dump.items()
        if not key.startswith("count.")
    }
    print(json.dumps({"n": n, "us_per_blob": us}))
    return 0


def profile_split(n: int = 256) -> dict | None:
    """Per-stage us/blob from a profile-enabled CHILD process (the env
    gate must be set at process start), or None when the child cannot
    produce one.  Keys of interest: ``stage.tokenize_only`` (the
    tokenize-vs-normalize split), ``s2.title_strips`` and
    ``s2.fold_spell`` (the round-2 fused passes)."""
    import json
    import os
    import subprocess

    env = {
        **os.environ,
        "LICENSEE_TPU_PIPE_PROFILE": "1",
        "JAX_PLATFORMS": "cpu",
    }
    try:
        proc = subprocess.run(
            [
                sys.executable, "-m", "licensee_tpu.native.selftest",
                "--profile-json", str(n),
            ],
            env=env, capture_output=True, text=True, timeout=300,
        )
        if proc.returncode != 0:
            return None
        row = json.loads(proc.stdout.strip().splitlines()[-1])
    except (OSError, subprocess.TimeoutExpired, ValueError, IndexError):
        return None
    if not isinstance(row, dict) or "us_per_blob" not in row:
        return None
    return row


def main() -> int:
    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(mesh=None, device=False)
    if clf._nat is None:
        print("native selftest: SKIP (native pipeline unavailable)")
        return 0
    try:
        stats = run_parity(clf)
    except AssertionError as exc:
        print(f"native selftest: FAIL — {exc}", file=sys.stderr)
        return 1
    us = bench_crossing(clf)
    print(
        f"native selftest: parity OK over {stats['blobs']} blobs; "
        f"featurize crossing {us} us/blob"
    )
    split = profile_split()
    if split is not None and split.get("us_per_blob"):
        stages = split["us_per_blob"]
        shown = ", ".join(
            f"{key.split('.', 1)[-1]} {stages[key]}"
            for key in (
                "stage.tokenize_only", "s2.title_strips", "s2.fold_spell"
            )
            if key in stages
        )
        if shown:
            print(f"native selftest: stage split (us/blob): {shown}")
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--profile-json":
        n_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 256
        sys.exit(_profile_main(n_arg))
    sys.exit(main())
