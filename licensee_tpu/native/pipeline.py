"""ctypes bindings for the whole-pipeline native path (native/pipeline.cpp).

Where textops.py accelerates individual passes (leaving ~18 regex passes
and ~17 ctypes crossings per blob in Python), this module runs the ENTIRE
stage-1/stage-2 normalization — PCRE2 for the complex patterns, the shared
hand-coded scanners for the rest — plus wordset extraction, vocabulary
projection, and the Exact-matcher wordset hash, in at most two crossings
per blob.  Ruby String#downcase is full-Unicode, so the downcase between
the stages stays in Python (str.lower).

All pattern strings are shipped to C++ from the single source of truth in
licensee_tpu/normalize/pipeline.py; the only translation is Python's
``\\Z`` (end of string) to PCRE2's ``\\z``.  ``load()`` returns a
``NativePipeline`` or ``None`` (no toolchain / no libpcre2 / disabled via
LICENSEE_TPU_NO_NATIVE), in which case callers keep the pure-Python or
hybrid path.  Differential tests: tests/test_native_pipeline.py; the
SHA1 golden corpus (tests/test_normalize_hashes.py) runs through this
path when built.
"""

from __future__ import annotations

import ctypes
import re
import threading

import numpy as np

from licensee_tpu.native.build import NativeUnavailable, build_and_load

_instance = None
_failed = False


class NativeResourceError(RuntimeError):
    """PCRE2 hit a resource limit (MATCHLIMIT/DEPTHLIMIT) on this blob.

    Python `re` has no such limits, so treating this as "no match" would
    silently diverge from the fallback path on adversarial inputs
    (nested-quantifier patterns vs pathological text).  Callers catch
    this and re-run the single blob through the pure-Python pipeline."""


def _flags_str(pattern: re.Pattern) -> str:
    flags = ""
    if pattern.flags & re.I:
        flags += "i"
    if pattern.flags & re.S:
        flags += "s"
    if pattern.flags & re.X:
        flags += "x"
    return flags


def _pcre_pattern(pattern: re.Pattern) -> bytes:
    # Python \Z (end of string) == PCRE2 \z; PCRE2's \Z allows a final
    # newline, which Python's does not.
    return pattern.pattern.replace("\\Z", "\\z").encode("utf-8")


def _build_config() -> bytes:
    from licensee_tpu.corpus.license import global_title_regex
    from licensee_tpu.normalize import pipeline as pl
    from licensee_tpu.project_files.license_file import CC_FALSE_POSITIVE_REGEX

    named: dict[str, re.Pattern] = {
        "hrs": pl.REGEXES["hrs"],
        "comment_markup": pl.REGEXES["comment_markup"],
        "markdown_headings": pl.REGEXES["markdown_headings"],
        "link_markup": pl.REGEXES["link_markup"],
        "title": global_title_regex(),
        "version": pl.REGEXES["version"],
        "lists": pl._LISTS,
        "span_markup": pl.REGEXES["span_markup"],
        "bullet": pl.REGEXES["bullet"],
        "bullet_join": pl._BULLET_JOIN,
        "bom": pl.REGEXES["bom"],
        "cc_dedication": pl.REGEXES["cc_dedication"],
        "cc_wiki": pl.REGEXES["cc_wiki"],
        "cc_legal_code": pl.REGEXES["cc_legal_code"],
        "cc0_info": pl.REGEXES["cc0_info"],
        "cc0_disclaimer": pl.REGEXES["cc0_disclaimer"],
        "unlicense_info": pl.REGEXES["unlicense_info"],
        "border_markup": pl.REGEXES["border_markup"],
        "url": pl.REGEXES["url"],
        "strip_copyright": pl._STRIP_COPYRIGHT,
        "block_markup": pl.REGEXES["block_markup"],
        "developed_by": pl.REGEXES["developed_by"],
        "end_of_terms": pl.END_OF_TERMS,
        "mit_optional": pl.REGEXES["mit_optional"],
        "copyright_full": pl.COPYRIGHT_FULL_REGEX,
        "cc_false_positive": CC_FALSE_POSITIVE_REGEX,
    }
    records = b"".join(
        name.encode() + b"\0" + _flags_str(p).encode() + b"\0"
        + _pcre_pattern(p) + b"\0"
        for name, p in named.items()
    )
    # spelling_table must be last: its payload contains '\0' separators
    table = b"".join(
        k.encode() + b"\0" + v.encode() + b"\0"
        for k, v in pl.VARIETAL_WORDS.items()
    )
    return records + b"spelling_table\0\0" + table


class VocabHandle:
    """Token -> id map resident in the native library (per corpus)."""

    def __init__(self, lib, words: list[str], n_lanes: int):
        self._lib = lib
        blob = "\0".join(words).encode("utf-8")
        self.n_lanes = n_lanes
        self._handle = lib.pipe_vocab_new(blob, len(blob), n_lanes)

    def close(self) -> None:
        if self._handle:
            self._lib.pipe_vocab_del(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


class NativePipeline:
    def __init__(self):
        lib = build_and_load("pipeline", (":libpcre2-8.so.0",))
        self._lib = lib
        lib.pipe_free.argtypes = [ctypes.c_void_p]
        lib.pipe_new.restype = ctypes.c_void_p
        lib.pipe_new.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.pipe_error.restype = ctypes.c_char_p
        lib.pipe_error.argtypes = [ctypes.c_void_p]
        lib.pipe_del.argtypes = [ctypes.c_void_p]
        out_len = ctypes.POINTER(ctypes.c_size_t)
        lib.pipe_stage1.restype = ctypes.c_void_p
        lib.pipe_stage1.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, out_len,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.pipe_stage2.restype = ctypes.c_void_p
        lib.pipe_stage2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, out_len,
        ]
        lib.pipe_vocab_new.restype = ctypes.c_void_p
        lib.pipe_vocab_new.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
        ]
        lib.pipe_vocab_del.argtypes = [ctypes.c_void_p]
        lib.pipe_featurize.restype = ctypes.c_int
        lib.pipe_featurize.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.pipe_exact_hash.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.pipe_refscan_new.restype = ctypes.c_void_p
        lib.pipe_refscan_new.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.pipe_refscan_del.argtypes = [ctypes.c_void_p]
        lib.pipe_refscan_min.restype = ctypes.c_int
        lib.pipe_refscan_min.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.pipe_refscan_set_singles.restype = ctypes.c_int
        lib.pipe_refscan_set_singles.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_int,
        ]
        lib.pipe_refscan_resolve.restype = ctypes.c_int
        lib.pipe_refscan_resolve.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.pipe_profile_dump.restype = ctypes.c_void_p
        lib.pipe_profile_dump.argtypes = [
            ctypes.POINTER(ctypes.c_size_t)
        ]
        # a cached .so built before pipe_profile_reset existed lacks the
        # symbol; degrade to reset-unavailable instead of failing init
        try:
            lib.pipe_profile_reset.restype = None
            lib.pipe_profile_reset.argtypes = []
            self._has_profile_reset = True
        except AttributeError:
            self._has_profile_reset = False
        lib.pipe_featurize_raw.restype = ctypes.c_int
        lib.pipe_featurize_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.pipe_featurize_batch.restype = None
        lib.pipe_featurize_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int8),
        ]

        config = _build_config()
        self._handle = lib.pipe_new(config, len(config))
        err = lib.pipe_error(self._handle)
        if err:
            msg = err.decode("utf-8", errors="replace")
            lib.pipe_del(self._handle)
            raise NativeUnavailable(f"pipeline init failed: {msg}")

    # -- per-blob API --

    def stage1(self, text: str) -> tuple[str, int]:
        """content_without_title_and_version (minus html/strip, which the
        caller does) + prefilter flags (bit0 copyright-only, bit1 cc-fp)."""
        data = text.encode("utf-8")
        n = ctypes.c_size_t()
        flags = ctypes.c_int32()
        ptr = self._lib.pipe_stage1(
            self._handle, data, len(data), ctypes.byref(n), ctypes.byref(flags)
        )
        if not ptr:
            raise NativeResourceError("pipe_stage1: PCRE2 resource limit")
        try:
            out = ctypes.string_at(ptr, n.value).decode("utf-8")
        finally:
            self._lib.pipe_free(ptr)
        return out, flags.value

    def stage2(self, lowered_stage1: str) -> str:
        data = lowered_stage1.encode("utf-8")
        n = ctypes.c_size_t()
        ptr = self._lib.pipe_stage2(self._handle, data, len(data), ctypes.byref(n))
        if not ptr:
            raise NativeResourceError("pipe_stage2: PCRE2 resource limit")
        try:
            return ctypes.string_at(ptr, n.value).decode("utf-8")
        finally:
            self._lib.pipe_free(ptr)

    def vocab(self, words: list[str], n_lanes: int) -> VocabHandle:
        return VocabHandle(self._lib, words, n_lanes)

    def featurize(
        self,
        vocab: VocabHandle,
        lowered_stage1: str,
        bits_out: np.ndarray | None = None,
    ) -> tuple[np.ndarray, int, int, bytes]:
        """(packed vocab bits, |wordset|, normalized char length,
        16-byte wordset hash) for one blob.  ``bits_out`` may be a
        caller-provided uint32[n_lanes] row (e.g. a slice of the batch
        matrix) to avoid a copy."""
        if bits_out is None:
            bits_out = np.zeros(vocab.n_lanes, dtype=np.uint32)
        assert bits_out.dtype == np.uint32 and bits_out.size == vocab.n_lanes
        data = lowered_stage1.encode("utf-8")
        scalars = (ctypes.c_int32 * 2)()
        hash16 = (ctypes.c_uint8 * 16)()
        rc = self._lib.pipe_featurize(
            self._handle,
            vocab._handle,
            data,
            len(data),
            bits_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            scalars,
            hash16,
        )
        if rc == 3:
            raise NativeResourceError("pipe_featurize: PCRE2 resource limit")
        if rc != 0:
            raise RuntimeError(f"pipe_featurize rc={rc}")
        return bits_out, int(scalars[0]), int(scalars[1]), bytes(hash16)

    def featurize_raw(
        self,
        vocab: VocabHandle,
        stripped_content: str,
        bits_out: np.ndarray | None = None,
    ) -> tuple[np.ndarray, int, int, int, bytes] | None:
        """One-crossing featurize of String#strip'd content: (bits,
        |wordset|, char length, prefilter flags, wordset hash).  Returns
        None when the content has non-ASCII bytes — the caller must use
        the two-crossing stage1 -> str.lower() -> featurize path so the
        downcase is full-Unicode."""
        if bits_out is None:
            bits_out = np.zeros(vocab.n_lanes, dtype=np.uint32)
        try:
            data = stripped_content.encode("ascii")
        except UnicodeEncodeError:
            return None
        scalars = (ctypes.c_int32 * 3)()
        hash16 = (ctypes.c_uint8 * 16)()
        rc = self._lib.pipe_featurize_raw(
            self._handle,
            vocab._handle,
            data,
            len(data),
            bits_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            scalars,
            hash16,
        )
        if rc == 2:
            return None
        if rc == 3:
            raise NativeResourceError("pipe_featurize_raw: PCRE2 resource limit")
        if rc != 0:
            raise RuntimeError(f"pipe_featurize_raw rc={rc}")
        return (
            bits_out,
            int(scalars[0]),
            int(scalars[1]),
            int(scalars[2]),
            bytes(hash16),
        )

    def featurize_batch(
        self,
        vocab: VocabHandle,
        contents: list[bytes],
        bits_out: np.ndarray,
        meta_out: np.ndarray,
        hash_out: np.ndarray,
        rows: np.ndarray | None = None,
    ) -> np.ndarray:
        """One ctypes crossing for a whole batch of RAW byte blobs.

        The native side also performs the per-blob preamble the scalar
        path does in Python — universal newlines (sanitize_content) and
        Ruby String#strip — so callers hand over file bytes untouched.
        Writes row i of ``bits_out`` (n, n_lanes) uint32, ``meta_out``
        (n, 3) int32 [|wordset|, length, prefilter flags], ``hash_out``
        (n, 16) uint8.  Returns a status array: 0 ok, 2 non-ASCII, 3
        PCRE2 resource limit — non-zero rows must be redone on the
        Unicode-safe Python path.  The GIL is dropped for the whole
        batch, so featurization worker threads scale across cores.

        ``rows`` (optional int64[n]) maps blob i to its ROW of a larger
        ``bits_out`` matrix: when the native-eligible blobs are a sparse
        subset of a batch (preset/dedupe rows interleaved), the token
        bits are still written zero-copy into the caller-owned final row
        — no staging matrix, no per-blob copy-out.  ``meta_out`` and
        ``hash_out`` stay compact (indexed by blob, not row)."""
        n = len(contents)
        status = np.zeros(n, dtype=np.int8)
        if n == 0:
            return status
        bits_rows = None
        if rows is not None:
            rows = np.ascontiguousarray(rows, dtype=np.int64)
            if rows.shape != (n,):
                raise ValueError(
                    f"rows: need int64 shape ({n},), got {rows.shape}"
                )
            if len(rows) and (
                rows.min() < 0 or rows.max() >= bits_out.shape[0]
            ):
                raise ValueError(
                    f"rows: values out of range for bits_out with "
                    f"{bits_out.shape[0]} rows"
                )
            bits_rows = rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        # the native side writes through raw row-strided pointers — the
        # layout contract must hold even under python -O, so raise, don't
        # assert
        n_bits_rows = bits_out.shape[0] if rows is not None else n
        for name, arr, dtype, shape in (
            ("bits_out", bits_out, np.uint32, (n_bits_rows, vocab.n_lanes)),
            ("meta_out", meta_out, np.int32, (n, 3)),
            ("hash_out", hash_out, np.uint8, (n, 16)),
        ):
            if (
                arr.dtype != dtype
                or not arr.flags.c_contiguous
                or arr.shape != shape
            ):
                raise ValueError(
                    f"{name}: need C-contiguous {np.dtype(dtype).name}"
                    f"{shape}, got {arr.dtype}{arr.shape}"
                )
        datas = (ctypes.c_char_p * n)(*contents)
        lens = (ctypes.c_int64 * n)(*[len(c) for c in contents])
        self._lib.pipe_featurize_batch(
            self._handle,
            vocab._handle,
            datas,
            lens,
            n,
            bits_rows,
            bits_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            meta_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            hash_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        )
        return status

    def refscan_new(self, pattern: re.Pattern, extra_flags: str = ""):
        """Compile a scan union (named groups ``g<i>``) with PCRE2+JIT.

        Default byte mode is the faithful twin of the repo's rb()
        patterns (re.A: ASCII-only \\b/\\w/case folding — in UTF-8 every
        non-ASCII byte is non-word, exactly like re.A's treatment of
        non-ASCII characters).  ``extra_flags``: 'u' switches to
        PCRE2_UTF|PCRE2_UCP Unicode semantics — NOT what rb() patterns
        mean; only for patterns compiled without re.A.  Returns an
        opaque handle, or None if PCRE2 rejects the pattern (caller
        keeps the pure-Python scan)."""
        data = _pcre_pattern(pattern)
        flags = (_flags_str(pattern) + extra_flags).encode()
        return self._lib.pipe_refscan_new(data, len(data), flags) or None

    def refscan_min(self, handle, section: str) -> int:
        """Min named-group pool index over every scan hit; -1 no hit,
        -2 PCRE2 resource failure (caller falls back to Python)."""
        data = section.encode("utf-8")
        return self._lib.pipe_refscan_min(handle, data, len(data))

    def refscan_set_singles(
        self,
        handle,
        patterns: list[re.Pattern],
        extra_flags: str = "",
    ) -> bool:
        """Attach the per-pool-index patterns the exact resolver needs
        (all must share one flag set); False if PCRE2 rejects any."""
        if not patterns:
            return False
        flags = {_flags_str(p) for p in patterns}
        if len(flags) != 1:
            return False
        blob = b"\0".join(_pcre_pattern(p) for p in patterns)
        # the expected count makes index misalignment (an embedded NUL
        # splitting one pattern into two) a hard failure, never a shift
        n = self._lib.pipe_refscan_set_singles(
            handle, blob, len(blob),
            (flags.pop() + extra_flags).encode(), len(patterns),
        )
        return n == len(patterns)

    def refscan_resolve(self, handle, section: str) -> int:
        """The exact first-matching pool index (union floor + per-index
        shadow re-checks, all in C); -1 no match, -2 fall back to the
        Python chain."""
        data = section.encode("utf-8")
        return self._lib.pipe_refscan_resolve(handle, data, len(data))

    def profile_dump(self) -> dict[str, float]:
        """Accumulated per-pass seconds (diagnostic; empty unless
        LICENSEE_TPU_PIPE_PROFILE=1 was set at process start)."""
        n = ctypes.c_size_t()
        ptr = self._lib.pipe_profile_dump(ctypes.byref(n))
        if not ptr:
            return {}
        try:
            text = ctypes.string_at(ptr, n.value).decode()
        finally:
            self._lib.pipe_free(ptr)
        out = {}
        for line in text.splitlines():
            name, _, secs = line.partition("=")
            if secs:
                out[name] = float(secs)
        return out

    def profile_reset(self) -> bool:
        """Zero every counter profile_dump reports (the obs registry
        scrapes deltas and bench intervals want a clean zero).  Returns
        False when the loaded .so predates the symbol."""
        if not self._has_profile_reset:
            return False
        self._lib.pipe_profile_reset()
        return True

    def exact_hash(self, wordset) -> bytes:
        """The 16-byte hash pipe_featurize computes, for a Python-side
        wordset (e.g. a compiled template's).  The hash is an
        order-independent multiset sum, so no sorting on either side."""
        blob = "\0".join(wordset).encode("utf-8")
        hash16 = (ctypes.c_uint8 * 16)()
        self._lib.pipe_exact_hash(blob, len(blob), hash16)
        return bytes(hash16)


def load() -> NativePipeline | None:
    """The shared NativePipeline instance, or None when unavailable."""
    global _instance, _failed
    if _instance is None and not _failed:
        try:
            _instance = NativePipeline()
        except NativeUnavailable:
            _failed = True
    return _instance


# ---------------------------------------------------------------------------
# Module-level profile surface with pure-Python fallback parity.
#
# The obs registry (and any scraper) wants ONE call pair that works
# whether or not the native library loaded: with it, the native
# stage.*/count.* counters; without it, a Python-side dict the fallback
# featurize path feeds (same key names, so dashboards and the delta
# collector never care which build served the traffic).

_py_profile: dict[str, float] = {}
_py_profile_lock = threading.Lock()


def py_profile_add(**rows: float) -> None:
    """Accumulate fallback-path rows, e.g. ``py_profile_add(**{
    "count.blobs": 1, "stage.normalize_s": dt})``.  Cheap enough for
    the per-blob pure-Python path (one lock + dict adds against a
    multi-100-us blob)."""
    with _py_profile_lock:
        for name, v in rows.items():
            _py_profile[name] = _py_profile.get(name, 0.0) + v


def profile_dump() -> dict[str, float]:
    """Cumulative stage.*/count.* rows, native and Python-side merged:
    with the native library loaded the native counters dominate and the
    Python accumulator carries only the rare failed-over blobs (PCRE2
    resource limits); without it, the Python accumulator is the whole
    story.  Key names are identical either way."""
    pipe = _instance  # never trigger a build from a metrics scrape
    native = pipe.profile_dump() if pipe is not None else {}
    with _py_profile_lock:
        py = dict(_py_profile)
    for name, v in py.items():
        native[name] = native.get(name, 0.0) + v
    return native


def profile_reset() -> bool:
    """Zero the cumulative profile surface (both sides).  Returns False
    only when a loaded native .so predates pipe_profile_reset — the
    pure-Python accumulator always resets."""
    with _py_profile_lock:
        _py_profile.clear()
    pipe = _instance
    return pipe.profile_reset() if pipe is not None else True
