"""ctypes bindings for the whole-pipeline native path (native/pipeline.cpp).

Where textops.py accelerates individual passes (leaving ~18 regex passes
and ~17 ctypes crossings per blob in Python), this module runs the ENTIRE
stage-1/stage-2 normalization — PCRE2 for the complex patterns, the shared
hand-coded scanners for the rest — plus wordset extraction, vocabulary
projection, and the Exact-matcher wordset hash, in at most two crossings
per blob.  Ruby String#downcase is full-Unicode, so the downcase between
the stages stays in Python (str.lower).

All pattern strings are shipped to C++ from the single source of truth in
licensee_tpu/normalize/pipeline.py; the only translation is Python's
``\\Z`` (end of string) to PCRE2's ``\\z``.  ``load()`` returns a
``NativePipeline`` or ``None`` (no toolchain / no libpcre2 / disabled via
LICENSEE_TPU_NO_NATIVE), in which case callers keep the pure-Python or
hybrid path.  Differential tests: tests/test_native_pipeline.py; the
SHA1 golden corpus (tests/test_normalize_hashes.py) runs through this
path when built.
"""

from __future__ import annotations

import ctypes
import re
import threading

import numpy as np

from licensee_tpu.native.build import NativeUnavailable, build_and_load

_instance = None
_failed = False


class NativeResourceError(RuntimeError):
    """PCRE2 hit a resource limit (MATCHLIMIT/DEPTHLIMIT) on this blob.

    Python `re` has no such limits, so treating this as "no match" would
    silently diverge from the fallback path on adversarial inputs
    (nested-quantifier patterns vs pathological text).  Callers catch
    this and re-run the single blob through the pure-Python pipeline."""


def _flags_str(pattern: re.Pattern) -> str:
    flags = ""
    if pattern.flags & re.I:
        flags += "i"
    if pattern.flags & re.S:
        flags += "s"
    if pattern.flags & re.X:
        flags += "x"
    return flags


def _pcre_pattern(pattern: re.Pattern) -> bytes:
    # Python \Z (end of string) == PCRE2 \z; PCRE2's \Z allows a final
    # newline, which Python's does not.
    return pattern.pattern.replace("\\Z", "\\z").encode("utf-8")


_TITLE_PREFIX_LEN = 6


def _title_prefixes_for(part: str, k: int = _TITLE_PREFIX_LEN) -> set[str] | None:
    """Lowercase literal prefixes covering every caseless match of one
    title-union alternative, or None when underivable.

    A conservative mini-parser over the pattern strings
    ``License.title_regex_pattern`` actually constructs (literals,
    escapes, ``(?i:``/``(?:...)?`` groups, small char classes): each
    returned prefix is a run of characters every match MUST start with,
    so a text matching none of them provably cannot match the
    alternative.  Anything the parser cannot bound returns the prefix
    accumulated so far (still sound — those characters are mandatory)
    or None when no character is guaranteed at all; the caller disables
    the native gate entirely on any None."""
    out: set[str] = set()
    budget = [256]

    def lc(ch: str) -> str:
        # ASCII-only fold: PCRE2 runs the union caseless in 8-bit byte
        # mode, where non-ASCII bytes never case-fold
        return ch.lower() if "A" <= ch <= "Z" else ch

    def stop(acc: str) -> bool:
        if not acc:
            return False
        out.add(acc)
        return True

    def group_end(s: str, i: int) -> int | None:
        depth = 0
        while i < len(s):
            c = s[i]
            if c == "\\":
                i += 2
                continue
            if c == "[":
                j = s.find("]", i + 1)
                if j < 0:
                    return None
                i = j + 1
                continue
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    return i + 1
            i += 1
        return None

    def split_alts(s: str) -> list[str] | None:
        parts, depth, cur, i = [], 0, "", 0
        while i < len(s):
            c = s[i]
            if c == "\\":
                cur += s[i:i + 2]
                i += 2
                continue
            if c == "[":
                j = s.find("]", i + 1)
                if j < 0:
                    return None
                cur += s[i:j + 1]
                i = j + 1
                continue
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
            elif c == "|" and depth == 0:
                parts.append(cur)
                cur = ""
                i += 1
                continue
            cur += c
            i += 1
        parts.append(cur)
        return parts

    def lit_step(ch: str, rest: str, acc: str) -> bool:
        quant = rest[0] if rest else ""
        if quant == "?":
            return walk(rest[1:], acc + lc(ch)) and walk(rest[1:], acc)
        if quant == "+":
            # at least one occurrence is guaranteed, then repetition is
            # unbounded: stop extending here
            return stop(acc + lc(ch))
        if quant and quant in "*{":
            return stop(acc)
        return walk(rest, acc + lc(ch))

    def walk(s: str, acc: str) -> bool:
        budget[0] -= 1
        if budget[0] < 0:
            return False
        if len(acc) >= k:
            out.add(acc[:k])
            return True
        if not s:
            return stop(acc)
        c = s[0]
        if c == "(":
            if s.startswith("(?:"):
                body_start = 3
            elif s.startswith("(?i:"):
                body_start = 4
            elif s.startswith("(?"):
                return stop(acc)  # lookaround/flags: out of scope
            else:
                body_start = 1
            e = group_end(s, 0)
            if e is None:
                return stop(acc)
            body = s[body_start:e - 1]
            rest = s[e:]
            quant = rest[0] if rest else ""
            alts = split_alts(body)
            if alts is None:
                return stop(acc)
            if quant == "?":
                rest = rest[1:]
                if not walk(rest, acc):
                    return False
                return all(walk(a + rest, acc) for a in alts)
            if quant and quant in "*+{":
                return stop(acc)
            return all(walk(a + rest, acc) for a in alts)
        if c == "[":
            j = s.find("]")
            if j <= 1:
                return stop(acc)
            body = s[1:j]
            rest = s[j + 1:]
            if body.startswith("^"):
                return stop(acc)
            chars: list[str] = []
            t = 0
            while t < len(body):
                bc = body[t]
                if bc == "\\":
                    if t + 1 < len(body) and not body[t + 1].isalnum():
                        chars.append(body[t + 1])
                        t += 2
                        continue
                    return stop(acc)  # \d/\s/... class inside: unbounded
                if bc == "-" and 0 < t < len(body) - 1:
                    return stop(acc)  # range: out of scope
                chars.append(bc)
                t += 1
            if not chars or len(chars) > 6:
                return stop(acc)
            quant = rest[0] if rest else ""
            if quant == "?":
                rest = rest[1:]
                if not walk(rest, acc):
                    return False
                return all(walk(rest, acc + lc(bc)) for bc in chars)
            if quant and quant in "*+{":
                return stop(acc)
            return all(walk(rest, acc + lc(bc)) for bc in chars)
        if c == "\\":
            if len(s) < 2 or s[1].isalnum():
                return stop(acc)  # \d \s \w \b ...: classes/anchors
            return lit_step(s[1], s[2:], acc)
        if c == "|":
            # a bare alternation reached mid-walk can't be folded into a
            # single mandatory prefix; top-level '|' is pre-split below,
            # so hitting one here means the pattern is out of scope
            return False
        if c in ".^$?*+{)":
            return stop(acc)
        return lit_step(c, s[1:], acc)

    top_alts = split_alts(part)
    if top_alts is None:
        return None
    if not all(walk(a, "") for a in top_alts):
        return None
    return out


def _derive_title_prefixes() -> list[str] | None:
    """The '\\n'-joined payload of the ``title_prefixes`` config record:
    minimal lowercase literal prefixes for the whole title union, or
    None (record omitted, native gate disabled) when any alternative is
    underivable."""
    from licensee_tpu.corpus.license import global_title_parts

    all_prefixes: set[str] = set()
    for part in global_title_parts():
        got = _title_prefixes_for(part)
        if not got:
            return None
        all_prefixes.update(got)
    if any("\n" in p or "\0" in p or not p for p in all_prefixes):
        return None
    # minimality: a prefix subsumed by a shorter one never changes the
    # gate's answer, so drop it
    keep = [
        p for p in all_prefixes
        if not any(p != q and p.startswith(q) for q in all_prefixes)
    ]
    if not keep or len(keep) > 1024:
        return None
    return sorted(keep)


def _build_config() -> bytes:
    from licensee_tpu.corpus.license import global_title_regex
    from licensee_tpu.normalize import pipeline as pl
    from licensee_tpu.project_files.license_file import CC_FALSE_POSITIVE_REGEX

    named: dict[str, re.Pattern] = {
        "hrs": pl.REGEXES["hrs"],
        "comment_markup": pl.REGEXES["comment_markup"],
        "markdown_headings": pl.REGEXES["markdown_headings"],
        "link_markup": pl.REGEXES["link_markup"],
        "title": global_title_regex(),
        "version": pl.REGEXES["version"],
        "lists": pl._LISTS,
        "span_markup": pl.REGEXES["span_markup"],
        "bullet": pl.REGEXES["bullet"],
        "bullet_join": pl._BULLET_JOIN,
        "bom": pl.REGEXES["bom"],
        "cc_dedication": pl.REGEXES["cc_dedication"],
        "cc_wiki": pl.REGEXES["cc_wiki"],
        "cc_legal_code": pl.REGEXES["cc_legal_code"],
        "cc0_info": pl.REGEXES["cc0_info"],
        "cc0_disclaimer": pl.REGEXES["cc0_disclaimer"],
        "unlicense_info": pl.REGEXES["unlicense_info"],
        "border_markup": pl.REGEXES["border_markup"],
        "url": pl.REGEXES["url"],
        "strip_copyright": pl._STRIP_COPYRIGHT,
        "block_markup": pl.REGEXES["block_markup"],
        "developed_by": pl.REGEXES["developed_by"],
        "end_of_terms": pl.END_OF_TERMS,
        "mit_optional": pl.REGEXES["mit_optional"],
        "copyright_full": pl.COPYRIGHT_FULL_REGEX,
        "cc_false_positive": CC_FALSE_POSITIVE_REGEX,
    }
    records = b"".join(
        name.encode() + b"\0" + _flags_str(p).encode() + b"\0"
        + _pcre_pattern(p) + b"\0"
        for name, p in named.items()
    )
    # optional title-union gate record (before spelling_table, which
    # must stay last); omitted when the derivation declines
    prefixes = _derive_title_prefixes()
    gate = b""
    if prefixes:
        gate = (
            b"title_prefixes\0\0"
            + "\n".join(prefixes).encode("utf-8") + b"\0"
        )
    # spelling_table must be last: its payload contains '\0' separators
    table = b"".join(
        k.encode() + b"\0" + v.encode() + b"\0"
        for k, v in pl.VARIETAL_WORDS.items()
    )
    return records + gate + b"spelling_table\0\0" + table


class VocabHandle:
    """Token -> id map resident in the native library (per corpus)."""

    def __init__(self, lib, words: list[str], n_lanes: int):
        self._lib = lib
        blob = "\0".join(words).encode("utf-8")
        self.n_lanes = n_lanes
        self._handle = lib.pipe_vocab_new(blob, len(blob), n_lanes)

    def close(self) -> None:
        if self._handle:
            self._lib.pipe_vocab_del(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


class NativePipeline:
    def __init__(self):
        lib = build_and_load("pipeline", (":libpcre2-8.so.0",))
        self._lib = lib
        lib.pipe_free.argtypes = [ctypes.c_void_p]
        lib.pipe_new.restype = ctypes.c_void_p
        lib.pipe_new.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.pipe_error.restype = ctypes.c_char_p
        lib.pipe_error.argtypes = [ctypes.c_void_p]
        lib.pipe_del.argtypes = [ctypes.c_void_p]
        out_len = ctypes.POINTER(ctypes.c_size_t)
        lib.pipe_stage1.restype = ctypes.c_void_p
        lib.pipe_stage1.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, out_len,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.pipe_stage2.restype = ctypes.c_void_p
        lib.pipe_stage2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, out_len,
        ]
        lib.pipe_vocab_new.restype = ctypes.c_void_p
        lib.pipe_vocab_new.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
        ]
        lib.pipe_vocab_del.argtypes = [ctypes.c_void_p]
        lib.pipe_featurize.restype = ctypes.c_int
        lib.pipe_featurize.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.pipe_exact_hash.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.pipe_refscan_new.restype = ctypes.c_void_p
        lib.pipe_refscan_new.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ]
        lib.pipe_refscan_del.argtypes = [ctypes.c_void_p]
        lib.pipe_refscan_min.restype = ctypes.c_int
        lib.pipe_refscan_min.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.pipe_refscan_set_singles.restype = ctypes.c_int
        lib.pipe_refscan_set_singles.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_int,
        ]
        lib.pipe_refscan_resolve.restype = ctypes.c_int
        lib.pipe_refscan_resolve.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ]
        lib.pipe_profile_dump.restype = ctypes.c_void_p
        lib.pipe_profile_dump.argtypes = [
            ctypes.POINTER(ctypes.c_size_t)
        ]
        # a cached .so built before pipe_profile_reset existed lacks the
        # symbol; degrade to reset-unavailable instead of failing init
        try:
            lib.pipe_profile_reset.restype = None
            lib.pipe_profile_reset.argtypes = []
            self._has_profile_reset = True
        except AttributeError:
            self._has_profile_reset = False
        lib.pipe_featurize_raw.restype = ctypes.c_int
        lib.pipe_featurize_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.pipe_featurize_batch.restype = None
        lib.pipe_featurize_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int8),
        ]

        config = _build_config()
        self._handle = lib.pipe_new(config, len(config))
        err = lib.pipe_error(self._handle)
        if err:
            msg = err.decode("utf-8", errors="replace")
            lib.pipe_del(self._handle)
            raise NativeUnavailable(f"pipeline init failed: {msg}")

    # -- per-blob API --

    def stage1(self, text: str) -> tuple[str, int]:
        """content_without_title_and_version (minus html/strip, which the
        caller does) + prefilter flags (bit0 copyright-only, bit1 cc-fp)."""
        data = text.encode("utf-8")
        n = ctypes.c_size_t()
        flags = ctypes.c_int32()
        ptr = self._lib.pipe_stage1(
            self._handle, data, len(data), ctypes.byref(n), ctypes.byref(flags)
        )
        if not ptr:
            raise NativeResourceError("pipe_stage1: PCRE2 resource limit")
        try:
            out = ctypes.string_at(ptr, n.value).decode("utf-8")
        finally:
            self._lib.pipe_free(ptr)
        return out, flags.value

    def stage2(self, lowered_stage1: str) -> str:
        data = lowered_stage1.encode("utf-8")
        n = ctypes.c_size_t()
        ptr = self._lib.pipe_stage2(self._handle, data, len(data), ctypes.byref(n))
        if not ptr:
            raise NativeResourceError("pipe_stage2: PCRE2 resource limit")
        try:
            return ctypes.string_at(ptr, n.value).decode("utf-8")
        finally:
            self._lib.pipe_free(ptr)

    def vocab(self, words: list[str], n_lanes: int) -> VocabHandle:
        return VocabHandle(self._lib, words, n_lanes)

    def featurize(
        self,
        vocab: VocabHandle,
        lowered_stage1: str,
        bits_out: np.ndarray | None = None,
    ) -> tuple[np.ndarray, int, int, bytes]:
        """(packed vocab bits, |wordset|, normalized char length,
        16-byte wordset hash) for one blob.  ``bits_out`` may be a
        caller-provided uint32[n_lanes] row (e.g. a slice of the batch
        matrix) to avoid a copy."""
        if bits_out is None:
            bits_out = np.zeros(vocab.n_lanes, dtype=np.uint32)
        assert bits_out.dtype == np.uint32 and bits_out.size == vocab.n_lanes
        data = lowered_stage1.encode("utf-8")
        scalars = (ctypes.c_int32 * 2)()
        hash16 = (ctypes.c_uint8 * 16)()
        rc = self._lib.pipe_featurize(
            self._handle,
            vocab._handle,
            data,
            len(data),
            bits_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            scalars,
            hash16,
        )
        if rc == 3:
            raise NativeResourceError("pipe_featurize: PCRE2 resource limit")
        if rc != 0:
            raise RuntimeError(f"pipe_featurize rc={rc}")
        return bits_out, int(scalars[0]), int(scalars[1]), bytes(hash16)

    def featurize_raw(
        self,
        vocab: VocabHandle,
        stripped_content: str,
        bits_out: np.ndarray | None = None,
    ) -> tuple[np.ndarray, int, int, int, bytes] | None:
        """One-crossing featurize of String#strip'd content: (bits,
        |wordset|, char length, prefilter flags, wordset hash).  Returns
        None when the content has non-ASCII bytes — the caller must use
        the two-crossing stage1 -> str.lower() -> featurize path so the
        downcase is full-Unicode."""
        if bits_out is None:
            bits_out = np.zeros(vocab.n_lanes, dtype=np.uint32)
        try:
            data = stripped_content.encode("ascii")
        except UnicodeEncodeError:
            return None
        scalars = (ctypes.c_int32 * 3)()
        hash16 = (ctypes.c_uint8 * 16)()
        rc = self._lib.pipe_featurize_raw(
            self._handle,
            vocab._handle,
            data,
            len(data),
            bits_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            scalars,
            hash16,
        )
        if rc == 2:
            return None
        if rc == 3:
            raise NativeResourceError("pipe_featurize_raw: PCRE2 resource limit")
        if rc != 0:
            raise RuntimeError(f"pipe_featurize_raw rc={rc}")
        return (
            bits_out,
            int(scalars[0]),
            int(scalars[1]),
            int(scalars[2]),
            bytes(hash16),
        )

    def featurize_batch(
        self,
        vocab: VocabHandle,
        contents: list[bytes],
        bits_out: np.ndarray,
        meta_out: np.ndarray,
        hash_out: np.ndarray,
        rows: np.ndarray | None = None,
    ) -> np.ndarray:
        """One ctypes crossing for a whole batch of RAW byte blobs.

        The native side also performs the per-blob preamble the scalar
        path does in Python — universal newlines (sanitize_content) and
        Ruby String#strip — so callers hand over file bytes untouched.
        Writes row i of ``bits_out`` (n, n_lanes) uint32, ``meta_out``
        (n, 3) int32 [|wordset|, length, prefilter flags], ``hash_out``
        (n, 16) uint8.  Returns a status array: 0 ok, 2 non-ASCII, 3
        PCRE2 resource limit — non-zero rows must be redone on the
        Unicode-safe Python path.  The GIL is dropped for the whole
        batch, so featurization worker threads scale across cores.

        ``rows`` (optional int64[n]) maps blob i to its ROW of a larger
        ``bits_out`` matrix: when the native-eligible blobs are a sparse
        subset of a batch (preset/dedupe rows interleaved), the token
        bits are still written zero-copy into the caller-owned final row
        — no staging matrix, no per-blob copy-out.  ``meta_out`` and
        ``hash_out`` stay compact (indexed by blob, not row)."""
        n = len(contents)
        status = np.zeros(n, dtype=np.int8)
        if n == 0:
            return status
        bits_rows = None
        if rows is not None:
            rows = np.ascontiguousarray(rows, dtype=np.int64)
            if rows.shape != (n,):
                raise ValueError(
                    f"rows: need int64 shape ({n},), got {rows.shape}"
                )
            if len(rows) and (
                rows.min() < 0 or rows.max() >= bits_out.shape[0]
            ):
                raise ValueError(
                    f"rows: values out of range for bits_out with "
                    f"{bits_out.shape[0]} rows"
                )
            bits_rows = rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        # the native side writes through raw row-strided pointers — the
        # layout contract must hold even under python -O, so raise, don't
        # assert
        n_bits_rows = bits_out.shape[0] if rows is not None else n
        for name, arr, dtype, shape in (
            ("bits_out", bits_out, np.uint32, (n_bits_rows, vocab.n_lanes)),
            ("meta_out", meta_out, np.int32, (n, 3)),
            ("hash_out", hash_out, np.uint8, (n, 16)),
        ):
            if (
                arr.dtype != dtype
                or not arr.flags.c_contiguous
                or arr.shape != shape
            ):
                raise ValueError(
                    f"{name}: need C-contiguous {np.dtype(dtype).name}"
                    f"{shape}, got {arr.dtype}{arr.shape}"
                )
        datas = (ctypes.c_char_p * n)(*contents)
        lens = (ctypes.c_int64 * n)(*[len(c) for c in contents])
        self._lib.pipe_featurize_batch(
            self._handle,
            vocab._handle,
            datas,
            lens,
            n,
            bits_rows,
            bits_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            meta_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            hash_out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        )
        return status

    def refscan_new(self, pattern: re.Pattern, extra_flags: str = ""):
        """Compile a scan union (named groups ``g<i>``) with PCRE2+JIT.

        Default byte mode is the faithful twin of the repo's rb()
        patterns (re.A: ASCII-only \\b/\\w/case folding — in UTF-8 every
        non-ASCII byte is non-word, exactly like re.A's treatment of
        non-ASCII characters).  ``extra_flags``: 'u' switches to
        PCRE2_UTF|PCRE2_UCP Unicode semantics — NOT what rb() patterns
        mean; only for patterns compiled without re.A.  Returns an
        opaque handle, or None if PCRE2 rejects the pattern (caller
        keeps the pure-Python scan)."""
        data = _pcre_pattern(pattern)
        flags = (_flags_str(pattern) + extra_flags).encode()
        return self._lib.pipe_refscan_new(data, len(data), flags) or None

    def refscan_min(self, handle, section: str) -> int:
        """Min named-group pool index over every scan hit; -1 no hit,
        -2 PCRE2 resource failure (caller falls back to Python)."""
        data = section.encode("utf-8")
        return self._lib.pipe_refscan_min(handle, data, len(data))

    def refscan_set_singles(
        self,
        handle,
        patterns: list[re.Pattern],
        extra_flags: str = "",
    ) -> bool:
        """Attach the per-pool-index patterns the exact resolver needs
        (all must share one flag set); False if PCRE2 rejects any."""
        if not patterns:
            return False
        flags = {_flags_str(p) for p in patterns}
        if len(flags) != 1:
            return False
        blob = b"\0".join(_pcre_pattern(p) for p in patterns)
        # the expected count makes index misalignment (an embedded NUL
        # splitting one pattern into two) a hard failure, never a shift
        n = self._lib.pipe_refscan_set_singles(
            handle, blob, len(blob),
            (flags.pop() + extra_flags).encode(), len(patterns),
        )
        return n == len(patterns)

    def refscan_resolve(self, handle, section: str) -> int:
        """The exact first-matching pool index (union floor + per-index
        shadow re-checks, all in C); -1 no match, -2 fall back to the
        Python chain."""
        data = section.encode("utf-8")
        return self._lib.pipe_refscan_resolve(handle, data, len(data))

    def profile_dump(self) -> dict[str, float]:
        """Accumulated per-pass seconds (diagnostic; empty unless
        LICENSEE_TPU_PIPE_PROFILE=1 was set at process start)."""
        n = ctypes.c_size_t()
        ptr = self._lib.pipe_profile_dump(ctypes.byref(n))
        if not ptr:
            return {}
        try:
            text = ctypes.string_at(ptr, n.value).decode()
        finally:
            self._lib.pipe_free(ptr)
        out = {}
        for line in text.splitlines():
            name, _, secs = line.partition("=")
            if secs:
                out[name] = float(secs)
        return out

    def profile_reset(self) -> bool:
        """Zero every counter profile_dump reports (the obs registry
        scrapes deltas and bench intervals want a clean zero).  Returns
        False when the loaded .so predates the symbol."""
        if not self._has_profile_reset:
            return False
        self._lib.pipe_profile_reset()
        return True

    def exact_hash(self, wordset) -> bytes:
        """The 16-byte hash pipe_featurize computes, for a Python-side
        wordset (e.g. a compiled template's).  The hash is an
        order-independent multiset sum, so no sorting on either side."""
        blob = "\0".join(wordset).encode("utf-8")
        hash16 = (ctypes.c_uint8 * 16)()
        self._lib.pipe_exact_hash(blob, len(blob), hash16)
        return bytes(hash16)


def load() -> NativePipeline | None:
    """The shared NativePipeline instance, or None when unavailable."""
    global _instance, _failed
    if _instance is None and not _failed:
        try:
            _instance = NativePipeline()
        except NativeUnavailable:
            _failed = True
    return _instance


# ---------------------------------------------------------------------------
# Module-level profile surface with pure-Python fallback parity.
#
# The obs registry (and any scraper) wants ONE call pair that works
# whether or not the native library loaded: with it, the native
# stage.*/count.* counters; without it, a Python-side dict the fallback
# featurize path feeds (same key names, so dashboards and the delta
# collector never care which build served the traffic).

_py_profile: dict[str, float] = {}
_py_profile_lock = threading.Lock()


def py_profile_add(**rows: float) -> None:
    """Accumulate fallback-path rows, e.g. ``py_profile_add(**{
    "count.blobs": 1, "stage.normalize_s": dt})``.  Cheap enough for
    the per-blob pure-Python path (one lock + dict adds against a
    multi-100-us blob)."""
    with _py_profile_lock:
        for name, v in rows.items():
            _py_profile[name] = _py_profile.get(name, 0.0) + v


def profile_dump() -> dict[str, float]:
    """Cumulative stage.*/count.* rows, native and Python-side merged:
    with the native library loaded the native counters dominate and the
    Python accumulator carries only the rare failed-over blobs (PCRE2
    resource limits); without it, the Python accumulator is the whole
    story.  Key names are identical either way."""
    pipe = _instance  # never trigger a build from a metrics scrape
    native = pipe.profile_dump() if pipe is not None else {}
    with _py_profile_lock:
        py = dict(_py_profile)
    for name, v in py.items():
        native[name] = native.get(name, 0.0) + v
    return native


def profile_reset() -> bool:
    """Zero the cumulative profile surface (both sides).  Returns False
    only when a loaded native .so predates pipe_profile_reset — the
    pure-Python accumulator always resets."""
    with _py_profile_lock:
        _py_profile.clear()
    pipe = _instance
    return pipe.profile_reset() if pipe is not None else True
