"""Build-on-demand for the native components (g++ + system libs only).

Shared by gitodb.py and textops.py: compile ``native/<name>.cpp`` into a
cached ``_<name>.so`` next to the bindings, rebuilding when the source is
newer.  Never hard-fails at import — callers catch NativeUnavailable and
fall back to pure-Python paths.
"""

from __future__ import annotations

import os
import subprocess
import threading

_NATIVE_SRC_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_DIR = os.path.dirname(os.path.abspath(__file__))

_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def build_and_load(name: str, extra_libs: tuple[str, ...] = ()):
    """Compile native/<name>.cpp -> _<name>.so (cached) and ctypes-load it."""
    import ctypes

    if os.environ.get("LICENSEE_TPU_NO_NATIVE"):
        raise NativeUnavailable("disabled by LICENSEE_TPU_NO_NATIVE")
    src = os.path.join(_NATIVE_SRC_DIR, f"{name}.cpp")
    lib = os.path.join(_LIB_DIR, f"_{name}.so")
    if not os.path.exists(src):
        raise NativeUnavailable(f"missing source {src}")
    # staleness covers shared headers (scanners.h) too, not just the .cpp
    newest_src = os.path.getmtime(src)
    for entry in os.listdir(_NATIVE_SRC_DIR):
        if entry.endswith(".h"):
            newest_src = max(
                newest_src, os.path.getmtime(os.path.join(_NATIVE_SRC_DIR, entry))
            )
    with _lock:
        if not os.path.exists(lib) or os.path.getmtime(lib) < newest_src:
            # unique temp per process: concurrent builders must not
            # interleave g++ output into the same file (os.replace of a
            # complete .so is atomic either way)
            import tempfile

            fd, tmp = tempfile.mkstemp(
                prefix=f"_{name}.", suffix=".so.tmp", dir=_LIB_DIR
            )
            os.close(fd)
            try:
                cmd = [
                    "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                    "-o", tmp, src, *[f"-l{l}" for l in extra_libs],
                ]
                result = subprocess.run(cmd, capture_output=True, text=True)
                if result.returncode != 0:
                    raise NativeUnavailable(
                        f"{name} build failed: {result.stderr[:500]}"
                    )
                os.replace(tmp, lib)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        try:
            return ctypes.CDLL(lib)
        except OSError as exc:
            raise NativeUnavailable(f"{name} load failed: {exc}") from exc
