"""ctypes bindings for the native normalization scanners (native/textops.cpp).

Exposes str -> str twins of the five hottest pipeline passes.  ``load()``
returns a ``TextOps`` instance or ``None`` (toolchain missing / disabled),
in which case pipeline.py keeps its pure-Python regex path.  Outputs are
bit-identical to the regexes — enforced by tests/test_textops.py
differential tests and the license-hash golden corpus.
"""

from __future__ import annotations

import ctypes

from licensee_tpu.native.build import NativeUnavailable, build_and_load

_instance = None
_failed = False


class TextOps:
    def __init__(self):
        lib = build_and_load("textops")
        self._lib = lib
        lib.top_free.argtypes = [ctypes.c_void_p]
        out_len = ctypes.POINTER(ctypes.c_size_t)
        for fname in (
            "top_squeeze_strip",
            "top_strip_whitespace",
            "top_dashes",
            "top_quotes",
            "top_hyphenated",
            "top_wordset",
        ):
            fn = getattr(lib, fname)
            fn.restype = ctypes.c_void_p
            fn.argtypes = [ctypes.c_char_p, ctypes.c_size_t, out_len]
        lib.top_spelling_new.restype = ctypes.c_void_p
        lib.top_spelling_new.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.top_spelling_del.argtypes = [ctypes.c_void_p]
        lib.top_spelling.restype = ctypes.c_void_p
        lib.top_spelling.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, out_len,
        ]

        from licensee_tpu.normalize.pipeline import VARIETAL_WORDS

        table = b"".join(
            k.encode() + b"\0" + v.encode() + b"\0"
            for k, v in VARIETAL_WORDS.items()
        )
        self._spelling = lib.top_spelling_new(table, len(table))

    def _call(self, fname: str, s: str, *pre) -> str:
        data = s.encode("utf-8")
        n = ctypes.c_size_t()
        ptr = getattr(self._lib, fname)(*pre, data, len(data), ctypes.byref(n))
        try:
            return ctypes.string_at(ptr, n.value).decode("utf-8")
        finally:
            self._lib.top_free(ptr)

    def squeeze_strip(self, s: str) -> str:
        return self._call("top_squeeze_strip", s)

    def strip_whitespace(self, s: str) -> str:
        return self._call("top_strip_whitespace", s)

    def dashes(self, s: str) -> str:
        return self._call("top_dashes", s)

    def quotes(self, s: str) -> str:
        return self._call("top_quotes", s)

    def hyphenated(self, s: str) -> str:
        return self._call("top_hyphenated", s)

    def spelling(self, s: str) -> str:
        return self._call("top_spelling", s, self._spelling)

    def wordset(self, s: str) -> frozenset[str]:
        """Unique wordset tokens of normalized content (the
        WORDSET_TOKEN findall + frozenset, one native scan)."""
        joined = self._call("top_wordset", s)
        return frozenset(joined.split("\0")) if joined else frozenset()


def load() -> TextOps | None:
    """The shared TextOps instance, or None when native is unavailable."""
    global _instance, _failed
    if _instance is None and not _failed:
        try:
            _instance = TextOps()
        except NativeUnavailable:
            _failed = True
    return _instance
