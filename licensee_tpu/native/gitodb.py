"""ctypes bindings for the native git ODB reader (native/gitodb.cpp).

The shared library is built on demand with the system toolchain (g++ +
zlib, both baked into the image) and cached next to this module; a stale
cache (older than the source) is rebuilt.  If the toolchain or build is
unavailable the caller falls back to git plumbing subprocesses
(projects/git_project.py), so importing this module must never hard-fail.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "gitodb.cpp",
)
_LIB = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_gitodb.so")

_build_lock = threading.Lock()
_lib = None
_lib_error: str | None = None


class NativeUnavailable(RuntimeError):
    pass


def _build() -> None:
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", _LIB + ".tmp", _SRC, "-lz",
    ]
    result = subprocess.run(cmd, capture_output=True, text=True)
    if result.returncode != 0:
        raise NativeUnavailable(f"gitodb build failed: {result.stderr[:500]}")
    os.replace(_LIB + ".tmp", _LIB)


def _load():
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        raise NativeUnavailable(_lib_error)
    with _build_lock:
        if _lib is not None:
            return _lib
        try:
            if os.environ.get("LICENSEE_TPU_NO_NATIVE"):
                raise NativeUnavailable("disabled by LICENSEE_TPU_NO_NATIVE")
            if not os.path.exists(_SRC):
                raise NativeUnavailable(f"missing source {_SRC}")
            if (
                not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            ):
                _build()
            lib = ctypes.CDLL(_LIB)
        except NativeUnavailable as exc:
            _lib_error = str(exc)
            raise
        except OSError as exc:
            _lib_error = f"gitodb load failed: {exc}"
            raise NativeUnavailable(_lib_error) from exc

        lib.godb_last_error.restype = ctypes.c_char_p
        lib.godb_open.restype = ctypes.c_void_p
        lib.godb_open.argtypes = [ctypes.c_char_p]
        lib.godb_close.argtypes = [ctypes.c_void_p]
        lib.godb_resolve.restype = ctypes.c_int
        lib.godb_resolve.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.godb_root_entries.restype = ctypes.c_void_p
        lib.godb_root_entries.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.godb_read_blob.restype = ctypes.c_void_p
        lib.godb_read_blob.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.godb_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class GitODBError(ValueError):
    pass


class GitODB:
    """A repository handle over the native object-database reader."""

    def __init__(self, path: str):
        lib = _load()
        self._lib = lib
        self._handle = lib.godb_open(os.fsencode(path))
        if not self._handle:
            raise GitODBError(lib.godb_last_error().decode("utf-8", "replace"))

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.godb_close(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

    def _error(self) -> str:
        return self._lib.godb_last_error().decode("utf-8", "replace")

    def resolve(self, revision: str | None = None) -> str:
        out = ctypes.create_string_buffer(41)
        rc = self._lib.godb_resolve(
            self._handle, (revision or "HEAD").encode("utf-8"), out
        )
        if rc != 0:
            raise GitODBError(self._error())
        return out.value.decode("ascii")

    def root_entries(self, commit_sha: str) -> list[dict]:
        """Root-tree entries: [{'mode', 'oid', 'type', 'name'}, ...]."""
        ptr = self._lib.godb_root_entries(
            self._handle, commit_sha.encode("ascii")
        )
        if not ptr:
            raise GitODBError(self._error())
        try:
            text = ctypes.string_at(ptr).decode("utf-8", "replace")
        finally:
            self._lib.godb_free(ptr)
        entries = []
        for line in text.splitlines():
            mode, oid, otype, name = line.split(" ", 3)
            entries.append(
                {"mode": mode, "oid": oid, "type": otype, "name": name}
            )
        return entries

    def read_blob(self, sha: str, max_len: int = 64 * 1024) -> bytes:
        n = ctypes.c_size_t()
        ptr = self._lib.godb_read_blob(
            self._handle, sha.encode("ascii"), max_len, ctypes.byref(n)
        )
        if not ptr:
            raise GitODBError(self._error())
        try:
            return ctypes.string_at(ptr, n.value)
        finally:
            self._lib.godb_free(ptr)
