"""ctypes bindings for the native git ODB reader (native/gitodb.cpp).

The shared library is built on demand with the system toolchain (g++ +
zlib, both baked into the image) and cached next to this module; a stale
cache (older than the source) is rebuilt.  If the toolchain or build is
unavailable the caller falls back to git plumbing subprocesses
(projects/git_project.py), so importing this module must never hard-fail.
"""

from __future__ import annotations

import ctypes
import os

from licensee_tpu.native.build import NativeUnavailable, build_and_load

_lib = None
_lib_error: str | None = None


def _load():
    global _lib, _lib_error
    if _lib is not None:
        return _lib
    if _lib_error is not None:
        raise NativeUnavailable(_lib_error)
    try:
        lib = build_and_load("gitodb", ("z",))
    except NativeUnavailable as exc:
        _lib_error = str(exc)
        raise

    lib.godb_last_error.restype = ctypes.c_char_p
    lib.godb_open.restype = ctypes.c_void_p
    lib.godb_open.argtypes = [ctypes.c_char_p]
    lib.godb_close.argtypes = [ctypes.c_void_p]
    lib.godb_resolve.restype = ctypes.c_int
    lib.godb_resolve.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.godb_root_entries.restype = ctypes.c_void_p
    lib.godb_root_entries.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.godb_read_blob.restype = ctypes.c_void_p
    lib.godb_read_blob.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_size_t),
    ]
    lib.godb_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


class GitODBError(ValueError):
    pass


class GitODB:
    """A repository handle over the native object-database reader."""

    def __init__(self, path: str):
        lib = _load()
        self._lib = lib
        self._handle = lib.godb_open(os.fsencode(path))
        if not self._handle:
            raise GitODBError(lib.godb_last_error().decode("utf-8", "replace"))

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.godb_close(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

    def _error(self) -> str:
        return self._lib.godb_last_error().decode("utf-8", "replace")

    def resolve(self, revision: str | None = None) -> str:
        out = ctypes.create_string_buffer(41)
        rc = self._lib.godb_resolve(
            self._handle, (revision or "HEAD").encode("utf-8"), out
        )
        if rc != 0:
            raise GitODBError(self._error())
        return out.value.decode("ascii")

    def root_entries(self, commit_sha: str) -> list[dict]:
        """Root-tree entries: [{'mode', 'oid', 'type', 'name'}, ...].

        Records are NUL-separated (git forbids NUL in tree entry names but
        permits newlines, so '\\0' is the only safe delimiter)."""
        n = ctypes.c_size_t()
        ptr = self._lib.godb_root_entries(
            self._handle, commit_sha.encode("ascii"), ctypes.byref(n)
        )
        if not ptr:
            raise GitODBError(self._error())
        try:
            text = ctypes.string_at(ptr, n.value).decode("utf-8", "replace")
        finally:
            self._lib.godb_free(ptr)
        entries = []
        for record in text.split("\0"):
            if not record:
                continue
            mode, oid, otype, name = record.split(" ", 3)
            entries.append(
                {"mode": mode, "oid": oid, "type": otype, "name": name}
            )
        return entries

    def read_blob(self, sha: str, max_len: int = 64 * 1024) -> bytes:
        n = ctypes.c_size_t()
        ptr = self._lib.godb_read_blob(
            self._handle, sha.encode("ascii"), max_len, ctypes.byref(n)
        )
        if not ptr:
            raise GitODBError(self._error())
        try:
            return ctypes.string_at(ptr, n.value)
        finally:
            self._lib.godb_free(ptr)
