"""The pallas Dice kernel must be bit-identical to the XLA reference path
(`dice_xla.score_pairs`) — same (numerator, denominator) for every pair,
same top-1 — across batch shapes that exercise the tile padding, the CC
false-positive guard, and the padding-template mask.

On the CPU test mesh the kernel runs in pallas interpreter mode; numerics
are identical to the compiled Mosaic path (validated on TPU hardware).
"""

import numpy as np
import pytest

from licensee_tpu.corpus.compiler import default_corpus
from licensee_tpu.kernels.dice_xla import (
    CorpusArrays,
    make_best_match_fn,
    score_pairs,
)
from licensee_tpu.kernels.dice_pallas import (
    best_match_pallas,
    make_padded_best_match_fn,
    score_pairs_pallas,
)


@pytest.fixture(scope="module")
def corpus():
    return default_corpus()


@pytest.fixture(scope="module")
def arrays(corpus):
    return CorpusArrays.from_compiled(corpus)


def random_features(corpus, B, seed=0, cc=True):
    rng = np.random.default_rng(seed)
    bits = rng.integers(0, 2**32, size=(B, corpus.n_lanes), dtype=np.uint32)
    n_words = rng.integers(50, 3000, size=B).astype(np.int32)
    lengths = rng.integers(100, 60000, size=B).astype(np.int32)
    cc_fp = (
        rng.integers(0, 2, size=B).astype(bool)
        if cc
        else np.zeros(B, dtype=bool)
    )
    return bits, n_words, lengths, cc_fp


@pytest.mark.parametrize("B", [1, 7, 128, 129, 300])
def test_score_pairs_matches_xla(corpus, arrays, B):
    feats = random_features(corpus, B, seed=B)
    n_xla, d_xla = score_pairs(arrays, *feats)
    n_pal, d_pal = score_pairs_pallas(arrays, *feats)
    np.testing.assert_array_equal(np.asarray(n_xla), np.asarray(n_pal))
    np.testing.assert_array_equal(np.asarray(d_xla), np.asarray(d_pal))


def test_best_match_matches_xla(corpus, arrays):
    feats = random_features(corpus, 200, seed=42)
    xla = make_best_match_fn(arrays)(*feats)
    pal = best_match_pallas(arrays, *feats)
    for a, b in zip(xla, pal):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_real_template_features_top1(corpus, arrays):
    """Each template's own bitset must rank itself first (overlap == n_wf,
    zero length delta) through the pallas path."""
    T = corpus.n_templates
    bits = np.asarray(arrays.bits)[:T]
    n_words = np.asarray(arrays.n_wf)[:T]
    lengths = np.asarray(arrays.length)[:T]
    cc_fp = np.zeros(T, dtype=bool)
    # CC templates would be masked under cc_fp; keep the guard off here
    idx, num, den = best_match_pallas(arrays, bits, n_words, lengths, cc_fp)
    ref_idx, ref_num, ref_den = make_best_match_fn(arrays)(
        bits, n_words, lengths, cc_fp
    )
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref_idx))
    np.testing.assert_array_equal(np.asarray(num), np.asarray(ref_num))
    np.testing.assert_array_equal(np.asarray(den), np.asarray(ref_den))
    idx = np.asarray(idx)
    num = np.asarray(num)
    for t in range(T):
        # a template that ranks itself first has full fieldless overlap
        if idx[t] == t:
            assert num[t] == n_words[t]


def test_cc_guard_masks_cc_templates(corpus, arrays):
    cc_rows = [
        t for t, flag in enumerate(np.asarray(arrays.cc_flag)) if flag
    ]
    assert cc_rows, "corpus should contain CC templates"
    t = cc_rows[0]
    bits = np.asarray(arrays.bits)[t : t + 1]
    n_words = np.asarray(arrays.n_wf)[t : t + 1]
    lengths = np.asarray(arrays.length)[t : t + 1]
    # with the CC false-positive flag set, the perfect CC match must lose
    idx, num, den = best_match_pallas(
        arrays, bits, n_words, lengths, np.array([True])
    )
    assert int(np.asarray(idx)[0]) != t
    # without the flag it must win at score 100
    idx2, num2, den2 = best_match_pallas(
        arrays, bits, n_words, lengths, np.array([False])
    )
    assert int(np.asarray(idx2)[0]) == t
    assert 200.0 * int(np.asarray(num2)[0]) / int(np.asarray(den2)[0]) == 100.0


def test_padded_best_match_fn(corpus, arrays):
    feats = random_features(corpus, 150, seed=7)
    prepare, fn = make_padded_best_match_fn(arrays)
    out = fn(*prepare(*feats))
    ref = make_best_match_fn(arrays)(*feats)
    B = 150
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(np.asarray(a)[:B], np.asarray(b)[:B])


def test_batch_classifier_pallas_agrees_with_default(corpus):
    """End-to-end: BatchClassifier(method='pallas') must produce identical
    results to the default XLA method on real license texts."""
    import re

    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels.batch import BatchClassifier

    contents = []
    for lic in License.all(hidden=True, pseudo=False)[:12]:
        text = re.sub(r"\[(\w+)\]", "example", lic.content or "")
        contents.append(text)
        contents.append(text + "\nsome extra trailing words here")

    default = BatchClassifier(pad_batch_to=64).classify_blobs(contents)
    pallas = BatchClassifier(method="pallas", pad_batch_to=64).classify_blobs(
        contents
    )
    for d, p in zip(default, pallas):
        assert (d.key, d.matcher) == (p.key, p.matcher)
        assert d.confidence == p.confidence


# -- the MXU (fused-unpack int8 dot) variant --


@pytest.mark.parametrize("B", [1, 7, 129, 300])
def test_mxu_overlap_matches_xla(corpus, arrays, B):
    from licensee_tpu.kernels.dice_xla import overlap_pairs
    from licensee_tpu.kernels.dice_pallas import overlap_pairs_mxu

    bits = random_features(corpus, B, seed=B)[0]
    ref = np.asarray(overlap_pairs(arrays, bits, "popcount"))
    mxu = np.asarray(overlap_pairs_mxu(arrays, bits))
    np.testing.assert_array_equal(ref, mxu)


def test_mxu_best_match_matches_xla(corpus, arrays):
    from licensee_tpu.kernels.dice_pallas import make_best_match_fn_pallas_mxu

    feats = random_features(corpus, 200, seed=11)
    ref = make_best_match_fn(arrays)(*feats)
    mxu = make_best_match_fn_pallas_mxu(arrays)(*feats)
    for a, b in zip(ref, mxu):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_batch_classifier_pallas_mxu_agrees_with_default(corpus):
    import re

    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels.batch import BatchClassifier

    contents = []
    for lic in License.all(hidden=True, pseudo=False)[:8]:
        text = re.sub(r"\[(\w+)\]", "example", lic.content or "")
        contents.append(text + "\nwith a little trailing noise")

    default = BatchClassifier(pad_batch_to=64).classify_blobs(contents)
    mxu = BatchClassifier(method="pallas-mxu", pad_batch_to=64).classify_blobs(
        contents
    )
    for d, p in zip(default, mxu):
        assert (d.key, d.matcher, d.confidence) == (p.key, p.matcher, p.confidence)


def test_auto_method_resolution(tmp_path):
    """method='auto' picks the measured winner by corpus width (the ADR
    table in dice_pallas.py): popcount <=128 templates, matmul above."""
    from licensee_tpu.corpus.spdx import spdx_corpus
    from licensee_tpu.corpus.spdx_synth import synth_spdx_dir
    from licensee_tpu.kernels.batch import BatchClassifier

    assert BatchClassifier(pad_batch_to=16).method == "popcount"

    wide = spdx_corpus(synth_spdx_dir(str(tmp_path / "w"), 130))
    assert BatchClassifier(corpus=wide, pad_batch_to=16).method == "matmul"
