"""GitHubProject behavior with a stubbed HTTP layer — the reference's
WebMock pattern (spec/licensee/projects/git_hub_project_spec.rb): fake the
remote, never hit the network."""

import os

import pytest

from licensee_tpu.corpus.license import License
from licensee_tpu.projects import GitHubProject, RepoNotFound
from tests.conftest import FIXTURES_DIR, fixture_path


class StubbedGitHubProject(GitHubProject):
    """Serves the contents API from a local fixture directory."""

    def __init__(self, url, fixture="mit", **kwargs):
        self.fixture = fixture
        super().__init__(url, **kwargs)

    def _request(self, path, raw=False):
        root = fixture_path(self.fixture)
        if not path:
            return [
                {"name": name, "type": "file", "path": name}
                for name in sorted(os.listdir(root))
            ]
        full = os.path.join(root, path)
        if not os.path.exists(full):
            return None
        with open(full, "rb") as f:
            return f.read()


class EmptyGitHubProject(GitHubProject):
    def _request(self, path, raw=False):
        return None if raw else []


def test_repo_url_parsing():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee")
    assert project.repo == "benbalter/licensee"


def test_repo_url_with_dot_git():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee.git")
    assert project.repo == "benbalter/licensee"


def test_invalid_url_raises():
    with pytest.raises(ValueError):
        GitHubProject("https://gitlab.com/benbalter/licensee")


def test_detects_license_remotely():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee")
    assert project.license == License.find("mit")


def test_missing_repo_raises_not_found():
    project = EmptyGitHubProject("https://github.com/benbalter/does-not-exist")
    with pytest.raises(RepoNotFound):
        _ = project.license


def test_facade_routes_github_urls(monkeypatch):
    import licensee_tpu

    captured = {}

    class FakeProject:
        def __init__(self, url, **kwargs):
            captured["url"] = url

    monkeypatch.setattr(
        "licensee_tpu.projects.GitHubProject", FakeProject
    )
    licensee_tpu.project("https://github.com/a/b")
    assert captured["url"] == "https://github.com/a/b"


def test_vanished_file_raises_not_found():
    """A listed file that 404s during load is an API error, not an empty
    license (github_project.rb:48-53 lets octokit raise)."""

    class VanishingGitHubProject(StubbedGitHubProject):
        def _request(self, path, raw=False):
            if raw:
                return None  # every per-file fetch 404s
            return super()._request(path, raw)

    project = VanishingGitHubProject("https://github.com/user/repo")
    with pytest.raises(RepoNotFound, match="Could not load"):
        project.license_file
