"""GitHubProject behavior with a stubbed HTTP layer — the reference's
WebMock pattern (spec/licensee/projects/git_hub_project_spec.rb): fake the
remote, never hit the network."""

import os

import pytest

from licensee_tpu.corpus.license import License
from licensee_tpu.projects import GitHubProject, RepoNotFound
from tests.conftest import FIXTURES_DIR, fixture_path


class StubbedGitHubProject(GitHubProject):
    """Serves the contents API from a local fixture directory."""

    def __init__(self, url, fixture="mit", **kwargs):
        self.fixture = fixture
        super().__init__(url, **kwargs)

    def _request(self, path, raw=False):
        root = fixture_path(self.fixture)
        if not path:
            return [
                {"name": name, "type": "file", "path": name}
                for name in sorted(os.listdir(root))
            ]
        full = os.path.join(root, path)
        if not os.path.exists(full):
            return None
        with open(full, "rb") as f:
            return f.read()


class EmptyGitHubProject(GitHubProject):
    def _request(self, path, raw=False):
        return None if raw else []


def test_repo_url_parsing():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee")
    assert project.repo == "benbalter/licensee"


def test_repo_url_with_dot_git():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee.git")
    assert project.repo == "benbalter/licensee"


def test_invalid_url_raises():
    with pytest.raises(ValueError):
        GitHubProject("https://gitlab.com/benbalter/licensee")


def test_detects_license_remotely():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee")
    assert project.license == License.find("mit")


def test_missing_repo_raises_not_found():
    project = EmptyGitHubProject("https://github.com/benbalter/does-not-exist")
    with pytest.raises(RepoNotFound):
        _ = project.license


def test_facade_routes_github_urls(monkeypatch):
    import licensee_tpu

    captured = {}

    class FakeProject:
        def __init__(self, url, **kwargs):
            captured["url"] = url

    monkeypatch.setattr(
        "licensee_tpu.projects.GitHubProject", FakeProject
    )
    licensee_tpu.project("https://github.com/a/b")
    assert captured["url"] == "https://github.com/a/b"


def test_vanished_file_raises_not_found():
    """A listed file that 404s during load is an API error, not an empty
    license (github_project.rb:48-53 lets octokit raise)."""

    class VanishingGitHubProject(StubbedGitHubProject):
        def _request(self, path, raw=False):
            if raw:
                return None  # every per-file fetch 404s
            return super()._request(path, raw)

    project = VanishingGitHubProject("https://github.com/user/repo")
    with pytest.raises(RepoNotFound, match="Could not load"):
        project.license_file


def test_local_folder_raises():
    with pytest.raises(ValueError):
        GitHubProject(fixture_path("mit"))


def test_matched_and_license_file_accessors():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee")
    assert project.license == License.find("mit")
    assert project.matched_file is not None
    assert project.matched_file.filename == "LICENSE.txt"
    assert project.license_file is project.matched_file


def test_readme_and_package_detection_off_by_default():
    project = StubbedGitHubProject("https://github.com/benbalter/licensee")
    assert project.readme_file is None
    assert project.package_file is None


def test_readme_detection_over_the_api():
    project = StubbedGitHubProject(
        "https://github.com/benbalter/licensee",
        fixture="readme",
        detect_readme=True,
    )
    assert project.readme_file is not None
    assert project.readme_file.filename == "README.md"
    assert project.license == License.find("mit")


def test_ref_is_stored_and_sent_as_query(monkeypatch):
    project = StubbedGitHubProject(
        "https://github.com/benbalter/licensee", ref="dev-branch"
    )
    assert project.ref == "dev-branch"

    # the REAL request layer carries the ref as an escaped query param
    import urllib.request

    sent = []

    def fake_urlopen(req, *a, **kw):
        sent.append(req.full_url)
        raise AssertionError("network stop")

    p2 = GitHubProject.__new__(GitHubProject)
    p2.repo = "o/r"
    p2.ref = "dev branch"
    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    with pytest.raises(AssertionError):
        GitHubProject._request(p2, "LICENSE")
    assert sent and "ref=dev%20branch" in sent[0]
