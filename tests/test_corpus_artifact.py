"""Versioned corpus artifacts (licensee_tpu/corpus/artifact.py):
canonical fingerprinting, bundle round-trips, integrity verification,
and the shared source resolver behind --corpus and the reload verbs."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from licensee_tpu.corpus.artifact import (
    ArtifactError,
    build_manifest,
    corpus_fingerprint,
    load_artifact,
    resolve_corpus,
    short_fingerprint,
    write_artifact,
)
from licensee_tpu.corpus.compiler import CompiledCorpus

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def small_corpus():
    from licensee_tpu.corpus.license import License

    pool = [License.find("mit"), License.find("apache-2.0")]
    return CompiledCorpus.compile(pool)


@pytest.fixture(scope="module")
def other_corpus():
    from licensee_tpu.corpus.license import License

    pool = [License.find("mit"), License.find("isc")]
    return CompiledCorpus.compile(pool)


def test_fingerprint_is_stable_and_content_sensitive(
    small_corpus, other_corpus
):
    fp = corpus_fingerprint(small_corpus)
    assert len(fp) == 64 and int(fp, 16) >= 0
    assert corpus_fingerprint(small_corpus) == fp  # memoized, stable
    assert corpus_fingerprint(other_corpus) != fp
    assert short_fingerprint(fp) == fp[:12]
    assert short_fingerprint(None) is None


def test_fingerprint_changes_when_the_matrix_changes(small_corpus):
    from dataclasses import replace

    bits = small_corpus.bits.copy()
    bits[0, 0] ^= 1  # one flipped bit anywhere in the matrix
    tampered = replace(small_corpus, bits=bits)
    assert corpus_fingerprint(tampered) != corpus_fingerprint(small_corpus)


def test_artifact_roundtrip_preserves_everything(small_corpus, tmp_path):
    path = str(tmp_path / "small.corpus.npz")
    manifest = write_artifact(path, small_corpus, source="unit-test")
    assert manifest["fingerprint"] == corpus_fingerprint(small_corpus)
    assert manifest["templates"] == small_corpus.n_templates
    assert manifest["source"] == "unit-test"

    loaded, loaded_manifest = load_artifact(path)
    assert loaded_manifest == manifest
    assert loaded.keys == small_corpus.keys
    assert loaded.vocab == small_corpus.vocab
    assert loaded.content_hashes == small_corpus.content_hashes
    assert loaded.exact_sets == small_corpus.exact_sets
    for name in ("bits", "n_wf", "n_fieldset", "field_count",
                 "alt_count", "length", "cc_flag"):
        assert np.array_equal(
            getattr(loaded, name), getattr(small_corpus, name)
        ), name
    # the load is proven, not assumed: fingerprints agree
    assert corpus_fingerprint(loaded) == manifest["fingerprint"]


def test_artifact_refuses_corruption(small_corpus, tmp_path):
    path = str(tmp_path / "a.corpus.npz")
    write_artifact(path, small_corpus)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\x00" * 16)
    with pytest.raises(ArtifactError):
        load_artifact(path)


def test_artifact_refuses_garbage_truncation_and_wrong_format(tmp_path):
    garbage = tmp_path / "g.npz"
    garbage.write_bytes(b"not a zip at all")
    with pytest.raises(ArtifactError, match="cannot read"):
        load_artifact(str(garbage))
    plain = tmp_path / "plain.npz"
    np.savez(plain, foo=np.zeros(3))
    with pytest.raises(ArtifactError, match="not a corpus artifact"):
        load_artifact(str(plain))


def test_manifest_fingerprint_mismatch_fails_closed(
    small_corpus, other_corpus, tmp_path
):
    """A manifest lying about its payload must be refused: rebuild the
    bundle with one array swapped and the OLD manifest kept."""
    path = str(tmp_path / "lie.corpus.npz")
    write_artifact(path, small_corpus)
    with np.load(path, allow_pickle=False) as npz:
        data = {name: npz[name] for name in npz.files}
    meta = json.loads(bytes(data["meta"]).decode("utf-8"))
    # splice other_corpus's template constants under small_corpus's
    # manifest (shapes agree: both pools have 2 templates)
    data["n_wf"] = other_corpus.n_wf
    np.savez(path, **data)
    assert meta["manifest"]["fingerprint"] == corpus_fingerprint(
        small_corpus
    )
    with pytest.raises(ArtifactError, match="fingerprint"):
        load_artifact(path)


def test_resolve_corpus_sources(small_corpus, tmp_path):
    art = str(tmp_path / "r.corpus.npz")
    write_artifact(art, small_corpus, source="unit-test")
    corpus, fp, manifest = resolve_corpus(art)
    assert fp == corpus_fingerprint(small_corpus)
    assert manifest["source"] == "unit-test"
    corpus_v, fp_v, manifest_v = resolve_corpus("vendored")
    assert manifest_v is None
    assert fp_v == corpus_fingerprint(corpus_v)
    with pytest.raises(ArtifactError, match="cannot load corpus"):
        resolve_corpus(str(tmp_path / "nope"))


def test_build_manifest_shape(small_corpus):
    manifest = build_manifest(small_corpus, source="s")
    assert manifest["format"] == "licensee-tpu-corpus"
    assert manifest["format_version"] == 1
    assert manifest["vocab"] == small_corpus.vocab_size
    assert manifest["lanes"] == small_corpus.n_lanes


def test_corpus_build_cli_roundtrip(tmp_path):
    """The corpus-build verb: build an artifact from the vendored pool,
    inspect it, and refuse a corrupt one — all through the real CLI."""
    art = str(tmp_path / "vendored.corpus.npz")
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO_ROOT}
    built = subprocess.run(
        [sys.executable, "-m", "licensee_tpu.cli.main", "corpus-build",
         "--corpus", "vendored", "--output", art],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert built.returncode == 0, built.stderr
    manifest = json.loads(built.stdout)
    assert manifest["templates"] > 0

    inspected = subprocess.run(
        [sys.executable, "-m", "licensee_tpu.cli.main", "corpus-build",
         "--inspect", art],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert inspected.returncode == 0, inspected.stderr
    assert json.loads(inspected.stdout) == manifest

    with open(art, "r+b") as f:
        f.seek(os.path.getsize(art) // 2)
        f.write(b"\x00" * 8)
    broken = subprocess.run(
        [sys.executable, "-m", "licensee_tpu.cli.main", "corpus-build",
         "--inspect", art],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert broken.returncode == 1
    assert "error" in broken.stderr
