"""BatchProject: manifest-driven classification with resume."""

import json
import os

from licensee_tpu.projects.batch_project import BatchProject
from tests.conftest import FIXTURES_DIR, fixture_path


def manifest_paths():
    paths = []
    for fixture in ("mit", "bsd-2-author", "cc-by-nd", "mit-with-copyright"):
        dir_path = fixture_path(fixture)
        for name in sorted(os.listdir(dir_path)):
            full = os.path.join(dir_path, name)
            if os.path.isfile(full) and name.lower().startswith(("license", "copying")):
                paths.append(full)
    return paths


def test_batch_run_and_resume(tmp_path):
    paths = manifest_paths()
    out = tmp_path / "results.jsonl"

    project = BatchProject(paths, batch_size=4)
    stats = project.run(str(out))
    lines = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(lines) == len(paths)
    assert stats.total == len(paths)

    by_path = {line["path"]: line for line in lines}
    assert by_path[fixture_path("mit/LICENSE.txt")]["key"] == "mit"
    assert by_path[fixture_path("bsd-2-author/LICENSE")]["key"] == "bsd-2-clause"
    assert by_path[fixture_path("cc-by-nd/LICENSE")]["key"] is None

    # resume: a second run appends nothing
    project2 = BatchProject(paths, batch_size=4)
    project2.run(str(out), resume=True)
    assert len(out.read_text().splitlines()) == len(paths)


def test_batch_stats(tmp_path):
    paths = manifest_paths()
    project = BatchProject(paths, batch_size=8)
    project.run(str(tmp_path / "r.jsonl"))
    stats = project.stats
    assert stats.prefiltered_exact >= 1  # mit/LICENSE.txt
    assert stats.dice_matched >= 1       # bsd-2-author
    assert stats.unmatched >= 1          # cc-by-nd


def test_classify_contents():
    project = BatchProject([])
    results = project.classify_contents(
        [open(fixture_path("mit/LICENSE.txt"), "rb").read(), b"nope"]
    )
    assert results[0].key == "mit"
    assert results[1].key is None
