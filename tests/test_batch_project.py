"""BatchProject: manifest-driven classification with resume."""

import json
import os

import pytest

from licensee_tpu.projects.batch_project import BatchProject
from tests.conftest import FIXTURES_DIR, fixture_contents, fixture_path


def manifest_paths():
    paths = []
    for fixture in ("mit", "bsd-2-author", "cc-by-nd", "mit-with-copyright"):
        dir_path = fixture_path(fixture)
        for name in sorted(os.listdir(dir_path)):
            full = os.path.join(dir_path, name)
            if os.path.isfile(full) and name.lower().startswith(("license", "copying")):
                paths.append(full)
    return paths


def test_batch_run_and_resume(tmp_path):
    paths = manifest_paths()
    out = tmp_path / "results.jsonl"

    project = BatchProject(paths, batch_size=4)
    stats = project.run(str(out))
    lines = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(lines) == len(paths)
    assert stats.total == len(paths)

    by_path = {line["path"]: line for line in lines}
    assert by_path[fixture_path("mit/LICENSE.txt")]["key"] == "mit"
    assert by_path[fixture_path("bsd-2-author/LICENSE")]["key"] == "bsd-2-clause"
    assert by_path[fixture_path("cc-by-nd/LICENSE")]["key"] is None

    # resume: a second run appends nothing
    project2 = BatchProject(paths, batch_size=4)
    project2.run(str(out), resume=True)
    assert len(out.read_text().splitlines()) == len(paths)


def test_batch_stats(tmp_path):
    paths = manifest_paths()
    project = BatchProject(paths, batch_size=8)
    project.run(str(tmp_path / "r.jsonl"))
    stats = project.stats
    assert stats.prefiltered_exact >= 1  # mit/LICENSE.txt
    assert stats.dice_matched >= 1       # bsd-2-author
    assert stats.unmatched >= 1          # cc-by-nd


def test_classify_contents():
    project = BatchProject([])
    results = project.classify_contents(
        [open(fixture_path("mit/LICENSE.txt"), "rb").read(), b"nope"]
    )
    assert results[0].key == "mit"
    assert results[1].key is None


def test_resume_discards_torn_tail(tmp_path):
    """A crash mid-write leaves a torn final line; resume must rewrite it
    instead of counting it as done."""
    paths = manifest_paths()
    out = tmp_path / "results.jsonl"
    BatchProject(paths, batch_size=4).run(str(out))
    full = out.read_text()
    n = len(full.splitlines())

    # simulate a crash: chop the last record in half (no trailing newline)
    torn = full[: full.rindex('{"path"') + 20]
    out.write_text(torn)

    BatchProject(paths, batch_size=4).run(str(out), resume=True)
    lines = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(lines) == n  # every row parses, torn row rewritten


def test_unreadable_path_marked_as_read_error(tmp_path):
    paths = [fixture_path("mit/LICENSE.txt"), str(tmp_path / "does-not-exist")]
    out = tmp_path / "results.jsonl"
    project = BatchProject(paths, batch_size=4)
    stats = project.run(str(out))
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert rows[0]["key"] == "mit"
    assert "error" not in rows[0]
    assert rows[1]["key"] is None
    assert rows[1]["error"] == "read_error"
    assert stats.read_errors == 1
    # stats are internally consistent: categories + read errors == total
    counted = (
        stats.prefiltered_copyright
        + stats.prefiltered_exact
        + stats.dice_matched
        + stats.unmatched
    )
    assert counted + stats.read_errors == stats.total


def test_resume_stats_count_only_new_rows(tmp_path):
    paths = manifest_paths()
    out = tmp_path / "results.jsonl"
    BatchProject(paths, batch_size=4).run(str(out))

    # remove the last two completed rows, then resume with a new project
    lines = out.read_text().splitlines()
    out.write_text("\n".join(lines[:-2]) + "\n")
    project = BatchProject(paths, batch_size=4)
    stats = project.run(str(out), resume=True)
    assert stats.total == 2
    assert len(out.read_text().splitlines()) == len(paths)


def test_poisoned_blob_is_contained(tmp_path, monkeypatch):
    """A featurizer exception on one blob must produce an error row for
    that blob only — the run continues and every other row is classified
    (resume would otherwise wedge at the same offset forever)."""
    import licensee_tpu.kernels.batch as batch_mod

    poison = b"\x00POISON\x00"
    real_sanitize = batch_mod.sanitize_content

    def exploding_sanitize(raw):
        if isinstance(raw, bytes) and b"POISON" in raw:
            raise RuntimeError("synthetic featurizer edge case")
        return real_sanitize(raw)

    monkeypatch.setattr(batch_mod, "sanitize_content", exploding_sanitize)
    # the whole-batch native crossing bypasses sanitize_content; poison
    # it too so BOTH containment layers are exercised: the batch call's
    # exception demotes every row to the per-blob loop, whose sanitize
    # raises on the poison blob only
    from licensee_tpu.native import pipeline as npipe

    nat = npipe.load()
    if nat is not None:
        real_batch = nat.featurize_batch

        def exploding_batch(vocab, contents, *args, **kwargs):
            if any(b"POISON" in c for c in contents):
                raise RuntimeError("synthetic batch featurizer edge case")
            return real_batch(vocab, contents, *args, **kwargs)

        monkeypatch.setattr(nat, "featurize_batch", exploding_batch)

    paths = []
    mit = open(fixture_path("mit/LICENSE.txt"), "rb").read()
    for i, content in enumerate([mit, poison, mit, b"not a license"]):
        p = tmp_path / f"LICENSE_{i}"
        p.write_bytes(content)
        paths.append(str(p))

    out = tmp_path / "results.jsonl"
    project = BatchProject(paths, batch_size=4)
    stats = project.run(str(out))

    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(rows) == 4
    assert rows[0]["key"] == "mit" and "error" not in rows[0]
    assert rows[1]["key"] is None
    assert rows[1]["error"].startswith("featurize_error")
    assert rows[2]["key"] == "mit"
    assert rows[3]["key"] is None and "error" not in rows[3]
    assert stats.featurize_errors == 1
    assert stats.total == 4


def test_pipelined_run_matches_serial_classify(tmp_path):
    """The threaded read->featurize->dispatch pipeline must produce
    byte-identical rows to the serial classify path, in manifest order."""
    import json
    import re

    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels.batch import BatchClassifier

    licenses = License.all(hidden=True, pseudo=False)
    paths = []
    for i, lic in enumerate(licenses[:20]):
        p = tmp_path / f"LICENSE_{i}"
        content = re.sub(r"\[(\w+)\]", "example", lic.content or "")
        if i % 5 == 0:
            content += f"\nextra words {i} beyond the template"
        if i % 7 == 0:
            content = "Copyright (c) 2024 Someone"
        p.write_text(content)
        paths.append(str(p))

    project = BatchProject(
        paths, batch_size=8, workers=4, inflight=3
    )
    out = tmp_path / "results.jsonl"
    stats = project.run(str(out))
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths  # manifest order preserved

    clf = BatchClassifier(pad_batch_to=8)
    serial = clf.classify_blobs([open(p, "rb").read() for p in paths])
    for row, res in zip(rows, serial):
        assert row["key"] == res.key and row["matcher"] == res.matcher
        assert row["confidence"] == res.confidence

    # stage timers recorded (the observability surface)
    for stage in ("read", "featurize", "dispatch", "score", "write", "elapsed"):
        assert stage in stats.stage_seconds


def test_dedupe_short_circuits_repeats(tmp_path):
    """Identical (basename, content) pairs classify once; repeats come
    from the cache with identical rows (classification is a pure function
    of content + filename, so hits are exact).  The cache fills at
    finish time, so hits start a few batches behind the first copy —
    enough copies must span enough batches."""
    mit = open(fixture_path("mit/LICENSE.txt"), "rb").read()
    paths = []
    for i in range(8):
        d = tmp_path / f"repo{i}"
        d.mkdir()
        p = d / "LICENSE"
        p.write_bytes(mit)
        paths.append(str(p))
    paths.append(str(tmp_path / "other.txt"))
    (tmp_path / "other.txt").write_bytes(b"no license text at all here")

    out = tmp_path / "out.jsonl"
    project = BatchProject(paths, batch_size=1, workers=1, inflight=1)
    stats = project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["key"] for r in rows] == ["mit"] * 8 + [None]
    assert stats.dedupe_hits >= 1
    body = {k: v for k, v in rows[0].items() if k != "path"}
    assert all(
        {k: v for k, v in r.items() if k != "path"} == body for r in rows[:8]
    )

    # the same run without dedupe produces identical rows
    out2 = tmp_path / "out2.jsonl"
    project2 = BatchProject(paths, batch_size=1, dedupe=False)
    stats2 = project2.run(str(out2), resume=False)
    rows2 = [json.loads(line) for line in out2.read_text().splitlines()]
    assert [
        {k: v for k, v in r.items() if k != "path"} for r in rows
    ] == [{k: v for k, v in r.items() if k != "path"} for r in rows2]
    assert stats2.dedupe_hits == 0


def test_progress_lines(tmp_path, capsys):
    """--progress SECS: JSON heartbeat on stderr while run() streams
    (rate-limited; 0 disables)."""
    mit = open(fixture_path("mit/LICENSE.txt"), "rb").read()
    paths = []
    for i in range(6):
        p = tmp_path / f"L{i}"
        p.write_bytes(mit + str(i).encode())
        paths.append(str(p))
    project = BatchProject(
        paths, batch_size=1, workers=1, inflight=1, progress_every=1e-9
    )
    project.run(str(tmp_path / "out.jsonl"), resume=False)
    lines = [
        json.loads(l)
        for l in capsys.readouterr().err.strip().splitlines()
        if l.startswith("{")
    ]
    assert lines, "expected progress heartbeats"
    assert lines[-1]["progress"] == 6 and lines[-1]["of"] == 6
    assert all("files_per_sec" in l for l in lines)

    project2 = BatchProject(paths, batch_size=1)
    project2.run(str(tmp_path / "out2.jsonl"), resume=False)
    assert capsys.readouterr().err.strip() == ""  # off by default

    for bad in (-1, float("nan")):
        with pytest.raises(ValueError):
            BatchProject(paths, progress_every=bad)


def test_dedupe_cache_holds_immutable_snapshots(tmp_path):
    """The dedupe cache stores a snapshot (tuple closest), never the live
    BlobResult a batch is still finishing: cached objects alias many
    output rows, so any in-place mutation after insertion would corrupt
    unrelated rows.  finish_chunks also trims only rows it built, so a
    preset row's (already-trimmed) list is never re-sliced."""
    mit = open(fixture_path("mit/LICENSE.txt"), "rb").read()
    # perturb so the Exact prefilter misses and the Dice scorer (the
    # closest-list producer) runs
    blob = mit + b"\nextra trailing words beyond the template text\n"
    paths = []
    for i in range(6):
        d = tmp_path / f"r{i}"
        d.mkdir()
        p = d / "LICENSE"
        p.write_bytes(blob)
        paths.append(str(p))
    project = BatchProject(
        paths, batch_size=1, workers=1, inflight=1, closest=2, threshold=90
    )
    out = tmp_path / "out.jsonl"
    stats = project.run(str(out), resume=False)
    assert stats.dedupe_hits >= 1
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert all(r["key"] == "mit" for r in rows)
    # every duplicate row carries the identical trimmed closest list
    assert all(len(r["closest"]) == 2 for r in rows)
    assert all(r["closest"] == rows[0]["closest"] for r in rows)
    # and the cache's own copies are frozen (tuple, trimmed)
    for cached in project._dedupe_cache.values():
        assert cached.closest is None or (
            isinstance(cached.closest, tuple) and len(cached.closest) <= 2
        )


def test_dedupe_key_carries_filename_dispatch(tmp_path):
    """The cache key carries the filename-dependent dispatch (the HTML
    gate in license mode), so HTML-converted semantics never leak onto a
    same-content non-HTML file — while plain files with DIFFERENT names
    (LICENSE vs COPYING) do share hits."""
    html = b"<html><body><h1>MIT License</h1></body></html>"
    p1 = tmp_path / "LICENSE.html"
    p2 = tmp_path / "LICENSE"
    p1.write_bytes(html)
    p2.write_bytes(html)
    project = BatchProject([str(p1), str(p2)], batch_size=2)
    out = tmp_path / "out.jsonl"
    project.run(str(out), resume=False)
    assert project.stats.dedupe_hits == 0  # html vs non-html: no hit

    mit = open(fixture_path("mit/LICENSE.txt"), "rb").read()
    paths = []
    for i, name in enumerate(
        ["LICENSE", "COPYING", "LICENSE.txt", "LICENSE.md"] * 2
    ):
        d = tmp_path / f"r{i}"
        d.mkdir()
        p = d / name
        p.write_bytes(mit)
        paths.append(str(p))
    project2 = BatchProject(paths, batch_size=1, workers=1, inflight=1)
    project2.run(str(tmp_path / "out2.jsonl"), resume=False)
    assert project2.stats.dedupe_hits >= 1  # names differ, dispatch same


# -- the resume-compatibility sidecar (<output>.meta.json) --

def test_resume_config_mismatch_is_refused(tmp_path):
    """Resuming an output written under a different mode/config must
    fail loudly instead of silently mixing incompatible rows."""
    mit = fixture_contents("mit/LICENSE.txt")
    p = tmp_path / "LICENSE"
    p.write_text(mit)
    paths = [str(p)] * 4
    out = tmp_path / "out.jsonl"
    BatchProject(paths[:2], batch_size=2, workers=1).run(
        str(out), resume=False
    )
    assert (tmp_path / "out.jsonl.meta.json").exists()

    # same config resumes fine (and re-writes the sidecar)
    BatchProject(paths, batch_size=2, workers=1).run(str(out), resume=True)
    assert len(out.read_text().splitlines()) == 4

    # different mode: refused, output untouched
    before = out.read_text()
    with pytest.raises(ValueError, match="mode"):
        BatchProject(
            paths, batch_size=2, workers=1, mode="package", mesh=None
        ).run(str(out), resume=True)
    assert out.read_text() == before

    # different threshold: refused too
    with pytest.raises(ValueError, match="threshold"):
        BatchProject(paths, batch_size=2, workers=1, threshold=90.0).run(
            str(out), resume=True
        )

    # resume=False overwrites both output and sidecar
    BatchProject(
        paths[:2], batch_size=2, workers=1, threshold=90.0
    ).run(str(out), resume=False)
    assert len(out.read_text().splitlines()) == 2


def test_resume_without_sidecar_is_accepted(tmp_path):
    """Outputs from before the sidecar existed (or with a deleted
    sidecar) must keep resuming — the check is best-effort."""
    import os

    mit = fixture_contents("mit/LICENSE.txt")
    p = tmp_path / "LICENSE"
    p.write_text(mit)
    out = tmp_path / "out.jsonl"
    BatchProject([str(p)] * 2, batch_size=2, workers=1).run(
        str(out), resume=False
    )
    os.unlink(tmp_path / "out.jsonl.meta.json")
    BatchProject([str(p)] * 4, batch_size=2, workers=1).run(
        str(out), resume=True
    )
    assert len(out.read_text().splitlines()) == 4
    assert (tmp_path / "out.jsonl.meta.json").exists()  # re-written


def test_resume_mismatch_cli_error(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    mit = fixture_contents("mit/LICENSE.txt")
    (tmp_path / "LICENSE").write_text(mit)
    manifest = tmp_path / "m.txt"
    manifest.write_text(str(tmp_path / "LICENSE") + "\n")
    out = tmp_path / "out.jsonl"
    rc = main(["batch-detect", str(manifest), "--output", str(out),
               "--mesh", "none"])
    assert rc == 0
    rc = main(["batch-detect", str(manifest), "--output", str(out),
               "--mesh", "none", "--mode", "auto"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "cannot resume" in err and "mode" in err


def test_preset_prerendered_rows_match_loop_rendering(tmp_path):
    """Preset rows (cache hits / unrouted) are JSONL-rendered on the
    produce workers; every written line must equal what the write loop
    would render from the final result object."""
    from licensee_tpu.projects.batch_project import _jsonl_row

    mit = fixture_contents("mit/LICENSE.txt")
    (tmp_path / "LICENSE").write_text(mit)
    (tmp_path / "mod.c").write_text("int x;\n")
    (tmp_path / "package.json").write_text('{"license": "MIT"}')
    paths = (
        [str(tmp_path / "LICENSE")] * 5
        + [str(tmp_path / "mod.c")] * 3
        + [str(tmp_path / "package.json")] * 2
    ) * 3
    out = tmp_path / "out.jsonl"
    project = BatchProject(
        paths, batch_size=5, workers=1, mode="auto", mesh=None
    )
    project.run(str(out), resume=False)
    rows = out.read_text().splitlines()
    assert len(rows) == len(paths)
    # oracle: re-render every row from a fresh unpipelined pass
    oracle = BatchProject(paths, batch_size=5, mode="auto", mesh=None)
    _, results = oracle.classify_paths(paths)
    for line, path, result in zip(rows, paths, results):
        assert line == _jsonl_row(path, result, None)


def test_resume_sidecar_with_extra_future_keys_is_accepted(tmp_path):
    """A sidecar written by a newer version (extra fields) must not
    refuse a resume whose tracked settings all match."""
    mit = fixture_contents("mit/LICENSE.txt")
    p = tmp_path / "LICENSE"
    p.write_text(mit)
    out = tmp_path / "out.jsonl"
    BatchProject([str(p)] * 2, batch_size=2, workers=1).run(
        str(out), resume=False
    )
    meta = tmp_path / "out.jsonl.meta.json"
    prior = json.loads(meta.read_text())
    prior["future_field"] = "something"
    meta.write_text(json.dumps(prior))
    BatchProject([str(p)] * 4, batch_size=2, workers=1).run(
        str(out), resume=True
    )
    assert len(out.read_text().splitlines()) == 4


def test_resume_fingerprint_pins_template_content(tmp_path):
    """Regression (ADVICE r5): the sidecar's corpus fingerprint folds in
    per-template normalized-content hashes — an edited vendored template
    with unchanged keys and vocab size must refuse to resume."""
    from dataclasses import replace

    from licensee_tpu.kernels.batch import BatchClassifier

    mit = fixture_contents("mit/LICENSE.txt")
    p = tmp_path / "LICENSE"
    p.write_text(mit)
    out = tmp_path / "out.jsonl"
    first = BatchProject([str(p)] * 2, batch_size=2, workers=1)
    first.run(str(out), resume=False)
    config = first._run_config()
    assert "content_sha1" in config["corpus"]

    # same corpus -> same fingerprint -> resume accepted
    BatchProject([str(p)] * 4, batch_size=2, workers=1).run(
        str(out), resume=True
    )

    # simulate ONE template's normalized content changing while keys and
    # vocab size stay identical (the exact blind spot of the old
    # keys+vocab-only fingerprint)
    corpus = first.classifier.corpus
    hashes = dict(corpus.content_hashes)
    h, key = next(iter(hashes.items()))
    del hashes[h]
    hashes["0" * 40] = key
    edited = replace(corpus, content_hashes=hashes)
    clf = BatchClassifier(corpus=edited, pad_batch_to=2, mesh=None)
    project = BatchProject([str(p)] * 4, batch_size=2, classifier=clf)
    assert (
        project._run_config()["corpus"]["keys_sha1"]
        == config["corpus"]["keys_sha1"]
    )
    before = out.read_text()
    with pytest.raises(ValueError, match="corpus"):
        project.run(str(out), resume=True)
    assert out.read_text() == before


def test_resume_corpus_mismatch_names_both_fingerprints(tmp_path):
    """The refusal must NAME the evidence: both corpora's content
    fingerprints and the --corpus sources that produced them, not an
    opaque 'corpus changed'."""
    import json

    from licensee_tpu.corpus.compiler import CompiledCorpus
    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels.batch import BatchClassifier
    from licensee_tpu.projects.batch_project import ResumeConfigError

    def project_for(keys, source):
        corpus = CompiledCorpus.compile(
            [License.find(k) for k in keys]
        )
        clf = BatchClassifier(
            corpus=corpus, pad_batch_to=2, mesh=None, device=False
        )
        return BatchProject(
            [], batch_size=2, classifier=clf, corpus_source=source,
            process_index=0, process_count=1, tracer=False,
        )

    writer = project_for(["mit", "apache-2.0"], "corpusA")
    out = tmp_path / "out.jsonl"
    out.write_text('{"path": "x"}\n')
    sidecar = tmp_path / "out.jsonl.meta.json"
    sidecar.write_text(json.dumps(writer._run_config()) + "\n")
    writer_sha = writer._run_config()["corpus"]["content_sha1"]

    # the same corpus under a different source label still resumes:
    # corpus_source is descriptive, the fingerprints decide
    relabeled = project_for(["mit", "apache-2.0"], "corpusA-moved")
    relabeled._check_resume_config(str(out), resume=True)

    reader = project_for(["mit", "isc"], "corpusB")
    reader_sha = reader._run_config()["corpus"]["content_sha1"]
    with pytest.raises(ResumeConfigError) as excinfo:
        reader._check_resume_config(str(out), resume=True)
    message = str(excinfo.value)
    assert "corpus fingerprint mismatch" in message
    assert writer_sha in message and reader_sha in message
    assert "corpusA" in message and "corpusB" in message

    # an OLD sidecar (no corpus_source key) still gets the fingerprint
    # detail, with the source reported as unknown
    prior = json.loads(sidecar.read_text())
    del prior["corpus_source"]
    sidecar.write_text(json.dumps(prior) + "\n")
    with pytest.raises(ResumeConfigError) as excinfo:
        reader._check_resume_config(str(out), resume=True)
    assert "unknown source" in str(excinfo.value)
    assert writer_sha in str(excinfo.value)


def test_writer_thread_failure_propagates_without_deadlock(
    tmp_path, monkeypatch
):
    """The finish/write loop runs on a dedicated writer thread (the r6
    serial-path reduction): a failure there must surface as run()'s
    exception — never a silent truncation, never a producer blocked
    forever on the bounded handoff queue."""
    import licensee_tpu.projects.batch_project as bp

    calls = {"n": 0}
    real_row = bp._jsonl_row

    def poisoned_row(path, result, error):
        calls["n"] += 1
        if calls["n"] > 2:
            raise RuntimeError("disk on fire")
        return real_row(path, result, error)

    monkeypatch.setattr(bp, "_jsonl_row", poisoned_row)
    paths = manifest_paths() * 3  # several batches through the queue
    project = BatchProject(paths, batch_size=2, workers=1)
    with pytest.raises(RuntimeError, match="disk on fire"):
        project.run(str(tmp_path / "out.jsonl"), resume=False)


def test_writer_thread_keeps_manifest_order_across_many_batches(tmp_path):
    """Rows must land in manifest order (the resume invariant) even
    with many small batches racing through the dispatch -> writer
    handoff."""
    paths = manifest_paths() * 5
    out = tmp_path / "out.jsonl"
    project = BatchProject(paths, batch_size=2)
    stats = project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths
    assert stats.total == len(paths)
    # the write stage is accounted by the writer thread
    assert "write" in stats.stage_seconds
