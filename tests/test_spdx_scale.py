"""Full-SPDX-width validation: the license-list-XML schema zoo and the
T≈600 north-star corpus (BASELINE.md config 4).

The adversarial fixtures stress what the real license-list repo contains
— nested <optional>, <alt> inside deep <list> nesting, exceptions,
<standardLicenseHeader> carrying its own markup — and the scale tests
prove self-detection + cross-template separation through the REAL
ingestion path (XML -> render -> compile -> device score), not synthetic
bitsets.
"""

from __future__ import annotations

import os

import pytest

from licensee_tpu.corpus.spdx import SpdxTemplate, load_spdx_dir, spdx_corpus
from licensee_tpu.corpus.spdx_synth import synth_spdx_dir
from licensee_tpu.kernels.batch import BatchClassifier
from tests.conftest import fixture_path

ADVERSARIAL = fixture_path("spdx-adversarial")


@pytest.fixture(scope="module")
def adversarial():
    return {t.key: t for t in load_spdx_dir(ADVERSARIAL)}


def test_adversarial_dir_skips_only_the_broken(adversarial):
    # Malformed.xml (unclosed elements) and No-License-Element.xml are
    # skipped; every schema-stressing-but-valid file loads
    assert sorted(adversarial) == [
        "crlf-whitespace",
        "deep-list",
        "empty-text",
        "header-zoo",
        "nested-optional",
        "only-exception",
    ]


def test_nested_optional_renders_all_bodies(adversarial):
    content = adversarial["nested-optional"].content
    assert "outer optional notice" in content
    assert "inner optional aside" in content
    assert "sibling optional paragraph" in content
    assert "permission grant verbatim" in content


def test_standard_license_header_is_excluded(adversarial):
    # standardLicenseHeader is not part of the license body
    # (corpus/spdx.py:_render) even when it carries alt/optional/list
    content = adversarial["header-zoo"].content
    assert "menagerie artifact" in content
    assert "headerword-one" not in content
    assert "zoo of markup" not in content


def test_deep_list_renders_every_item(adversarial):
    content = adversarial["deep-list"].content
    for needle in (
        "first stipulation",
        "a. keep the notice",
        "i. in source bundles",
        "embedded marker",
        "b. forward the stipulations",
        "survives termination",
    ):
        assert needle in content, needle


def test_exception_element_loads(adversarial):
    t = adversarial["only-exception"]
    assert t.spdx_id == "Only-Exception"
    assert "special exception" in t.content


def test_empty_text_compiles_and_never_matches(adversarial, tmp_path):
    # an empty template must not crash compilation nor claim any blob
    assert adversarial["empty-text"].content == ""
    corpus = spdx_corpus(ADVERSARIAL)
    assert corpus.n_templates == 6
    clf = BatchClassifier(corpus=corpus, pad_batch_to=16, mesh=None)
    results = clf.classify_blobs(
        [b"some unrelated prose that matches nothing at all"], threshold=60
    )
    assert results[0].key != "empty-text"


def test_adversarial_self_detection(adversarial):
    corpus = spdx_corpus(ADVERSARIAL)
    clf = BatchClassifier(corpus=corpus, pad_batch_to=16, mesh=None)
    todo = {k: t for k, t in adversarial.items() if t.content}
    results = clf.classify_blobs(
        [t.content for t in todo.values()], threshold=90
    )
    for t, r in zip(todo.values(), results):
        assert r.key == t.key, (t.key, r.key, r.confidence)
        assert r.confidence == 100.0


# -- the T≈600 north-star corpus --


@pytest.fixture(scope="module")
def scale(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("spdx600"))
    synth_spdx_dir(d, n_templates=600, seed=3)
    templates = load_spdx_dir(d)
    corpus = spdx_corpus(d)
    return templates, corpus


def test_scale_corpus_width(scale):
    templates, corpus = scale
    assert len(templates) == 600
    assert corpus.n_templates == 600


def test_scale_self_detection_and_confusion(scale):
    """Every template's own rendering must come back as itself at 100 —
    across 600 mutually-similar templates (the synthetics are ~92%-word
    copies of real ones, the hardest confusion regime)."""
    templates, corpus = scale
    clf = BatchClassifier(corpus=corpus, pad_batch_to=1024, mesh=None)
    results = clf.classify_blobs(
        [t.content for t in templates], threshold=90
    )
    misses = [
        (t.key, r.key, r.matcher, r.confidence)
        for t, r in zip(templates, results)
        if r.key != t.key or r.confidence != 100.0
    ]
    assert not misses, misses[:10]


def test_scale_noisy_blobs_still_separate(scale):
    """Rendered templates + copyright headers + trailing noise (the blob
    shape of BASELINE.md configs 2/3): across 600 mutually-similar
    templates no blob may match the WRONG one.  A short template may
    conservatively decline when the noise exceeds its length-delta
    window (license.rb:242-247 candidate filter — Ruby declines these
    too), so no-match is acceptable, a wrong key never is."""
    import numpy as np

    templates, corpus = scale
    clf = BatchClassifier(corpus=corpus, pad_batch_to=256, mesh=None)
    sample = templates[::5][:120]
    blobs = [
        f"Copyright (c) 20{i % 30:02d} Example Author {i}\n\n"
        + t.content
        + f"\n\nProject homepage: https://example.invalid/p{i}\n"
        for i, t in enumerate(sample)
    ]
    results = clf.classify_blobs(blobs, threshold=90)
    wrong = [
        (t.key, r.key, r.confidence)
        for t, r in zip(sample, results)
        if r.key is not None and r.key != t.key
    ]
    assert not wrong, wrong[:10]
    declined = [t for t, r in zip(sample, results) if r.key is None]
    # misses happen only via the length-delta candidate filter: the blob
    # length must actually fall outside the template's window
    lengths = np.asarray(corpus.length)
    for t in declined:
        k = list(corpus.keys).index(t.key)
        assert lengths[k] * 0.05 < 90, (t.key, int(lengths[k]))
    assert len(declined) <= len(sample) // 20


def test_ingester_survives_xml_garbage(tmp_path):
    """Random XML-ish garbage in a corpus dir must never crash the
    ingester — broken entries are skipped, valid ones load (the 600-file
    license-list zoo includes deprecated/malformed strays)."""
    import random

    rng = random.Random(7)
    frags = [
        "<", ">", "/", "&", "&amp;", "&#x0;", "<license", "licenseId=",
        '"x"', "<text>", "</text>", "<optional>", "</optional>",
        "<alt match='['>", "<!--", "-->", "<![CDATA[", "]]>", "\x00",
        "\xff", "<?xml", "?>", "<SPDXLicenseCollection>", "</license>",
        "utter garbage", "<p>", "</p>", "\n",
    ]
    d = tmp_path / "zoo"
    d.mkdir()
    for i in range(40):
        blob = "".join(rng.choice(frags) for _ in range(rng.randrange(2, 60)))
        (d / f"G{i}.xml").write_text(blob, encoding="utf-8", errors="ignore")
    # plant one valid file among the garbage
    import shutil

    from licensee_tpu import vendor_paths

    shutil.copy(
        os.path.join(vendor_paths.SPDX_DIR, "MIT.xml"), d / "MIT.xml"
    )
    templates = load_spdx_dir(str(d))
    keys = [t.key for t in templates]
    assert "mit" in keys  # the valid entry survives the zoo
