"""The durable jobs tier (PR 16): journal torn-tail replay, idempotent
duplicate submits, cancel-mid-run, crash-resume via journal replay, and
the HTTP jobs API end-to-end against a live stub fleet — all over stub
runners (the real StripeRunner path is drilled by ``fleet
--selftest-jobs`` and test_stripes.py)."""

import json
import os
import sys
import tempfile
import threading
import time

import pytest

from licensee_tpu.fleet.http_edge import HttpEdgeServer
from licensee_tpu.fleet.router import Router
from licensee_tpu.fleet.supervisor import Supervisor, worker_env
from licensee_tpu.jobs.client import JobsClient
from licensee_tpu.jobs.executor import (
    TERMINAL_STATES,
    JobExecutor,
    forward_args_for,
    validate_spec,
)
from licensee_tpu.jobs.journal import JobJournal, JournalError
from licensee_tpu.parallel.stripes import StripeStopped

TOKEN = "test-jobs-token"


# -- journal durability ------------------------------------------------


def _journal(tmpdir):
    return JobJournal(os.path.join(tmpdir, "journal.jsonl"))


def test_journal_roundtrip_in_order():
    with tempfile.TemporaryDirectory() as tmp:
        j = _journal(tmp)
        records = [
            {"rec": "submit", "job": "aa", "spec": {"stripes": 1}},
            {"rec": "state", "job": "aa", "state": "running"},
            {"rec": "state", "job": "aa", "state": "completed"},
        ]
        for r in records:
            j.append(r)
        j.close()
        assert _journal(tmp).replay() == records


def test_journal_survives_reopen_and_appends():
    with tempfile.TemporaryDirectory() as tmp:
        j = _journal(tmp)
        j.append({"rec": "submit", "job": "aa"})
        j.close()
        j2 = _journal(tmp)
        j2.append({"rec": "state", "job": "aa", "state": "running"})
        j2.close()
        assert [r["rec"] for r in _journal(tmp).replay()] == [
            "submit", "state",
        ]


def test_journal_torn_tail_without_newline_is_dropped():
    with tempfile.TemporaryDirectory() as tmp:
        j = _journal(tmp)
        j.append({"rec": "submit", "job": "aa"})
        j.append({"rec": "state", "job": "aa", "state": "running"})
        j.close()
        # a crash mid-append: the final line never got its newline
        with open(j.path, "ab") as f:
            f.write(b'{"rec":"state","job":"aa","sta')
        replay = _journal(tmp).replay()
        assert [r["rec"] for r in replay] == ["submit", "state"]


def test_journal_torn_final_line_with_newline_is_dropped():
    with tempfile.TemporaryDirectory() as tmp:
        j = _journal(tmp)
        j.append({"rec": "submit", "job": "aa"})
        j.close()
        # the newline page made it to disk but the line body is cut
        with open(j.path, "ab") as f:
            f.write(b'{"rec":"state","jo\n')
        replay = _journal(tmp).replay()
        assert [r["rec"] for r in replay] == ["submit"]


def test_journal_corrupt_mid_file_refuses():
    with tempfile.TemporaryDirectory() as tmp:
        j = _journal(tmp)
        j.append({"rec": "submit", "job": "aa"})
        with open(j.path, "ab") as f:
            f.write(b"not json\n")
        j.append({"rec": "state", "job": "aa", "state": "running"})
        j.close()
        with pytest.raises(JournalError):
            _journal(tmp).replay()


def test_journal_missing_file_replays_empty():
    with tempfile.TemporaryDirectory() as tmp:
        assert _journal(tmp).replay() == []


def test_journal_newline_in_values_stays_one_line():
    # json escapes control characters, so a newline INSIDE a value can
    # never tear the line framing — it must round-trip intact
    with tempfile.TemporaryDirectory() as tmp:
        j = _journal(tmp)
        j.append({"rec": "submit", "note": "a\nb"})
        j.close()
        (rec,) = _journal(tmp).replay()
        assert rec["note"] == "a\nb"


# -- spec validation ---------------------------------------------------


def test_validate_spec_normalizes():
    spec, reason = validate_spec({
        "manifest": ["  /a/b  ", "t.tar::*"],
        "stripes": 2,
        "options": {"batch_size": 16, "confidence": 1},
        "idempotency_key": "k1",
    })
    assert reason is None
    assert spec["manifest"] == ["/a/b", "t.tar::*"]
    assert spec["options"]["confidence"] == 1.0  # int -> float coercion
    assert forward_args_for(spec["options"]) == (
        "--batch-size", "16", "--confidence", "1.0",
    )


@pytest.mark.parametrize("bad,why", [
    ("nope", "object"),
    ({}, "manifest"),
    ({"manifest": []}, "manifest"),
    ({"manifest": ["a\nb"]}, "newline"),
    ({"manifest": ["a"], "stripes": 0}, "stripes"),
    ({"manifest": ["a"], "stripes": True}, "stripes"),
    ({"manifest": ["a"], "stripes": 999}, "stripes"),
    ({"manifest": ["a"], "options": {"argv": ["rm"]}}, "option"),
    ({"manifest": ["a"], "options": {"batch_size": "big"}}, "batch_size"),
    ({"manifest": ["a"], "idempotency_key": "x" * 300}, "idempotency"),
])
def test_validate_spec_refuses(bad, why):
    spec, reason = validate_spec(bad)
    assert spec is None
    assert why in reason


def test_validate_spec_probes_remote_entries():
    """A manifest naming a remote container gets a submit-time probe:
    reachable hosts pass, dead/range-less/git-over-HTTP ones land the
    400 reason at POST /jobs instead of a failed job minutes later."""
    import io
    import tarfile

    from licensee_tpu.ingest.loopback import LoopbackBlobHost

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        info = tarfile.TarInfo("LICENSE")
        info.size = 4
        tf.addfile(info, io.BytesIO(b"MIT\n"))
    with LoopbackBlobHost({"r.tar": buf.getvalue()}) as host:
        good = host.url("r.tar") + "::*"
        spec, reason = validate_spec({"manifest": [good, "/loose"]})
        assert reason is None and spec["manifest"][0] == good

        spec, reason = validate_spec(
            {"manifest": [host.url("gone.zip") + "::*"]}
        )
        assert spec is None and "gone.zip" in reason

        host.no_range = True
        spec, reason = validate_spec({"manifest": [good]})
        assert spec is None and "byte ranges" in reason

        spec, reason = validate_spec(
            {"manifest": [host.url("x.git") + "::HEAD"]}
        )
        assert spec is None and "tar/zip" in reason

    # the whole host is gone: connect refusal is a submit-time 400 too
    spec, reason = validate_spec({"manifest": [good]})
    assert spec is None and "probe" in reason


# -- stub runners ------------------------------------------------------


class _QuickRunner:
    """Completes instantly: one deterministic output row per manifest
    entry, plus the per-stripe stats artifact the status verb reads."""

    def __init__(self, job, on_progress):
        self.job = job
        self.cb = on_progress
        self._stop = False

    def request_stop(self):
        self._stop = True

    def run(self):
        self.cb("spawn", {"stripe": 0, "pid": os.getpid(), "first": True})
        if self._stop:
            raise StripeStopped("operator stop")
        with open(self.job.manifest_path, encoding="utf-8") as f:
            entries = [line.strip() for line in f if line.strip()]
        with open(self.job.output_path, "w", encoding="utf-8") as f:
            for e in entries:
                f.write(json.dumps({"path": e, "key": "mit"}) + "\n")
        with open(
            f"{self.job.output_path}.stats.json", "w", encoding="utf-8"
        ) as f:
            json.dump({"total": len(entries)}, f)
        self.cb("stripe_done", {"stripe": 0})
        return {
            "stripes": 1,
            "rows_written": len(entries),
            "elapsed_s": 0.01,
            "files_per_sec": 1.0,
            "already_complete": False,
        }


class _GateRunner(_QuickRunner):
    """Blocks mid-run on an event; ``request_stop`` (cancel, close)
    wakes it into StripeStopped — the resume-safe interruption."""

    def __init__(self, job, on_progress, gate, poison):
        super().__init__(job, on_progress)
        self.gate = gate
        self.poison = poison

    def request_stop(self):
        self._stop = True
        self.gate.set()

    def run(self):
        self.cb("spawn", {"stripe": 0, "pid": os.getpid(), "first": True})
        self.gate.wait(timeout=30.0)
        if self._stop or self.poison.is_set():
            raise StripeStopped("operator stop")
        return super().run()


def _wait_state(executor, job_id, states, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        row = executor.status(job_id)
        if row and row["state"] in states:
            return row
        time.sleep(0.01)
    raise AssertionError(
        f"job {job_id} never reached {states}: {executor.status(job_id)}"
    )


def _spec(entries, key=None):
    spec, reason = validate_spec({
        "manifest": list(entries),
        "stripes": 1,
        "idempotency_key": key,
    })
    assert reason is None, reason
    return spec


# -- executor lifecycle ------------------------------------------------


def test_executor_submit_runs_to_completed():
    with tempfile.TemporaryDirectory() as tmp:
        ex = JobExecutor(
            tmp, runner_factory=lambda j, cb: _QuickRunner(j, cb)
        )
        ex.start()
        try:
            job, created = ex.submit(_spec(["/a", "/b"], key="k1"))
            assert created
            row = _wait_state(ex, job.job_id, ("completed",))
            assert row["rows_written"] == 2
            assert row["files_classified"] == 2
            assert row["stripes_done"] == 1
            path = ex.results_path(job.job_id)
            assert path and os.path.exists(path)
            with open(path, encoding="utf-8") as f:
                assert len(f.readlines()) == 2
        finally:
            ex.close()


def test_executor_duplicate_key_returns_original_job():
    with tempfile.TemporaryDirectory() as tmp:
        ex = JobExecutor(
            tmp, runner_factory=lambda j, cb: _QuickRunner(j, cb)
        )
        ex.start()
        try:
            job, created = ex.submit(_spec(["/a"], key="dup"))
            twin, twin_created = ex.submit(_spec(["/a"], key="dup"))
            assert created and not twin_created
            assert twin.job_id == job.job_id
        finally:
            ex.close()


def test_executor_cancel_queued_job():
    gate, poison = threading.Event(), threading.Event()

    def factory(job, cb):
        # first job blocks the single runner slot; later jobs queue
        if job.spec.get("idempotency_key") == "blocker":
            return _GateRunner(job, cb, gate, poison)
        return _QuickRunner(job, cb)

    with tempfile.TemporaryDirectory() as tmp:
        ex = JobExecutor(tmp, max_concurrent=1, runner_factory=factory)
        ex.start()
        try:
            blocker, _ = ex.submit(_spec(["/a"], key="blocker"))
            _wait_state(ex, blocker.job_id, ("running",))
            queued, _ = ex.submit(_spec(["/b"], key="victim"))
            row = ex.cancel(queued.job_id)
            assert row["state"] == "cancelled"
            gate.set()
            _wait_state(ex, blocker.job_id, ("completed",))
            # the cancelled job never ran
            assert ex.status(queued.job_id)["state"] == "cancelled"
            assert ex.results_path(queued.job_id) is None
        finally:
            poison.set()
            gate.set()
            ex.close()


def test_executor_cancel_running_job_is_terminal_across_restart():
    gate, poison = threading.Event(), threading.Event()
    with tempfile.TemporaryDirectory() as tmp:
        ex = JobExecutor(
            tmp, runner_factory=lambda j, cb: _GateRunner(
                j, cb, gate, poison
            ),
        )
        ex.start()
        job, _ = ex.submit(_spec(["/a"], key="k1"))
        _wait_state(ex, job.job_id, ("running",))
        ex.cancel(job.job_id)
        row = _wait_state(ex, job.job_id, TERMINAL_STATES)
        assert row["state"] == "cancelled"
        ex.close()
        # a terminal job is NOT re-enqueued by replay
        ex2 = JobExecutor(
            tmp, runner_factory=lambda j, cb: _QuickRunner(j, cb)
        )
        ex2.start()
        try:
            assert ex2.status(job.job_id)["state"] == "cancelled"
            assert ex2.resumed_jobs == 0
        finally:
            ex2.close()


def test_executor_close_requeues_running_job_for_next_boot():
    gate, poison = threading.Event(), threading.Event()
    with tempfile.TemporaryDirectory() as tmp:
        ex = JobExecutor(
            tmp, runner_factory=lambda j, cb: _GateRunner(
                j, cb, gate, poison
            ),
        )
        ex.start()
        job, _ = ex.submit(_spec(["/a", "/b"], key="k1"))
        _wait_state(ex, job.job_id, ("running",))
        ex.close()  # drains: request_stop -> StripeStopped -> queued
        ex2 = JobExecutor(
            tmp, runner_factory=lambda j, cb: _QuickRunner(j, cb)
        )
        ex2.start()
        try:
            row = _wait_state(ex2, job.job_id, ("completed",))
            assert row["rows_written"] == 2
        finally:
            ex2.close()


def test_executor_sigkill_replay_resumes_and_output_matches():
    """The crash contract, simulated in-process: executor A dies with
    the journal saying "running" (no close, no requeue record); B's
    replay must resume the job and the output must be byte-identical
    to an uninterrupted run of the same spec."""
    gate, poison = threading.Event(), threading.Event()
    entries = ["/a", "/b", "/c"]
    with tempfile.TemporaryDirectory() as tmp_ref:
        ref_ex = JobExecutor(
            tmp_ref, runner_factory=lambda j, cb: _QuickRunner(j, cb)
        )
        ref_ex.start()
        ref_job, _ = ref_ex.submit(_spec(entries, key="k1"))
        _wait_state(ref_ex, ref_job.job_id, ("completed",))
        with open(ref_ex.results_path(ref_job.job_id), "rb") as f:
            ref_bytes = f.read()
        ref_ex.close()
    with tempfile.TemporaryDirectory() as tmp:
        ex_a = JobExecutor(
            tmp, runner_factory=lambda j, cb: _GateRunner(
                j, cb, gate, poison
            ),
        )
        ex_a.start()
        job, _ = ex_a.submit(_spec(entries, key="k1"))
        _wait_state(ex_a, job.job_id, ("running",))
        # "SIGKILL": abandon A mid-run — journal last record: running
        ex_b = JobExecutor(
            tmp, runner_factory=lambda j, cb: _QuickRunner(j, cb)
        )
        ex_b.start()
        try:
            assert ex_b.resumed_jobs == 1
            row = _wait_state(ex_b, job.job_id, ("completed",))
            assert row["resumed"] is True
            with open(ex_b.results_path(job.job_id), "rb") as f:
                assert f.read() == ref_bytes
            # the idempotency key replayed too
            twin, created = ex_b.submit(_spec(entries, key="k1"))
            assert not created and twin.job_id == job.job_id
        finally:
            ex_b.close()
            poison.set()
            gate.set()
            # join A's abandoned worker thread before the tempdir goes:
            # its StripeStopped unwind still appends a requeue record
            ex_a.close()


def test_executor_failed_runner_lands_failed_with_error():
    class _Boom(_QuickRunner):
        def run(self):
            raise ValueError("manifest exploded")

    with tempfile.TemporaryDirectory() as tmp:
        ex = JobExecutor(tmp, runner_factory=lambda j, cb: _Boom(j, cb))
        ex.start()
        try:
            job, _ = ex.submit(_spec(["/a"]))
            row = _wait_state(ex, job.job_id, TERMINAL_STATES)
            assert row["state"] == "failed"
            assert "manifest exploded" in row["error"]
            assert ex.results_path(job.job_id) is None
        finally:
            ex.close()


def test_executor_save_upload_is_content_addressed():
    with tempfile.TemporaryDirectory() as tmp:
        ex = JobExecutor(tmp, runner_factory=lambda j, cb: None)
        p1 = ex.save_upload("x.tar", b"same bytes")
        p2 = ex.save_upload("../evil/x.tar", b"same bytes")
        p3 = ex.save_upload("x.tar", b"other bytes")
        assert p1 == p2  # content-addressed, path traversal stripped
        assert p1 != p3
        assert os.path.dirname(p1) == os.path.join(tmp, "uploads")
        with open(p1, "rb") as f:
            assert f.read() == b"same bytes"
        ex.journal.close()


# -- the HTTP jobs API against a live stub fleet -----------------------


def _stub_argv(name, sock):
    return [
        sys.executable, "-m", "licensee_tpu.fleet.faults",
        "--socket", sock, "--name", name, "--service-ms", "1",
    ]


class _JobsFleet:
    """Stub fleet + router + HTTP edge + a stub-runner JobExecutor."""

    def __init__(self, runner_factory=None, jobs=True):
        self.tmp = tempfile.mkdtemp(prefix="licensee-jobs-test-")
        sockets = {"w0": os.path.join(self.tmp, "w0.sock")}
        self.supervisor = Supervisor(
            sockets, argv_for=_stub_argv,
            env_for=lambda name, chips: worker_env(None, None),
            probe_interval_s=0.1, backoff_base_s=0.1, backoff_max_s=1.0,
        )
        self.supervisor.start()
        assert self.supervisor.wait_healthy(30.0)
        self.router = Router(
            sockets, supervisor=self.supervisor,
            probe_interval_s=0.1, request_timeout_s=10.0,
            dispatch_wait_s=5.0, trace_sample=1.0,
        )
        self.router.start()
        self.executor = None
        if jobs:
            factory = runner_factory or (
                lambda j, cb: _QuickRunner(j, cb)
            )
            self.executor = JobExecutor(
                os.path.join(self.tmp, "jobs"),
                max_concurrent=1,
                registry=self.router.obs.registry,
                runner_factory=factory,
            )
            self.executor.start()
            self.router.collector.add_source(
                "jobs", self.executor.trace_tail
            )
        self.edge = HttpEdgeServer(
            "127.0.0.1:0", self.router,
            tokens={TOKEN: "tester"}, rate_per_client=10000.0,
            stall_timeout_s=1.0, jobs=self.executor,
        )
        self.port = self.edge.bound_port
        self.thread = threading.Thread(
            target=self.edge.serve_forever,
            kwargs={"poll_interval": 0.05}, daemon=True,
        )
        self.thread.start()

    def client(self, token=TOKEN):
        return JobsClient(f"127.0.0.1:{self.port}", token=token)

    def close(self):
        self.edge.shutdown()
        self.edge.server_close()
        self.thread.join(timeout=5.0)
        if self.executor is not None:
            self.executor.close()
        self.router.close()
        self.supervisor.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def test_edge_jobs_api_full_flow():
    with _JobsFleet() as fleet:
        client = fleet.client()
        try:
            code, row = client.submit({
                "manifest": ["/a", "/b"],
                "stripes": 1,
                "idempotency_key": "flow",
            })
            assert code == 202 and row["state"] == "queued"
            assert not row["duplicate"]
            job_id = row["job_id"]
            assert row.get("trace")  # the edge minted a submit trace
            final = client.wait(job_id, timeout_s=15.0)
            assert final["state"] == "completed"
            assert final["rows_written"] == 2
            # duplicate POST, same key: the ORIGINAL id, 200 not 202
            code, dup = client.submit({
                "manifest": ["/a", "/b"],
                "stripes": 1,
                "idempotency_key": "flow",
            })
            assert code == 200 and dup["job_id"] == job_id
            assert dup["duplicate"]
            code, payload = client.results(job_id)
            assert code == 200
            rows = [json.loads(l) for l in payload.splitlines()]
            assert [r["path"] for r in rows] == ["/a", "/b"]
            # no container sidecar for a loose-path job: empty 200
            code, payload = client.containers(job_id)
            assert code == 200 and payload == b""
        finally:
            client.close()


def test_edge_jobs_error_codes():
    gate, poison = threading.Event(), threading.Event()

    def factory(job, cb):
        return _GateRunner(job, cb, gate, poison)

    with _JobsFleet(runner_factory=factory) as fleet:
        client = fleet.client()
        try:
            # unknown id -> 404 job_not_found
            code, row = client.status("deadbeefdead")
            assert code == 404 and row["error"].startswith("job_not_found")
            # an id that is not lowercase hex never reaches the jobs
            # tier: unknown route -> 404
            code, _hdrs, _body = client.request("GET", "/jobs/NOPE!")
            assert code == 404
            # malformed body -> 400 bad_request
            code, _hdrs, body = client.request(
                "POST", "/jobs", b"{nope"
            )
            assert code == 400
            assert json.loads(body)["error"].startswith("bad_request")
            # a valid submit against the gated runner...
            code, row = client.submit({"manifest": ["/a"], "stripes": 1})
            assert code == 202
            job_id = row["job_id"]
            # ...results before completion -> 409 job_not_done
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                code, srow = client.status(job_id)
                if srow.get("state") == "running":
                    break
                time.sleep(0.01)
            code, payload = client.results(job_id)
            assert code == 409
            assert json.loads(payload)["error"].startswith("job_not_done")
            # cancel -> 202, terminal state cancelled
            code, row = client.cancel(job_id)
            assert code == 202
            final = client.wait(job_id, timeout_s=15.0)
            assert final["state"] == "cancelled"
            # wrong bearer token -> 401 before any jobs logic
            bad = fleet.client(token="wrong")
            try:
                code, _row = bad.submit({"manifest": ["/a"]})
                assert code == 401
            finally:
                bad.close()
        finally:
            poison.set()
            gate.set()
            client.close()


def test_edge_jobs_disabled_answers_503():
    with _JobsFleet(jobs=False) as fleet:
        client = fleet.client()
        try:
            code, row = client.submit({"manifest": ["/a"]})
            assert code == 503
            assert row["error"].startswith("jobs_disabled")
        finally:
            client.close()


def test_edge_job_archive_upload_submit():
    import base64
    import io
    import tarfile

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        data = b"MIT License\n"
        info = tarfile.TarInfo(name="pkg/LICENSE")
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))
    with _JobsFleet() as fleet:
        client = fleet.client()
        try:
            code, row = client.submit({
                "archive_b64": base64.b64encode(buf.getvalue()).decode(),
                "archive_name": "up.tar",
                "stripes": 1,
            })
            assert code == 202, row
            final = client.wait(row["job_id"], timeout_s=15.0)
            assert final["state"] == "completed"
            # the staged upload became the job's one manifest entry
            job = fleet.executor.job(row["job_id"])
            (entry,) = job.spec["manifest"]
            assert entry.endswith("-up.tar::*")
            assert os.path.exists(entry.split("::", 1)[0])
        finally:
            client.close()


def test_edge_jobs_metrics_ride_the_fleet_exposition():
    with _JobsFleet() as fleet:
        client = fleet.client()
        try:
            code, row = client.submit({"manifest": ["/a"], "stripes": 1})
            assert code == 202
            client.wait(row["job_id"], timeout_s=15.0)
        finally:
            client.close()
        # the fleet exposition injects worker="router" onto the
        # router-registry series the executor registered into
        import re

        exposition = fleet.router.prometheus()
        for series in ("jobs_submitted_total", "jobs_completed_total"):
            assert re.search(
                rf'{series}\{{[^}}]*\}} 1(\.0)?$', exposition, re.M
            ), f"{series} missing from the fleet exposition"
        assert "jobs_queue_depth" in exposition
