"""Agreement harness: the DiceXLA batch kernel must reproduce the scalar
reference-semantics path — same top-1 key, same float64 score — on every
fixture, every rendered template, and mutation variants (the ≥99.9%
agreement contract of BASELINE.md, held here at 100%)."""

import os

import numpy as np
import pytest

from licensee_tpu.corpus.license import License
from licensee_tpu.kernels.batch import BatchClassifier, NormalizedBlob
from licensee_tpu.matchers import Dice
from licensee_tpu.project_files.license_file import LicenseFile
from tests.conftest import FIXTURES_DIR, fixture_path, sub_copyright_info


@pytest.fixture(scope="module")
def classifier():
    return BatchClassifier(pad_batch_to=64)


def scalar_result(content):
    file = LicenseFile(content, "LICENSE")
    matcher = Dice(file)
    match = matcher.match
    return (match.key if match else None, matcher.confidence if match else 0)


def collect_fixture_license_files():
    contents = []
    for name in sorted(os.listdir(FIXTURES_DIR)):
        dir_path = os.path.join(FIXTURES_DIR, name)
        if not os.path.isdir(dir_path):
            continue
        for fname in sorted(os.listdir(dir_path)):
            full = os.path.join(dir_path, fname)
            if LicenseFile.name_score(fname) > 0 and os.path.isfile(full):
                with open(full, "rb") as f:
                    contents.append(f.read())
    return contents


def test_agreement_on_fixture_license_files(classifier):
    contents = collect_fixture_license_files()
    assert len(contents) > 50
    batch = classifier.classify_blobs(contents)
    for content, result in zip(contents, batch):
        if result.matcher == "dice" or result.matcher is None:
            key, confidence = scalar_result(content)
            assert result.key == key, content[:80]
            if result.key is not None:
                assert result.confidence == confidence  # bit-exact float64
        elif result.matcher == "exact":
            # exact prefilter must agree with the scalar Exact matcher
            file = LicenseFile(content, "LICENSE")
            from licensee_tpu.matchers import Exact

            assert Exact(file).match.key == result.key


def test_agreement_on_rendered_templates(classifier):
    licenses = License.all(hidden=True, pseudo=False)
    contents = [sub_copyright_info(lic) for lic in licenses]
    batch = classifier.classify_blobs(contents)
    for lic, content, result in zip(licenses, contents, batch):
        assert result.key == lic.key, lic.key
        if result.matcher == "dice":
            key, confidence = scalar_result(content)
            assert (result.key, result.confidence) == (key, confidence)


def test_agreement_on_mutations(classifier):
    from licensee_tpu.normalize.pipeline import wrap
    from tests.test_vendored_licenses import add_random_words

    contents = []
    for lic in License.all(hidden=True, pseudo=False)[:12]:
        rendered = sub_copyright_info(lic)
        contents.append(wrap(rendered, 60))
        contents.append(add_random_words(rendered, 75, seed=42))
        contents.append(rendered + "\n\nExtra trailing paragraph of text.")
    batch = classifier.classify_blobs(contents)
    for content, result in zip(contents, batch):
        # full matcher-chain comparison (Copyright -> Exact -> Dice), same
        # first-match-wins semantics as license_file.rb:67-69
        file = LicenseFile(content, "LICENSE")
        matcher = file.matcher
        if matcher is None:
            assert result.key is None
        else:
            assert result.matcher == matcher.name
            assert result.key == matcher.match.key
            assert result.confidence == matcher.confidence


def test_copyright_prefilter(classifier):
    # a pure copyright statement (matchers/copyright.rb:12-17); note that an
    # "All rights reserved" line is NOT part of the matcher regex
    results = classifier.classify_blobs(
        ["Copyright (c) 2024 Example Author", "Copyright 2024 Example\n(c) Example"]
    )
    for result in results:
        assert result.key == "no-license"
        assert result.matcher == "copyright"


def test_cc_false_positive_guard_in_batch(classifier):
    with open(fixture_path("cc-by-nd/LICENSE"), "rb") as f:
        content = f.read()
    results = classifier.classify_blobs([content])
    assert results[0].key is None


def test_matmul_method_agrees(classifier):
    mm = BatchClassifier(method="matmul", pad_batch_to=64)
    contents = collect_fixture_license_files()[:40]
    a = classifier.classify_blobs(contents)
    b = mm.classify_blobs(contents)
    for ra, rb in zip(a, b):
        assert (ra.key, ra.confidence) == (rb.key, rb.confidence)


def test_dice_xla_matcher_plugin():
    from licensee_tpu.matchers.dice_xla_matcher import DiceXLA

    gpl = License.find("gpl-3.0")
    file = LicenseFile(sub_copyright_info(gpl), "LICENSE.txt")
    matcher = DiceXLA(file)
    assert matcher.match == gpl
    assert matcher.confidence == 100.0


def test_exact_proof_rejects_oov_word_swap(classifier):
    """The Exact prefilter must not answer 'exact' for a blob whose
    in-vocab projection and word count match a template but whose actual
    wordset differs (the engineered-hash-collision shape): the compiler
    vocab covers every template's full wordset, so equality of (bits,
    count) IS set equality — verify both directions."""
    if classifier._nat is None:
        pytest.skip("native pipeline unavailable")
    corpus = classifier.corpus
    # every template's full wordset must be inside the vocab (the proof's
    # precondition)
    for wordset in corpus.exact_sets:
        missing = [w for w in wordset if w not in corpus.vocab]
        assert not missing, missing[:5]
    # and the stored projections popcount back to the full word count
    for h, (tpl_bits, tpl_count, key) in classifier._exact_feats.items():
        popc = int(np.unpackbits(tpl_bits.view(np.uint8)).sum())
        assert popc == tpl_count, key

    # same count, one word swapped for an out-of-vocab word: even if an
    # attacker matched the additive hash, the bits/count proof fails
    mit = dict(zip(corpus.keys, range(len(corpus.keys))))
    lic = {l.key: l for l in License.all(hidden=True, pseudo=False)}["mit"]
    words = sorted(lic.wordset)
    swapped = set(words[1:]) | {"zzzunvocabword"}
    fake_h = classifier._nat.exact_hash(lic.wordset)
    blob = NormalizedBlob(" ".join(sorted(swapped)))
    bits, nw, _ln = corpus.file_features(blob)
    assert nw == len(lic.wordset)  # same cardinality as the template
    assert classifier._confirm_exact(fake_h, bits, nw) is None
