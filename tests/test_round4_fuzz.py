"""Seeded differential fuzz over the round-4 surfaces.

auto-mode routing must equal the corresponding fixed-mode classifier for
every (filename, content) pair, and batch attribution must equal the
scalar LicenseFile path — across randomized filenames, license bodies,
noise documents, copyright lines, and README shapes.
"""

from __future__ import annotations

import random
import re

import pytest

from licensee_tpu.corpus.license import License
from licensee_tpu.kernels.batch import BatchClassifier


@pytest.fixture(scope="module")
def clfs():
    return {
        "auto": BatchClassifier(pad_batch_to=32, mesh=None, mode="auto"),
        "license": BatchClassifier(pad_batch_to=32, mesh=None),
        "readme": BatchClassifier(pad_batch_to=32, mesh=None, mode="readme"),
        "package": BatchClassifier(mode="package"),
    }


def _random_cases(rng: random.Random, n: int):
    licenses = License.all(hidden=True, pseudo=False)
    bodies = [
        re.sub(r"\[(\w+)\]", "example", lic.content or "")
        for lic in licenses[:12]
    ]
    filenames = [
        "LICENSE", "LICENSE.md", "COPYING", "license.txt", "LICENSE-MIT",
        "MIT-LICENSE", "COPYRIGHT", "PATENTS", "UNLICENSE",
        "README", "README.md", "README.rst", "readme.txt",
        "package.json", "bower.json", "Cargo.toml", "DESCRIPTION",
        "dist.ini", "LICENSE.spdx", "proj.gemspec", "lib.cabal",
        "x.nuspec", "main.c", "setup.py", "notes.md", "index.html",
        "Makefile", "LICENSE.html", "readme.html", "",
    ]
    noise = [
        "just some prose\n", "int main(void) { return 0; }\n",
        '{"license": "MIT"}\n', '{"license": "Zlib"}\n',
        '[package]\nlicense = "ISC"\n',
        "Package: x\nLicense: GPL-3\n",
        "Copyright (c) 2020 Someone Somewhere\n",
    ]
    cases = []
    for _ in range(n):
        filename = rng.choice(filenames)
        kind = rng.randrange(5)
        if kind == 0:
            content = rng.choice(bodies)
        elif kind == 1:
            hdr = f"Copyright (c) {rng.randrange(1980, 2030)} Fuzz Co\n\n"
            content = hdr + rng.choice(bodies)
        elif kind == 2:
            content = (
                f"# Project\n\n## License\n\n{rng.choice(bodies)}"
                if rng.random() < 0.5
                else "# Project\n\n## License\n\nMIT License.\n"
            )
        elif kind == 3:
            content = rng.choice(noise)
        else:
            content = rng.choice(bodies)[: rng.randrange(10, 400)]
        cases.append((filename, content.encode()))
    return cases


def test_auto_routing_agrees_with_fixed_modes(clfs):
    rng = random.Random(20260730)
    cases = _random_cases(rng, 120)
    got = clfs["auto"].classify_blobs(
        [c for _, c in cases], filenames=[f for f, _ in cases]
    )
    for (filename, content), g in zip(cases, got):
        route = BatchClassifier.route_for(filename)
        if route is None:
            assert (g.key, g.matcher, g.confidence) == (None, None, 0.0), (
                filename
            )
            continue
        w = clfs[route].classify_blobs([content], filenames=[filename])[0]
        assert (g.key, g.matcher, g.confidence) == (
            w.key,
            w.matcher,
            w.confidence,
        ), (filename, route)


def test_attribution_agrees_with_scalar(clfs):
    from licensee_tpu.project_files.license_file import LicenseFile

    rng = random.Random(4)
    clf = clfs["license"]
    cases = [
        (f, c)
        for f, c in _random_cases(rng, 240)
        if BatchClassifier.route_for(f) == "license"
    ]
    results = clf.classify_blobs(
        [c for _, c in cases], filenames=[f for f, _ in cases]
    )
    checked = 0
    for (filename, content), r in zip(cases, results):
        if r.error:
            continue
        got = clf.attribution_for(content, filename, r)
        want = LicenseFile(content, filename).attribution
        assert got == want, filename
        checked += 1
    assert checked >= 50  # the fuzz actually exercised the comparison
