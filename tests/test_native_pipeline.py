"""Differential tests: the whole-pipeline native path (native/pipeline.cpp)
must be byte-identical to the Python normalization pipeline on every
fixture, every rendered vendored template, and adversarial inputs.

The native path is PCRE2 + hand-coded scanners; the Python path is the
re-module pipeline (which itself is pinned to the Ruby reference by the
SHA1 golden corpus in tests/test_normalize_hashes.py).  Equality here
therefore chains the native path to the Ruby goldens.
"""

import glob
import os

import numpy as np
import pytest

from licensee_tpu.rubytext import ruby_strip


def _native():
    try:
        from licensee_tpu.native import pipeline as npipe

        return npipe.load()
    except Exception:
        return None


nat = _native()
pytestmark = pytest.mark.skipif(
    nat is None, reason="native pipeline unavailable (no toolchain/libpcre2)"
)

from licensee_tpu.kernels.batch import NormalizedBlob  # noqa: E402
from tests.conftest import FIXTURES_DIR  # noqa: E402


def _fixture_files():
    out = []
    for d in sorted(glob.glob(os.path.join(FIXTURES_DIR, "*"))):
        if os.path.isdir(d):
            for f in sorted(glob.glob(os.path.join(d, "*"))):
                if os.path.isfile(f):
                    out.append(f)
    return out


ADVERSARIAL = [
    b"",
    b"\xef\xbb\xbfMIT License",
    ("a b c d e f g h " * 2000).encode(),  # 1-char-token table growth
    "licença ática—«q» d'été's ’s".encode(),
    b"Copyright (c) 2024 Example\nAll rights reserved.",
    b"http://example.com & http://other.example\n\n- item one\n\n- item two",
    b"== Title ==\n*emphasis* [link](http://x) `code`\n> quoted\n\nEnd of terms and conditions",
    b"word-\ncontinued hyphen-\n  ated licence favour organisation",
    # a stage-2 substitution (span_markup) leaves a double space before
    # the cc-dedication contains-gate: the gate must see SQUEEZED text
    # (plain_strip repairs whitespace even on no-match; a literal gate
    # that skips the pass must preserve that side effect)
    b"the text of the creative * commons* public domain dedication.\n"
    b"permission is hereby granted, free of charge.\n",
    b"s's' apostrophe *x  y* edge's cases'",
]


@pytest.fixture(scope="module")
def vocab():
    from licensee_tpu.corpus.compiler import default_corpus

    corpus = default_corpus()
    return corpus, nat.vocab(list(corpus.vocab.keys()), corpus.n_lanes)


def _cases():
    cases = [(p, open(p, "rb").read()) for p in _fixture_files()]
    import re

    from licensee_tpu.corpus.license import License

    for lic in License.all(hidden=True, pseudo=False):
        rendered = re.sub(r"\[(\w+)\]", "example", lic.content or "")
        cases.append((f"template:{lic.key}", rendered.encode()))
    cases += [(f"adversarial:{i}", raw) for i, raw in enumerate(ADVERSARIAL)]
    return cases


@pytest.mark.parametrize("name,raw", _cases(), ids=[c[0] for c in _cases()])
def test_native_pipeline_matches_python(name, raw, vocab):
    corpus, vh = vocab
    blob = NormalizedBlob(raw)
    stripped = ruby_strip(blob.content or "")

    s1, flags = nat.stage1(stripped)
    assert s1 == blob.content_without_title_and_version

    assert nat.stage2(s1.lower()) == blob.content_normalized()

    bits, n_words, length, h = nat.featurize(vh, s1.lower())
    py_bits, py_nw, py_len = corpus.file_features(blob)
    assert np.array_equal(bits, py_bits)
    assert n_words == len(blob.wordset or ())
    assert length == blob.length

    # the one-crossing ASCII fast path must agree with the two-crossing path
    fast = nat.featurize_raw(vh, stripped)
    if fast is not None:
        fbits, fnw, flen, fflags, fh = fast
        assert np.array_equal(fbits, bits)
        assert (fnw, flen, fh) == (n_words, length, h)
        assert fflags == flags

    # prefilter flags == the Python regexes
    from licensee_tpu.normalize.pipeline import COPYRIGHT_FULL_REGEX
    from licensee_tpu.project_files.license_file import CC_FALSE_POSITIVE_REGEX

    py_flags = (1 if COPYRIGHT_FULL_REGEX.search(stripped) else 0) | (
        2 if CC_FALSE_POSITIVE_REGEX.search(stripped) else 0
    )
    assert flags == py_flags

    # wordset multiset-hash round trip (the Exact prefilter oracle)
    if blob.wordset is not None:
        assert h == nat.exact_hash(blob.wordset)


def test_exact_hash_order_independent():
    a = nat.exact_hash(["alpha", "beta", "gamma"])
    b = nat.exact_hash(["gamma", "alpha", "beta"])
    assert a == b
    assert nat.exact_hash(["alpha", "beta"]) != a


def test_classifier_native_matches_python_fallback(monkeypatch):
    """BatchClassifier must classify identically with and without the
    native whole-pipeline path."""
    import re

    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels import batch as batch_mod

    contents = []
    for i, lic in enumerate(License.all(hidden=True, pseudo=False)[:12]):
        text = re.sub(r"\[(\w+)\]", "example", lic.content or "")
        if i % 3 == 0:
            text += f"\nnoise words {i} here"
        contents.append(text.encode())
    contents.append(b"Copyright (c) 2020 Nobody")
    contents.append("licença não detectável".encode())

    native_clf = batch_mod.BatchClassifier(pad_batch_to=8)
    assert native_clf._nat is not None
    native_results = native_clf.classify_blobs(contents)

    from licensee_tpu.native import pipeline as npipe_mod

    monkeypatch.setattr(npipe_mod, "_instance", None)
    monkeypatch.setattr(npipe_mod, "_failed", True)  # force the fallback
    py_clf = batch_mod.BatchClassifier(pad_batch_to=8)
    assert py_clf._nat is None
    py_results = py_clf.classify_blobs(contents)

    for n, p in zip(native_results, py_results):
        assert (n.key, n.matcher) == (p.key, p.matcher)
        assert n.confidence == pytest.approx(p.confidence, abs=0)


def test_resource_limit_fails_over_to_python(monkeypatch):
    """A PCRE2 resource-limit failure on one blob must NOT produce an
    error row or a silent no-match: the blob re-runs on the pure-Python
    pipeline (which has no such limits) and classifies normally."""
    import re

    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels import batch as batch_mod
    from licensee_tpu.native.pipeline import NativeResourceError

    clf = batch_mod.BatchClassifier(pad_batch_to=4)
    if clf._nat is None:
        pytest.skip("native pipeline unavailable")

    mit = next(
        lic for lic in License.all(hidden=True, pseudo=False)
        if lic.key == "mit"
    )
    text = re.sub(r"\[(\w+)\]", "example", mit.content or "").encode()

    # the BATCH crossing reports a resource failure (status 3) for blob 0
    # -> the per-blob native path retries it, pretends to hit MATCHLIMIT
    # again -> the pure-Python path classifies it; blob 1 stays native
    real_batch = clf._nat.featurize_batch

    def flaky_batch(vocab, contents, *args, **kwargs):
        status = real_batch(vocab, contents, *args, **kwargs)
        if len(status):
            status[0] = 3
        return status

    monkeypatch.setattr(clf._nat, "featurize_batch", flaky_batch)

    calls = {"n": 0}

    def flaky_one(raw, *args, **kwargs):
        calls["n"] += 1
        raise NativeResourceError("pipe_featurize_raw: PCRE2 resource limit")

    monkeypatch.setattr(clf, "_prepare_one_native", flaky_one)
    results = clf.classify_blobs([text, text])
    assert calls["n"] == 1  # only the status-3 blob reaches the scalar path
    for r in results:
        assert r.error is None
        assert (r.key, r.matcher) == ("mit", "exact")


def test_profile_dump_stage_counters_and_gated_passes():
    """profile_dump always reports the stage.*/count.* attribution rows
    (cheap relaxed counters); the fine-grained per-pass rows require
    LICENSEE_TPU_PIPE_PROFILE at process start — exercised by a
    subprocess so this process stays clean."""
    import json
    import subprocess
    import sys

    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(pad_batch_to=8, mesh=None)
    if clf._nat is None:
        pytest.skip("native pipeline unavailable")
    before = clf._nat.profile_dump()
    clf.classify_blobs([b"some words to featurize"])
    prof = clf._nat.profile_dump()
    # always-on stage counters, no env flag required
    assert {
        "stage.normalize_s",
        "stage.wordset_s",
        "stage.pack_s",
        "count.blobs",
        "count.tokens",
        "count.unique",
        "count.oov",
        "count.bytes_in",
        "count.nonascii_fallback",
    } <= set(prof)
    assert prof["count.blobs"] >= before.get("count.blobs", 0) + 1
    assert prof["count.tokens"] >= prof["count.unique"]
    # the env-gated per-pass rows must NOT appear without the flag
    assert not any(k.startswith(("s1.", "s2.", "stage1", "stage2"))
                   for k in prof)

    code = (
        "import json\n"
        "from licensee_tpu.kernels.batch import BatchClassifier\n"
        "clf = BatchClassifier(pad_batch_to=8, mesh=None)\n"
        "clf.classify_blobs([b'some words to featurize here'])\n"
        "print(json.dumps(clf._nat.profile_dump()))\n"
    )
    result = subprocess.run(
        [sys.executable, "-c", code],
        env={
            **os.environ,
            "LICENSEE_TPU_PIPE_PROFILE": "1",
            "JAX_PLATFORMS": "cpu",
        },
        capture_output=True,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert result.returncode == 0, result.stderr[-2000:]
    prof = json.loads(result.stdout.strip().splitlines()[-1])
    assert {"stage1", "stage2", "stage.tokenize_only"} <= set(prof)
    assert all(v >= 0 for v in prof.values())


def test_differential_fuzz_native_vs_python():
    """Seeded random documents mixing everything the normalization
    pipeline reacts to (markdown, bullets, quotes/dashes, varietal
    words, copyright lines, CRLF, unicode, apostrophes): the native and
    pure-Python pipelines must agree bit-for-bit on every one."""
    import random

    from licensee_tpu.kernels.batch import BatchClassifier

    rng = random.Random(1234)
    vocab_words = [
        "software", "permission", "copyright", "licence", "organisation",
        "merge", "publish", "distribute", "sublicense", "warranty",
        "noninfringement", "s's'", "don't", "e-mail", "sub-license",
        "per cent", "favour", "whilst", "copyright owner",
    ]
    decorations = [
        "## License\n", "== Title ==\n", "* ", "- ", "1. ", "a) ",
        "> quoted\n", "*emphasis* ", "_under_ ", "`code` ",
        "[link](http://x.invalid) ", "http://example.invalid/x\n",
        "---\n", "“curly” ‘quotes’ ", "— em – en - dash ",
        "Copyright (c) 2024 Example\n", "All rights reserved.\n",
        "\r\n", "﻿", "   ", "\t", "licença ática ",
        "END OF TERMS AND CONDITIONS\n",
    ]

    def random_doc() -> str:
        parts = []
        for _ in range(rng.randrange(5, 60)):
            if rng.random() < 0.35:
                parts.append(rng.choice(decorations))
            else:
                parts.append(rng.choice(vocab_words) + " ")
            if rng.random() < 0.15:
                parts.append("\n\n")
        return "".join(parts)

    docs = [random_doc().encode("utf-8") for _ in range(100)]

    native_clf = BatchClassifier(pad_batch_to=128, mesh=None)
    if native_clf._nat is None:
        pytest.skip("native pipeline unavailable")
    py_clf = BatchClassifier(pad_batch_to=128, mesh=None)
    py_clf._nat = None  # force the pure-Python pipeline

    a = native_clf.classify_blobs(docs)
    b = py_clf.classify_blobs(docs)
    for i, (x, y) in enumerate(zip(a, b)):
        assert (x.key, x.matcher, x.confidence) == (
            y.key,
            y.matcher,
            y.confidence,
        ), (i, docs[i][:120])

    # feature-level agreement too (bits/wordset/length drive everything)
    pa = native_clf.prepare_batch(docs)
    pb = py_clf.prepare_batch(docs)
    np.testing.assert_array_equal(pa.bits, pb.bits)
    np.testing.assert_array_equal(pa.n_words, pb.n_words)
    np.testing.assert_array_equal(pa.lengths, pb.lengths)
    np.testing.assert_array_equal(pa.cc_fp, pb.cc_fp)
