"""Clean: alert transitions land in the ring as plain slot stores;
paging (socket I/O under a lock) lives on the flusher, where a slow
pager can stall nothing but itself."""

import time


class DeferredAlertRecorder:
    def __init__(self, sock, lock, capacity=64):
        self._sock = sock
        self._lock = lock
        self._slots = [None] * capacity
        self._capacity = capacity
        self._seq = 0

    def record(self, kind, **fields):
        seq = self._seq
        self._slots[seq % self._capacity] = (
            seq, time.perf_counter(), kind, fields
        )
        self._seq = seq + 1

    def flush_alerts(self):
        firing = [
            e for e in list(self._slots)
            if e is not None and e[2] == "alert_firing"
        ]
        with self._lock:
            for event in sorted(firing):
                self._sock.sendall(repr(event).encode())
