"""Seeded TP: the hot append path does file I/O per event — every
request now pays a syscall (and a full disk blocks serving)."""

import os
import time


class BadFlightRecorder:
    def __init__(self, path):
        self.path = path
        self._events = []

    def record(self, kind, **fields):
        self._events.append((time.perf_counter(), kind, fields))
        with open(self.path, "a", encoding="utf-8") as f:  # BAD
            f.write(repr(fields) + "\n")  # BAD
        os.replace(self.path, self.path)  # BAD
