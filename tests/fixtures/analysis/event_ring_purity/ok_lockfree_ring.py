"""Clean: a lock-free bounded ring whose append is one slot store;
the dump (file I/O) lives OUTSIDE the hot path."""

import time


class RingFlightRecorder:
    def __init__(self, capacity=64):
        self._slots = [None] * capacity
        self._capacity = capacity
        self._seq = 0

    def record(self, kind, **fields):
        seq = self._seq
        self._slots[seq % self._capacity] = (
            seq, time.perf_counter(), kind, fields
        )
        self._seq = seq + 1

    def dump(self, path):
        events = [e for e in list(self._slots) if e is not None]
        with open(path, "w", encoding="utf-8") as f:
            f.write(repr(sorted(events)))
