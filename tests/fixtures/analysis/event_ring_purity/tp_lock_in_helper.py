"""Seeded TP: the append takes a lock, and a helper reached from the
hot path sleeps — a stalled flusher holding the lock (or the sleep)
would block every event append."""

import threading
import time


class LockedEventRecorder:
    def __init__(self):
        self._lock = threading.Lock()
        self._ring = [None] * 16
        self._seq = 0

    def record(self, kind, **fields):
        with self._lock:  # BAD
            self._ring[self._seq % 16] = (kind, fields)
            self._seq += 1
        self._settle()

    def _settle(self):
        time.sleep(0.001)  # BAD
