"""Seeded TP: the watchdog's transition recorder pages out-of-band
from the hot append — a slow pager (or a contended lock) now stalls
every evaluation tick that merely wanted to note a state change."""

import time


class AlertEmitRecorder:
    def __init__(self, sock, lock):
        self._sock = sock
        self._lock = lock
        self._events = []

    def record(self, kind, **fields):
        self._events.append((time.perf_counter(), kind, fields))
        if kind == "alert_firing":
            self._notify(kind, fields)

    def _notify(self, kind, fields):
        with self._lock:  # BAD
            self._sock.sendall(repr((kind, fields)).encode())  # BAD
