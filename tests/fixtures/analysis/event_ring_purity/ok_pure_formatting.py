"""Clean: the hot path may format (str.join, dict reads, sorted) —
the rule flags I/O and locks, never pure CPU work."""


class FormattingRecorder:
    def __init__(self):
        self._ring = [None] * 8
        self._seq = 0

    def record(self, kind, **fields):
        label = self._label(kind, fields)
        self._ring[self._seq % 8] = (self._seq, label)
        self._seq = self._seq + 1

    def _label(self, kind, fields):
        parts = [kind]
        for key in sorted(fields):
            parts.append(f"{key}={fields[key]}")
        return " ".join(parts)
