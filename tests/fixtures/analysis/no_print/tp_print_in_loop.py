"""TP: print() buried in a loop next to a legitimate stream write."""

import sys


def run(events):
    for event in events:
        print(event)  # BAD
    sys.stderr.write("done\n")
