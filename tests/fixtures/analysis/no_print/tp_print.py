"""TP: print() on a layer that shares stdout with a transport."""


def report(stats):
    print("stats:", stats)  # BAD
