"""Clean: a .print() METHOD and a string mention — both tripped the
regex, neither is builtins.print."""

NOTE = "print() is banned here"


class Reporter:
    def __init__(self, printer):
        self._printer = printer

    def emit(self, row):
        self._printer.print(row)
