"""Clean: explicit streams and callbacks only."""

import sys


def report(stats, stream=None):
    stream = stream if stream is not None else sys.stderr
    stream.write(f"stats: {stats}\n")
