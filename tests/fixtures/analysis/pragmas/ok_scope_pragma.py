"""Clean: a standalone pragma directly above a def covers the whole
body."""

import time


# epoch math on purpose: this helper converts wall-clock sidecar
# timestamps, not latencies
# analysis: disable=wallclock-time
def sidecar_age_s(written_at):
    return time.time() - written_at
