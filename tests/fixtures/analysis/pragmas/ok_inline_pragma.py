"""Clean: an inline pragma (with justification prose) suppresses the
finding on its own line."""

import time


def epoch_stamp():
    # this fixture documents pragma suppression; the row label is a
    # REAL wall-clock timestamp by contract
    return time.time()  # analysis: disable=wallclock-time
