"""Clean: a lock-free read is fine when the class never hands work to
a thread — there is nothing to race."""

import threading


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def bump(self):
        with self._lock:
            self._count += 1

    def peek(self):
        return self._count
