"""TP: a thread-reachable method reads a lock-guarded counter
lock-free."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            current = self._count  # BAD
            self.bump(current)

    def bump(self, current):
        with self._lock:
            self._count = current + 1
