"""OK: a lock-held helper needs no pragma — every call site acquires
the lock, and the analyzer propagates the caller-holds-the-lock
contract through the call graph (transitively: _restart is only called
by _reap, which is only called under the lock)."""

import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.workers = {}
        self.restarts = 0

    def start(self):
        threading.Thread(target=self._monitor, daemon=True).start()

    def _monitor(self):
        while True:
            with self._lock:
                self._reap()

    def stop(self):
        with self._lock:
            self.workers = {}

    def _reap(self):
        for name, proc in list(self.workers.items()):
            if proc.poll() is not None:
                self._restart(name)

    def _restart(self, name):
        self.restarts += 1
        self.workers[name] = None
