"""A different class defining (and self-calling) its OWN helper."""


class T:
    def helper(self):
        return 1

    def go(self):
        self.helper()
