"""OK (cross-module): an unrelated class's own `self.helper()` is that
class's method — it must NOT revoke our contract (the supervisor/
stripes name-collision shape)."""

import threading


class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def start(self):
        threading.Thread(target=self._loop).start()

    def reset(self):
        with self._lock:
            self.count = 0

    def _loop(self):
        with self._lock:
            self.helper()

    def helper(self):
        self.count += 1
