"""TP (cross-module): the caller-holds-the-lock contract is revoked by
an OUTSIDE call site on an unknown receiver — `s.helper()` in another
module may be our instance, lock-free."""

import threading


class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def start(self):
        threading.Thread(target=self._loop).start()

    def reset(self):
        with self._lock:
            self.count = 0

    def _loop(self):
        with self._lock:
            self.helper()

    def helper(self):
        self.count += 1  # BAD
