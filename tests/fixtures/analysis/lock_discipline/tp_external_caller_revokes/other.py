"""The revoking module: pokes the helper with no lock."""


def poke(s) -> None:
    s.helper()
