"""TP: an executor-submitted method writes a guarded counter without
the lock."""

import threading
from concurrent.futures import ThreadPoolExecutor


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.errors = 0
        self._pool = ThreadPoolExecutor(max_workers=2)

    def submit_work(self, n):
        self._pool.submit(self._work, n)

    def _work(self, n):
        for _ in range(n):
            self.total += 1  # BAD
        with self._lock:
            self.errors += 1

    def reset(self):
        with self._lock:
            self.total = 0
            self.errors = 0
