"""TP: the caller-holds-the-lock contract is only as good as EVERY
call site — one lock-free caller on the spawned path and the helper's
accesses are races again."""

import threading


class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.workers = {}
        self.restarts = 0

    def start(self):
        threading.Thread(target=self._monitor, daemon=True).start()

    def _monitor(self):
        while True:
            with self._lock:
                self._reap()
            self._reap()  # the second sweep forgot the lock

    def stop(self):
        with self._lock:
            self.workers = {}
            self.restarts = 0

    def _reap(self):
        for name in list(self.workers):  # BAD
            self._restart(name)

    def _restart(self, name):
        self.restarts += 1  # BAD
        self.workers[name] = None  # BAD
