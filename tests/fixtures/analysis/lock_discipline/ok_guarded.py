"""Clean: every touch of the guarded state happens under the lock."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        for _ in range(8):
            with self._lock:
                self._count += 1

    def snapshot(self):
        with self._lock:
            return self._count
