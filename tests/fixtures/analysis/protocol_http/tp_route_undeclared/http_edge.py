"""TP edge: serves a route the schema never declared — edge drift is
a two-place change, and this table moved alone."""

ROUTES = {  # BAD
    ("POST", "/classify"): "content",
    ("GET", "/healthz"): "health",
    ("POST", "/jobs"): "job_submit",
    ("GET", "/jobs/{id}"): "job_status",
    ("GET", "/jobs/{id}/results"): "job_results",
    ("GET", "/jobs/{id}/containers"): "job_containers",
    ("DELETE", "/jobs/{id}"): "job_cancel",
    ("POST", "/corpus"): "corpus_upload",
    ("GET", "/metrics"): "prometheus",
    ("GET", "/metrics/history"): "metrics_history",
    ("POST", "/v2/classify"): "content",
}

STATUS_TEXT = {
    200: "OK",
    202: "Accepted",
    400: "Bad Request",
    401: "Unauthorized",
    403: "Forbidden",
    404: "Not Found",
    405: "Method Not Allowed",
    409: "Conflict",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}


def _respond(conn, code, body):
    conn.write(b"HTTP/1.1 %d %s\r\n\r\n" % (code, STATUS_TEXT[code].encode()))
    conn.write(body)


def handle(conn, route):
    if route in ROUTES:
        _respond(conn, 200, b"{}")
    else:
        _respond(conn, 404, b"{}")
