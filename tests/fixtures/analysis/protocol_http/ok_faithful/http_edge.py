"""OK edge: ROUTES and STATUS_TEXT match the declared schema exactly;
every mint site uses a declared code."""

ROUTES = {
    ("POST", "/classify"): "content",
    ("GET", "/healthz"): "health",
    ("POST", "/jobs"): "job_submit",
    ("GET", "/jobs/{id}"): "job_status",
    ("GET", "/jobs/{id}/results"): "job_results",
    ("GET", "/jobs/{id}/containers"): "job_containers",
    ("DELETE", "/jobs/{id}"): "job_cancel",
    ("POST", "/corpus"): "corpus_upload",
    ("GET", "/metrics"): "prometheus",
    ("GET", "/metrics/history"): "metrics_history",
}

STATUS_TEXT = {
    200: "OK",
    202: "Accepted",
    400: "Bad Request",
    401: "Unauthorized",
    403: "Forbidden",
    404: "Not Found",
    405: "Method Not Allowed",
    409: "Conflict",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}


def _respond(conn, code, body):
    conn.write(b"HTTP/1.1 %d %s\r\n\r\n" % (code, STATUS_TEXT[code].encode()))
    conn.write(body)


def handle(conn, route, authed):
    if route not in ROUTES:
        _respond(conn, 404, b"{}")
    elif not authed:
        _respond(conn, 401, b"{}")
    else:
        _respond(conn, 200, b"{}")
