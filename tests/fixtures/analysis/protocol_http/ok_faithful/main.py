"""OK client: posts the declared classify route through the edge."""


def classify(sock, body):
    head = (
        "POST /classify HTTP/1.1\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode()
    sock.sendall(head + body)
    return sock.recv(65536)
