"""OK client-only program: sends a schema-declared route with no edge
module in sight (a load-generator harness) — nothing to diff the
serving side against, nothing to flag."""


def probe(sock):
    sock.sendall(b"GET /healthz HTTP/1.1\r\nHost: edge\r\n\r\n")
    return sock.recv(65536)
