"""TP edge: the ROUTES table silently dropped /metrics — the schema
still declares it, and the client next door still asks for it."""

ROUTES = {  # BAD
    ("POST", "/classify"): "content",
    ("GET", "/healthz"): "health",
    ("POST", "/jobs"): "job_submit",
    ("GET", "/jobs/{id}"): "job_status",
    ("GET", "/jobs/{id}/results"): "job_results",
    ("GET", "/jobs/{id}/containers"): "job_containers",
    ("DELETE", "/jobs/{id}"): "job_cancel",
    ("POST", "/corpus"): "corpus_upload",
    ("GET", "/metrics/history"): "metrics_history",
}

STATUS_TEXT = {
    200: "OK",
    202: "Accepted",
    400: "Bad Request",
    401: "Unauthorized",
    403: "Forbidden",
    404: "Not Found",
    405: "Method Not Allowed",
    409: "Conflict",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}


def _respond(conn, code, body):
    conn.write(b"HTTP/1.1 %d\r\n\r\n" % code)
    conn.write(body)


def handle(conn, route):
    if route in ROUTES:
        _respond(conn, 200, b"{}")
    else:
        _respond(conn, 404, b"{}")
