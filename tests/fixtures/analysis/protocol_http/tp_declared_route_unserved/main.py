"""TP client: scrapes the route the edge dropped — every request
would answer 404."""


def scrape(sock):
    sock.sendall(b"GET /metrics HTTP/1.1\r\nHost: edge\r\n\r\n")  # BAD
    return sock.recv(65536)
