"""TP stub worker: claims to be protocol-faithful, but the reload verb
the real worker handles is silently missing — the rolling-upgrade
drills would exercise a protocol production does not speak."""

import json


def stub_answer(state, msg: dict) -> dict:
    op = msg.get("op")
    if op == "stats":  # BAD
        return {"id": msg.get("id"), "stats": {"completed": state.completed}}
    return {"id": msg.get("id"), "key": "stub-mit", "matcher": "stub",
            "confidence": 99.0}


def serve_line(state, line: str) -> str:
    return json.dumps(stub_answer(state, json.loads(line)))
