"""Client keeping the drift rule quiet: every handled op has a
sender."""

import json


def drive(send) -> None:
    send(json.dumps({"op": "stats"}))
    send(json.dumps({"op": "reload", "corpus": "next.npz"}))
    send(json.dumps({"id": 1, "content": "hello"}))
