"""Client keeping the drift rule quiet."""

import json


def drive(send) -> None:
    send(json.dumps({"op": "stats"}))
    send(json.dumps({"op": "trace", "n": 5}))
    send(json.dumps({"id": 1, "content": "hello"}))
