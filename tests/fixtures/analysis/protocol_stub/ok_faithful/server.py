"""OK real worker: stats, trace, and content."""

import json


def handle_line(batcher, line: str, write_line) -> None:
    msg = json.loads(line)
    op = msg.get("op")
    if op == "stats":
        write_line(json.dumps({"id": msg.get("id"), "stats": batcher.stats()}))
        return
    if op == "trace":
        write_line(json.dumps({"id": msg.get("id"),
                               "traces": batcher.trace_tail(msg.get("n", 20))}))
        return
    row = batcher.classify(msg.get("content"))
    write_line(json.dumps({"id": msg.get("id"), "key": row.key,
                           "matcher": row.matcher,
                           "confidence": row.confidence}))
