"""OK stub worker: the handled op set matches the real worker exactly
— protocol-faithful as a checked property."""

import json


def stub_answer(state, msg: dict) -> dict:
    op = msg.get("op")
    if op == "stats":
        return {"id": msg.get("id"), "stats": {"completed": state.completed}}
    if op == "trace":
        return {"id": msg.get("id"), "traces": list(state.traces)}
    return {"id": msg.get("id"), "key": "stub-mit", "matcher": "stub",
            "confidence": 99.0}


def serve_line(state, line: str) -> str:
    return json.dumps(stub_answer(state, json.loads(line)))
