"""Client for the stub-less program."""

import json


def drive(send) -> None:
    send(json.dumps({"op": "stats"}))
    send(json.dumps({"id": 1, "content": "hello"}))
