"""TP stub worker: answers a trace verb the REAL worker never
implemented — drills passing against stub-only protocol prove
nothing about production."""

import json


def stub_answer(state, msg: dict) -> dict:
    op = msg.get("op")
    if op == "stats":
        return {"id": msg.get("id"), "stats": {"completed": state.completed}}
    if op == "trace":  # BAD
        return {"id": msg.get("id"), "traces": list(state.traces)}
    return {"id": msg.get("id"), "key": "stub-mit", "matcher": "stub",
            "confidence": 99.0}


def serve_line(state, line: str) -> str:
    return json.dumps(stub_answer(state, json.loads(line)))
