"""TP real worker: stats and content only."""

import json


def handle_line(batcher, line: str, write_line) -> None:
    msg = json.loads(line)
    op = msg.get("op")
    if op == "stats":
        write_line(json.dumps({"id": msg.get("id"), "stats": batcher.stats()}))
        return
    row = batcher.classify(msg.get("content"))
    write_line(json.dumps({"id": msg.get("id"), "key": row.key,
                           "matcher": row.matcher,
                           "confidence": row.confidence}))
