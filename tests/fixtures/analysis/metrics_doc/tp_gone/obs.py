"""TP: the README documents a series no registration produces — stale
docs mislead dashboards."""


def register(registry) -> None:
    registry.gauge("widget_depth", "Widgets waiting right now")
