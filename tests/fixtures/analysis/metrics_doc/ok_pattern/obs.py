"""OK: an f-string registration becomes a wildcard pattern; the
documented per-lane names satisfy it (and it covers them)."""

LANES = ("featurize", "device", "writer")


def register(registry) -> None:
    for name in LANES:
        registry.gauge(f"lane_{name}_busy", f"Occupancy of the {name} lane")
