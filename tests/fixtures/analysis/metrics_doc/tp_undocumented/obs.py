"""TP: a series registered but absent from the README metric table —
the namespace grew undocumented."""


def register(registry) -> None:
    registry.gauge("widget_depth", "Widgets waiting right now")
    registry.counter("widget_spins_total", "Spins by kind", labels=("kind",))  # BAD
