"""OK: every registered series documented, every documented series
registered."""


def register(registry) -> None:
    registry.gauge("widget_depth", "Widgets waiting right now")
    registry.counter("widget_spins_total", "Spins by kind", labels=("kind",))
    registry.histogram("widget_latency_seconds", "End-to-end widget latency")
