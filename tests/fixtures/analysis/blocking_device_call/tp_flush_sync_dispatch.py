"""TP: the scheduler flush path calls the synchronous submit+await
wrapper instead of the async seam — the device lane serializes."""


class Batcher:
    def _flush(self, batch):
        merged = self.classifier.merge_prepared(batch)
        outs = self.classifier.dispatch_chunks(merged)  # BAD
        self.classifier.finish_chunks(merged, outs, self.threshold)

    def _submit_group(self, live):
        group = [r.prepared for r in live]
        merged = self.classifier.merge_prepared(group)
        return self.classifier.dispatch_chunks(merged, pad_to=64)  # BAD
