"""OK: the sync wrapper's own definition is the sanctioned home of the
await, and one-shot callers off the pipeline may use it freely."""


class Classifier:
    def dispatch_chunks_async(self, prepared):
        return self._submit(prepared)

    def dispatch_chunks(self, prepared):
        # the convenience wrapper: submit + await in one call
        return self.dispatch_chunks_async(prepared).result()

    def classify_blobs(self, contents):
        # a one-shot path, not reachable from the pipeline entries
        prepared = self.prepare_batch(contents)
        outs = self.dispatch_chunks(prepared)
        self.finish_chunks(prepared, outs, None)
        return prepared.results
