"""OK: the flush submits asynchronously and hands the future to the
completion lane — the await lives off the submit path by design."""


class Batcher:
    def _flush(self, batch):
        merged = self.classifier.merge_prepared(batch)
        future = self.classifier.dispatch_chunks_async(merged)
        self._device_q.put({"merged": merged, "future": future})

    def _complete_group(self, pend):
        # the completion thread is the sanctioned blocking lane: it is
        # not reachable from the submit entries, so awaiting here is fine
        outs = pend["future"].result()
        self.classifier.finish_chunks(pend["merged"], outs, self.threshold)
