"""TP: the batch run loop synchronizes with the device per chunk —
block_until_ready on the submit path defeats the overlap pipeline."""

import jax


class Project:
    def run(self, output):
        fut = self.classifier.dispatch_chunks_async(self.prepared)
        for arr in fut.arrays:
            arr.block_until_ready()  # BAD
        jax.block_until_ready(fut.arrays)  # BAD
        return fut
