"""TP: the staged per-blob entry points."""


def warm(pipeline, blob):
    pipeline.stage1(blob)  # BAD
    return pipeline.stage2(blob)  # BAD
