"""Clean: one batch crossing covers the whole chunk."""


def produce(classifier, blobs):
    prepared = classifier.prepare_batch(blobs)
    return classifier.featurize_batch(prepared)
