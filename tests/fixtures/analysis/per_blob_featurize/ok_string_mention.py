"""Clean: '.featurize(' in prose — the regex lint flagged exactly
this."""

RULE = "never call .featurize( per blob on the hot path"


def describe():
    return RULE
