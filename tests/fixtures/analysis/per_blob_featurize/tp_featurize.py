"""TP: per-blob native crossing in a loop."""


def produce(classifier, blobs):
    rows = []
    for blob in blobs:
        rows.append(classifier.featurize(blob))  # BAD
    return rows
