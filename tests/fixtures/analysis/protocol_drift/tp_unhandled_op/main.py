"""TP client: sends a schema-declared op that NO surface in this
program handles — the request would answer bad_request everywhere."""

import json
import socket


def scrape(sock: socket.socket) -> None:
    sock.sendall((json.dumps({"op": "stats"}) + "\n").encode())
    sock.sendall((json.dumps({"id": 1, "content": "hello"}) + "\n").encode())
    sock.sendall((json.dumps({"op": "reload", "corpus": "a.npz"}) + "\n").encode())  # BAD
