"""TP worker: handles stats and content rows, but the reload verb the
client sends is nowhere in this dispatch."""

import json


def handle_line(batcher, line: str, write_line) -> None:
    msg = json.loads(line)
    op = msg.get("op")
    if op == "stats":
        write_line(json.dumps({"id": msg.get("id"), "stats": batcher.stats()}))
        return
    content = msg.get("content")
    row = batcher.classify(content)
    write_line(json.dumps({"id": msg.get("id"), "key": row.key,
                           "matcher": row.matcher,
                           "confidence": row.confidence}))
