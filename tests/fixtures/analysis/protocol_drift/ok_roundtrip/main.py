"""OK client: every op it sends is handled, every field it reads is
produced."""

import json
import socket


def ask(sock: socket.socket, blob: str) -> dict:
    sock.sendall((json.dumps({"op": "stats"}) + "\n").encode())
    sock.sendall((json.dumps({"id": 1, "content": blob}) + "\n").encode())
    row = json.loads(sock.recv(65536).decode())
    return {"verdict": row.get("key"), "stats": row.get("stats")}
