"""OK probe helper: a one-shot stats round trip (the supervisor's
health-probe shape) against the worker in this program."""

import json
import socket

PROBE_LINE = '{"op": "stats"}'


def probe(path: str, timeout: float) -> dict:
    sock = socket.create_connection(path, timeout)
    try:
        sock.sendall(PROBE_LINE.encode() + b"\n")
        return json.loads(sock.recv(65536).decode())
    finally:
        sock.close()
