"""OK worker for the probe helper: a stats-only control surface."""

import json


def handle_line(stats_fn, line: str, write_line) -> None:
    msg = json.loads(line)
    op = msg.get("op")
    if op == "stats":
        write_line(json.dumps({"id": msg.get("id"), "stats": stats_fn()}))
    else:
        write_line(json.dumps({"id": msg.get("id"),
                               "error": f"bad_request: unknown op {op!r}"}))
