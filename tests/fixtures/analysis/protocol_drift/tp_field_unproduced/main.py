"""TP client: backs off on a ``retry_after`` hint that no producer in
this program ever emits — the backoff branch is dead drift."""

import json
import socket
import time


def ask(sock: socket.socket, blob: str) -> dict:
    sock.sendall((json.dumps({"op": "stats"}) + "\n").encode())
    sock.sendall((json.dumps({"id": 7, "content": blob}) + "\n").encode())
    row = json.loads(sock.recv(65536).decode())
    hint = row.get("retry_after")  # BAD
    if hint:
        time.sleep(hint)
    return row
