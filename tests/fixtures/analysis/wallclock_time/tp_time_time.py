"""TP: wall-clock latency math."""

import time


def latency_probe():
    t0 = time.time()  # BAD
    return time.time() - t0  # BAD
