"""Clean: time.time() appears only in prose — the regex lint flagged
this; the AST rule must not."""

BANNER = "never call time.time() in serving code"


def describe():
    # a comment mentioning time.time() is also fine
    return BANNER
