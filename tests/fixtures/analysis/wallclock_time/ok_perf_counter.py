"""Clean: monotonic clock."""

import time


def latency_probe():
    t0 = time.perf_counter()
    return time.perf_counter() - t0
