"""TP: the aliased form the regex lint could never see."""

from time import time as wallclock


def span():
    start = wallclock()  # BAD
    return wallclock() - start  # BAD
