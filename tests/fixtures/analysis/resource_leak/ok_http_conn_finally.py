"""Clean: try/finally guarantees the HTTP connection closes even
when the request raises."""

import http.client


def fetch(host, target):
    conn = http.client.HTTPConnection(host, timeout=5.0)
    try:
        conn.request("GET", target)
        return conn.getresponse().read()
    finally:
        conn.close()
