"""Clean: ownership hand-offs — a dialed connection parked in a pool
(call argument) or returned to the caller is not a leak here."""

import http.client


def dial_into(pool, host):
    conn = http.client.HTTPSConnection(host, timeout=5.0)
    pool.release(conn)  # the pool owns it now


def dial(host):
    conn = http.client.HTTPConnection(host, timeout=5.0)
    return conn  # the caller owns it now
