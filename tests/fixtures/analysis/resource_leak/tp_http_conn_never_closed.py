"""TP: an HTTP connection that is never closed and never handed off."""

import http.client


def fetch(host, target):
    conn = http.client.HTTPConnection(host, timeout=5.0)  # BAD
    conn.request("GET", target)
    resp = conn.getresponse()
    return resp.read()
