"""TP: a socket that is never closed and never handed off."""

import socket


def probe(path):
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)  # BAD
    sock.connect(path)
    sock.sendall(b"ping\n")
    return sock.recv(1)
