"""Clean: `with` owns the close on every path."""

import socket


def read_config(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


def probe(path):
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as sock:
        sock.connect(path)
        return sock.recv(1)
