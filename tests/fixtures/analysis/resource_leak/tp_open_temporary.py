"""TP: file handles opened as temporaries — closed only when the GC
runs, a descriptor leak on a long-lived worker."""


def read_config(path):
    text = open(path, encoding="utf-8").read()  # BAD
    lines = open(path, encoding="utf-8").readlines()  # BAD
    return text, lines
