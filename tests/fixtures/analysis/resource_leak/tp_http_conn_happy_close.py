"""TP: the close sits on the happy path only — a raised request
leaks the dialed TLS connection."""

import http.client


def fetch_secure(host, target):
    conn = http.client.HTTPSConnection(host, timeout=5.0)  # BAD
    conn.request("GET", target)
    body = conn.getresponse().read()
    conn.close()
    return body
