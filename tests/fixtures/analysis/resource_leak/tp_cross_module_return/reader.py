"""TP (cross-module): the caller takes ownership of the returned file
handle and never closes it — a descriptor leak the per-file pass
cannot see (the factory lives in another module)."""

import conn_util


def head(path: str) -> bytes:
    feed = conn_util.open_feed(path)  # BAD
    return feed.read(16)


def skim(path: str) -> bytes:
    feed = conn_util.open_feed(path)  # BAD
    data = feed.read(16)
    feed.close()  # happy path only: an exception above leaks the fd
    return data
