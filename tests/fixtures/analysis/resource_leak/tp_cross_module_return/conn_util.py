"""The factory module: returning the handle is a legitimate ownership
hand-off HERE — the caller inherits the close obligation."""


def open_feed(path: str):
    f = open(path, "rb")
    return f
