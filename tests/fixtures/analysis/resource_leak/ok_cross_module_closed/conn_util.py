"""The factory module: ownership moves to the caller."""


def open_feed(path: str):
    return open(path, "rb")
