"""OK (cross-module): every caller of the returning factory releases
the handle on all paths, or hands ownership onward."""

import conn_util


def head(path: str) -> bytes:
    feed = conn_util.open_feed(path)
    try:
        return feed.read(16)
    finally:
        feed.close()


def reopen(path: str):
    feed = conn_util.open_feed(path)
    return feed  # ownership handed to OUR caller in turn
