"""Clean: try/finally guarantees the close; hand-offs move
ownership."""

import socket
import subprocess


def oneshot(path, payload):
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        sock.connect(path)
        sock.sendall(payload)
        return sock.recv(4096)
    finally:
        sock.close()


def spawn(handle, argv):
    handle.proc = subprocess.Popen(argv)  # stored: the handle owns it
