"""Clean: pure jnp math, vmapped helper included."""

import jax
import jax.numpy as jnp


@jax.jit
def dice_scores(overlap, totals):
    num = 2 * overlap
    den = totals + 1
    return num / den


def _row_norm(row):
    return row / (jnp.sum(row) + 1e-9)


normalize = jax.vmap(_row_norm)
