"""Clean: branches on static_argnames parameters and on shapes are
resolved at trace time — no tracer ever reaches bool()."""

import functools

import jax


@functools.partial(jax.jit, static_argnames=("method",))
def score(x, method):
    b, w = x.shape
    if method == "matmul":
        return x @ x.T
    if b > w:
        return x * 2
    return x
