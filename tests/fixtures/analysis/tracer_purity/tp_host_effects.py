"""TP: host-side effects inside a jitted function run once at trace
time and silently vanish from the compiled kernel."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def stamped_scores(x):
    t0 = time.time()  # BAD
    print("tracing", t0)  # BAD
    return jnp.sum(x) + t0
