"""TP: branching on a traced value (directly or through a tainted
local)."""

import jax
import jax.numpy as jnp


@jax.jit
def clamp(x, limit):
    scaled = x * 2.0
    if scaled.sum() > limit:  # BAD
        return jnp.zeros_like(x)
    return scaled
