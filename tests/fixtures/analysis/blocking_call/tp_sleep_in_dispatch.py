"""TP: the dispatch path sleeps, directly and via a reachable
helper."""

import time


class Router:
    def dispatch(self, msg):
        if not msg:
            self._backoff()
        time.sleep(0.05)  # BAD
        return {"ok": True}

    def _backoff(self):
        time.sleep(0.5)  # BAD
