"""Clean: the dispatch path only computes and enqueues."""

from collections import deque


class Router:
    def __init__(self):
        self._pending = deque()

    def dispatch(self, msg):
        row = {"id": msg.get("id"), "ok": True}
        self._pending.append(row)
        return row
