"""TP (cross-module): an event-loop callback reaches a blocking helper
DEFINED IN ANOTHER MODULE — per-file analysis would never see it."""

import wire_helpers


class FrontSession:
    def __init__(self, loop, conn):
        self.loop = loop
        self.conn = conn
        conn.on_line = self._on_line

    def _on_line(self, line: str) -> None:
        # the callback runs on the loop thread; the helper it calls
        # parks that thread on a socket read
        status = wire_helpers.fetch_status(self.conn.backend_path)
        self.conn.write_line(status)
