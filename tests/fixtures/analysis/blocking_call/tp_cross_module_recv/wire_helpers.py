"""The blocking helper module: innocent on its own (probes may block
on THEIR callers' threads), a loop-stall when a router callback can
reach it."""

import socket


def fetch_status(path: str) -> str:
    sock = socket.create_connection(path, 1.0)  # BAD
    try:
        sock.sendall(b'{"op": "stats"}\n')  # BAD
        return sock.recv(65536).decode()  # BAD
    finally:
        sock.close()
