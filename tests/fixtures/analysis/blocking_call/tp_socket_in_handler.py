"""TP: the line handler blocks on a socket read and opens a file."""


def handle_line(conn, line, path):
    data = conn.recv(4096)  # BAD
    with open(path, "ab") as f:  # BAD
        f.write(data)
    return data.decode("utf-8")
