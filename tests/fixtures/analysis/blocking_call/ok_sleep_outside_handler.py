"""Clean: a monitor loop may sleep — it is not a handler path."""

import time


def monitor_loop(stop):
    while not stop.is_set():
        time.sleep(1.0)
