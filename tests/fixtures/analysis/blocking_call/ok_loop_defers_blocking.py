"""Clean: loop callbacks only move bytes and hand blocking work off —
the sanctioned non-blocking recv carries its pragma, and the slow scrape
goes to the ops executor instead of running on the loop."""


class LoopConn:
    def _on_readable(self):
        # non-blocking socket: EAGAIN ends the pass, it never parks
        # the loop thread
        # analysis: disable=blocking-call
        chunk = self.sock.recv(65536)
        self.buf += chunk

    def _start_op(self, slot):
        # blocking fan-out scrape: deferred to the ops lane, the loop
        # only enqueues
        self.ops.submit(self._scrape_workers, slot)

    def _sweep(self):
        for conn in list(self.conns):
            if conn.stalled():
                conn.close("slowloris")
