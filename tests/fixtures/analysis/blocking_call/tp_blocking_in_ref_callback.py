"""TP: callbacks handed to the loop BY REFERENCE — call_later /
call_soon_threadsafe arguments, lambdas passed to connect factories,
``on_*`` attribute rebinding — run on the loop thread too, even though
plain call-edge reachability never sees an invocation."""

import time


class Router:
    def start(self):
        self.loop.call_soon_threadsafe(self._arm_sweep)

    def _arm_sweep(self):
        self.timer = self.loop.call_later(0.5, self._sweep_once)

    def _sweep_once(self):
        time.sleep(0.01)  # BAD

    def _dial(self):
        connect_unix(
            self.loop, self.path, 1.0,
            lambda sock: self._connected(sock),
            lambda exc: None,
        )

    def _connected(self, sock):
        self.stream = sock.makefile("rwb")  # BAD

    def _rebind(self, conn):
        conn.on_line = self.handle_probe_line

    def handle_probe_line(self, text):
        self.log = open("/tmp/x")  # BAD
