"""TP: event-loop callbacks must never block — a socket read in an fd
callback, a sleep plus a sync device dispatch in a timer callback, and
a synchronous dial in a connect callback."""

import time


class LoopConn:
    def _on_readable(self):
        chunk = self.sock.recv(65536)  # BAD
        self.buf += chunk

    def _probe_tick(self):
        time.sleep(0.25)  # BAD
        return self.classifier.dispatch_chunks(self.batch)  # BAD

    def on_writable(self, mask):
        self.sock.connect(self.path)  # BAD
