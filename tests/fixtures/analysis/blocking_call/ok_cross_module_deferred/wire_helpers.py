"""The same blocking helper — unreachable from any loop callback in
this program, so its socket waits are its callers' business."""

import socket


def fetch_status(path: str) -> str:
    sock = socket.create_connection(path, 1.0)
    try:
        sock.sendall(b'{"op": "stats"}\n')
        return sock.recv(65536).decode()
    finally:
        sock.close()
