"""OK (cross-module): the loop callback DEFERS the blocking helper to
an executor thread — the loop never carries the socket wait."""

import wire_helpers


class FrontSession:
    def __init__(self, loop, conn, ops_executor):
        self.loop = loop
        self.conn = conn
        self.ops = ops_executor
        conn.on_line = self._on_line

    def _on_line(self, line: str) -> None:
        # executor thunks block by design; the loop thread only
        # schedules the completion callback
        future = self.ops.submit(wire_helpers.fetch_status,
                                 self.conn.backend_path)
        future.add_done_callback(
            lambda f: self.loop.call_soon_threadsafe(self._answer, f)
        )

    def _answer(self, future) -> None:
        self.conn.write_line(future.result())
