"""OK: a def-scope pragma covering real findings in the body is used —
not stale."""

import time


# user-facing timestamps by contract (fixture)
# analysis: disable=wallclock-time
def stamps() -> tuple:
    return (time.time(), time.time())
