"""TP: a pragma naming a rule id that does not exist suppresses
nothing by construction (the classic typo'd escape hatch) — the real
finding still fires AND the pragma is reported stale."""

import time


def stamp() -> float:
    return time.time()  # analysis: disable=wallclock-times  # BAD
