"""OK: an inline pragma that suppresses a real finding is earning its
keep — not stale."""

import time


def stamp() -> float:
    # a wall-clock stamp on purpose: this value is user-facing
    return time.time()  # analysis: disable=wallclock-time
