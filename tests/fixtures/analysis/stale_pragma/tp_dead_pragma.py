"""TP: a pragma guarding a line that violates nothing is dead weight —
the violation it once excused was fixed for real, and the escape hatch
must shrink with it."""

import time


def elapsed(t0: float) -> float:
    # analysis: disable=wallclock-time — nothing below violates it  # BAD
    return time.perf_counter() - t0
