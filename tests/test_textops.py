"""Differential tests: every native textops scanner must be bit-identical
to its Python/Ruby-semantics regex twin on real license texts, adversarial
edge cases, and random fuzz inputs."""

import random
import re

import pytest

from licensee_tpu.native import textops as native_textops
from licensee_tpu.normalize import pipeline as P
from licensee_tpu.rubytext import ruby_strip, squeeze_spaces


ops = native_textops.load()
pytestmark = pytest.mark.skipif(ops is None, reason="native textops unavailable")


def py_squeeze_strip(s):
    return ruby_strip(squeeze_spaces(s))


def py_strip_whitespace(s):
    return ruby_strip(squeeze_spaces(P.REGEXES["whitespace"].sub(" ", s)))


def py_dashes(s):
    return P._DASHES.sub("-", s)


def py_quotes(s):
    return P._QUOTES.sub("'", s)


def py_hyphenated(s):
    return P._HYPHENATED.sub(lambda m: m.group(1) + "-" + m.group(2), s)


def py_spelling(s):
    return P._SPELLING.sub(lambda m: P.VARIETAL_WORDS[m.group(0)], s)


PAIRS = [
    (py_squeeze_strip, lambda s: ops.squeeze_strip(s)),
    (py_strip_whitespace, lambda s: ops.strip_whitespace(s)),
    (py_dashes, lambda s: ops.dashes(s)),
    (py_quotes, lambda s: ops.quotes(s)),
    (py_hyphenated, lambda s: ops.hyphenated(s)),
    (py_spelling, lambda s: ops.spelling(s)),
]


def check_all(s):
    for py, nat in PAIRS:
        assert py(s) == nat(s), (py.__name__, repr(s)[:120])


EDGE_CASES = [
    "",
    " ",
    "\n",
    "\x00 padded \x00",
    "a-b",
    "a - b",
    "a --- b",
    "a---\nb",
    "a-\nb",
    "word-\n  next",
    "word- \n \t next",
    "word-\n\nnext",   # two newlines: \s* spans both
    "-start",
    "end-",
    "\n-x",
    "\n--x",
    "\n---\n",
    "--",
    "—–-",
    "a—b",
    "a–\nb",
    "a—\n",
    "x''y",
    "‘quoted’ “double”",
    "`tick`",
    "licence",
    "LICENCE",          # spelling is case-sensitive on lowercased input
    "sub-license sub license sublicense",
    "favourite favour favours",
    "per cent percent per  cent",
    "copyright owner copyright  owner",
    "xlicence licencex a_licence licence_b",
    "judgment day",
    "non-commercial use",
    "practise makes practice",
    "whilst wilful fulfil",
    "organisation's organisational",
    "centre—piece",
    "  spaced   out  ",
    "\t tab \t mix \n newline \v vtab \f feed \r cr ",
    "a b",         # NBSP is NOT Ruby \s — must survive whitespace strip
]


@pytest.mark.parametrize("case", EDGE_CASES, ids=range(len(EDGE_CASES)))
def test_edge_cases(case):
    check_all(case)


def test_all_vendored_templates():
    from licensee_tpu.corpus.license import License

    for lic in License.all(hidden=True, pseudo=False):
        content = lic.content or ""
        check_all(content)
        check_all(content.lower())


def test_all_fixture_files():
    import os

    from tests.conftest import FIXTURES_DIR

    for name in sorted(os.listdir(FIXTURES_DIR)):
        d = os.path.join(FIXTURES_DIR, name)
        if not os.path.isdir(d):
            continue
        for fname in os.listdir(d):
            full = os.path.join(d, fname)
            if os.path.isfile(full):
                with open(full, "rb") as f:
                    text = f.read().decode("utf-8", errors="replace")
                check_all(text)
                check_all(text.lower())


def test_fuzz_random():
    rng = random.Random(1234)
    alphabet = (
        list("abcdefgz_09 \t\n\v\f\r-'\"`()")
        + ["—", "–", "‘", "’", "“", "”", "é", " "]
        + ["licence", "favour", "per cent", "sub license", "-\n", "--", " \n "]
    )
    for _ in range(400):
        s = "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 120)))
        check_all(s)


def test_spelling_order_favour_vs_favourite():
    # alternation order: 'favour' precedes 'favourite'; \b forces the
    # longer match only when the shorter one fails the boundary
    assert ops.spelling("favourite") == "favorite"
    assert ops.spelling("favour") == "favor"
    assert ops.spelling("favours") == py_spelling("favours")


def py_wordset(s):
    return frozenset(P.WORDSET_TOKEN.findall(s))


WORDSET_CASES = [
    "",
    "hello world hello",
    "it's the owner's copy",
    "boys' own s' x' 'lone",
    "a'sb s's' ss's x's'",
    "semi/colon path/to-file -dash- /x/",
    "under_score 0numbers9",
    "mixé uniçode tökens",
    "a-\nb c'd e''f",
    "'' ' s'",
]


@pytest.mark.parametrize("case", WORDSET_CASES, ids=range(len(WORDSET_CASES)))
def test_wordset_cases(case):
    assert ops.wordset(case) == py_wordset(case), repr(case)


def test_wordset_on_normalized_templates():
    from licensee_tpu.corpus.license import License

    for lic in License.all(hidden=True, pseudo=False):
        cn = lic.content_normalized()
        assert ops.wordset(cn) == py_wordset(cn), lic.key


def test_wordset_fuzz():
    rng = random.Random(99)
    alphabet = list("abs'/_-09 \n\t") + ["é", "'s", "s'", "--", "//"]
    for _ in range(500):
        s = "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 80)))
        assert ops.wordset(s) == py_wordset(s), repr(s)
