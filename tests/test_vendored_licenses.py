"""Round-trip detection-quality contract over every vendored license
(parity with spec/vendored_license_spec.rb): each rendered template must be
detected as itself — also without its title, with a doubled title, and
re-wrapped at 60 columns — and must NOT match once 75 random words are
injected."""

import random

import pytest

import licensee_tpu
from licensee_tpu.corpus.license import License
from licensee_tpu.normalize.pipeline import wrap
from licensee_tpu.project_files.license_file import LicenseFile
from tests.conftest import fixture_contents, sub_copyright_info

LICENSES = [
    lic for lic in License.all(hidden=True) if not lic.pseudo_license
]
KEYS = [lic.key for lic in LICENSES]

IPSUM_WORDS = fixture_contents("ipsum.txt").split()


def detected_as(content, license) -> bool:
    """The be_detected_as matcher (spec_helper.rb:119-149)."""
    file = LicenseFile(content, "LICENSE")
    return file.license is not None and file.license == license


def add_random_words(string: str, count: int = 5, seed: int = 0) -> str:
    rng = random.Random(seed)
    words = string.split()
    for _ in range(count):
        word = IPSUM_WORDS[rng.randrange(len(IPSUM_WORDS))]
        index = rng.randrange(len(words))
        words.insert(index, word)
    return " ".join(words)


@pytest.mark.parametrize("key", KEYS)
def test_detects_itself(key):
    lic = License.find(key)
    assert detected_as(sub_copyright_info(lic), lic)


@pytest.mark.parametrize("key", KEYS)
def test_confidence_equals_similarity(key):
    lic = License.find(key)
    file = LicenseFile(sub_copyright_info(lic), "LICENSE.txt")
    assert file.confidence == lic.similarity(file)


@pytest.mark.parametrize("key", KEYS)
def test_detects_without_title(key):
    lic = License.find(key)
    file = LicenseFile(sub_copyright_info(lic), "LICENSE.txt")
    stripped = file._strip_title(file.content_without_title_and_version)
    assert detected_as(stripped, lic)


@pytest.mark.parametrize("key", KEYS)
def test_detects_with_double_title(key):
    lic = License.find(key)
    content = lic.name.replace("*", "u", 1) + "\n\n" + sub_copyright_info(lic)
    assert detected_as(content, lic)


@pytest.mark.parametrize("key", KEYS)
def test_detects_rewrapped(key):
    lic = License.find(key)
    assert detected_as(wrap(sub_copyright_info(lic), 60), lic)


@pytest.mark.parametrize("key", KEYS)
def test_does_not_match_with_random_words(key):
    lic = License.find(key)
    content = add_random_words(sub_copyright_info(lic), 75, seed=hash(key) % 2**32)
    assert not detected_as(content, lic)


@pytest.mark.parametrize("key", KEYS)
def test_does_not_match_rewrapped_with_random_words(key):
    lic = License.find(key)
    content = wrap(
        add_random_words(sub_copyright_info(lic), 75, seed=hash(key) % 2**31), 60
    )
    assert not detected_as(content, lic)
