"""batch-detect --mode auto: per-file chain routing for mixed manifests
(north-star config 5: 50M files mixing LICENSEs, READMEs, package
manifests, and mostly-unrelated source files).

Parity targets: `projects/project.rb:111-124` (find_files selects each
project-file class by its own name_score table and never loads score-0
files) and the three score tables it dispatches through
(`license_file.rb:38-59`, `readme_file.rb:6-12`,
`package_manager_file.rb:30-41`).
"""

from __future__ import annotations

import json

import pytest

from licensee_tpu.kernels.batch import BatchClassifier
from licensee_tpu.projects.batch_project import BatchProject
from tests.conftest import fixture_path


def fixture_bytes(name: str) -> bytes:
    with open(fixture_path(name), "rb") as f:
        return f.read()


# -- routing table --


@pytest.mark.parametrize(
    ("filename", "route"),
    [
        ("LICENSE", "license"),
        ("license", "license"),
        ("COPYING.md", "license"),
        ("LICENSE.txt", "license"),
        ("UNLICENSE", "license"),
        ("COPYING.lesser", "license"),
        ("MIT-LICENSE", "license"),
        ("LICENSE-MIT.json", "license"),  # 0.70 beats the package table's 0
        ("PATENTS", "license"),
        ("LICENSE.html", "license"),
        ("README", "readme"),
        ("README.md", "readme"),
        ("README.rst", "readme"),
        ("package.json", "package"),
        ("bower.json", "package"),
        ("project.gemspec", "package"),
        ("foo.cabal", "package"),
        ("foo.nuspec", "package"),
        ("Cargo.toml", "package"),
        ("DESCRIPTION", "package"),
        ("dist.ini", "package"),
        ("LICENSE.spdx", "package"),  # license table excludes .spdx
        ("COPYING.cabal", "package"),  # package 1.0 outscores license 0.75
        ("main.c", None),
        ("readme.html", None),  # the reference never scores .html readmes
        ("notes.txt", None),
        ("", None),
    ],
)
def test_route_for(filename, route):
    assert BatchClassifier.route_for(filename) == route


# -- one-pass mixed classification --


@pytest.fixture(scope="module")
def auto_clf():
    return BatchClassifier(pad_batch_to=16, mesh=None, mode="auto")


def test_auto_classifies_mixed_blobs(auto_clf):
    contents = [
        fixture_bytes("mit/LICENSE.txt"),
        fixture_bytes("license-with-readme-reference/README"),
        b'{\n  "license": "MIT"\n}\n',
        b"int main(void) { return 0; }\n",
    ]
    filenames = ["LICENSE.txt", "README", "package.json", "main.c"]
    results = auto_clf.classify_blobs(contents, filenames=filenames)
    assert [(r.key, r.matcher) for r in results] == [
        ("mit", "exact"),
        ("mit", "reference"),
        ("mit", "npmbower"),
        (None, None),
    ]


def test_auto_agrees_with_fixed_modes(auto_clf):
    """Every routed row must equal what the corresponding fixed mode
    produces for the same (content, filename)."""
    cases = [
        ("LICENSE.txt", fixture_bytes("mit/LICENSE.txt"), "license"),
        ("LICENSE.md", fixture_bytes("gpl-3.0_markdown/LICENSE.md"), "license"),
        ("README.md", fixture_bytes("readme/README.md"), "readme"),
        (
            "README",
            fixture_bytes("license-with-readme-reference/README"),
            "readme",
        ),
        ("project.gemspec", fixture_bytes("gemspec/project._gemspec"), "package"),
        ("Cargo.toml", b'[package]\nlicense = "Apache-2.0"\n', "package"),
    ]
    got = auto_clf.classify_blobs(
        [c for _, c, _ in cases], filenames=[f for f, _, _ in cases]
    )
    fixed = {
        "license": BatchClassifier(pad_batch_to=16, mesh=None),
        "readme": BatchClassifier(pad_batch_to=16, mesh=None, mode="readme"),
        "package": BatchClassifier(mode="package"),
    }
    for (filename, content, mode), g in zip(cases, got):
        w = fixed[mode].classify_blobs([content], filenames=[filename])[0]
        assert (g.key, g.matcher, g.confidence) == (
            w.key,
            w.matcher,
            w.confidence,
        ), filename


# -- the pipelined BatchProject path --


def test_auto_pipeline_routes_and_stats(tmp_path):
    (tmp_path / "LICENSE").write_bytes(fixture_bytes("mit/LICENSE.txt"))
    (tmp_path / "README").write_bytes(
        fixture_bytes("license-with-readme-reference/README")
    )
    (tmp_path / "package.json").write_text('{"license": "MIT"}\n')
    (tmp_path / "main.c").write_text("int main(void) { return 0; }\n")
    paths = [
        str(tmp_path / n)
        for n in ["LICENSE", "README", "package.json", "main.c", "gone.c"]
    ]
    # gone.c does not exist AND is unrouted: auto must never try to read
    # it (no read_error row), exactly like find_files dropping score-0
    # names before load_file
    out = tmp_path / "out.jsonl"
    project = BatchProject(paths, batch_size=4, mesh=None, mode="auto")
    stats = project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [(r["key"], r["matcher"]) for r in rows] == [
        ("mit", "exact"),
        ("mit", "reference"),
        ("mit", "npmbower"),
        (None, None),
        (None, None),
    ]
    assert "error" not in rows[4]  # never read -> no read_error
    assert stats.read_errors == 0
    assert stats.routed == {
        "license": 1,
        "readme": 1,
        "package": 1,
        "none": 2,
    }
    assert stats.prefiltered_exact == 1
    assert stats.reference_matched == 1
    assert stats.package_matched == 1
    assert stats.unmatched == 2
    assert "routed" in stats.as_dict()


def test_fixed_mode_stats_keep_their_shape(tmp_path):
    p = tmp_path / "LICENSE"
    p.write_bytes(fixture_bytes("mit/LICENSE.txt"))
    project = BatchProject([str(p)], batch_size=4, mesh=None)
    project.run(str(tmp_path / "out.jsonl"), resume=False)
    assert "routed" not in project.stats.as_dict()


def test_auto_dedupe_key_carries_route(tmp_path):
    """Identical bytes under names that route differently must never
    share a cached result: full MIT text is an Exact match as LICENSE
    but has no '## License' section as README."""
    mit = fixture_bytes("mit/LICENSE.txt")
    for i in range(2):
        d = tmp_path / f"r{i}"
        d.mkdir()
        (d / "LICENSE").write_bytes(mit)
        (d / "README").write_bytes(mit)
    paths = []
    for i in range(2):
        paths += [
            str(tmp_path / f"r{i}" / "LICENSE"),
            str(tmp_path / f"r{i}" / "README"),
        ]
    out = tmp_path / "out.jsonl"
    project = BatchProject(
        paths, batch_size=1, workers=1, inflight=1, mode="auto"
    )
    stats = project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [(r["key"], r["matcher"]) for r in rows] == [
        ("mit", "exact"),
        (None, None),
        ("mit", "exact"),
        (None, None),
    ]
    # repeats of each (route, content) pair DO hit the cache
    assert stats.dedupe_hits == 2


def test_auto_closest_only_on_dice_routed_rows(tmp_path):
    near = fixture_bytes("mit/LICENSE.txt") + b"\nnudged off exact\n"
    (tmp_path / "LICENSE").write_bytes(near)
    (tmp_path / "package.json").write_text('{"license": "MIT"}\n')
    paths = [str(tmp_path / "LICENSE"), str(tmp_path / "package.json")]
    out = tmp_path / "out.jsonl"
    project = BatchProject(
        paths, batch_size=4, mode="auto", closest=2, threshold=90
    )
    project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert rows[0]["key"] == "mit" and len(rows[0]["closest"]) == 2
    assert rows[1]["matcher"] == "npmbower" and "closest" not in rows[1]


def test_cli_batch_detect_auto(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    (tmp_path / "LICENSE").write_bytes(fixture_bytes("mit/LICENSE.txt"))
    (tmp_path / "main.py").write_text("print('hello')\n")
    manifest = tmp_path / "manifest.txt"
    manifest.write_text(f"{tmp_path / 'LICENSE'}\n{tmp_path / 'main.py'}\n")
    assert main(["batch-detect", str(manifest), "--mode", "auto"]) == 0
    rows = [
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    ]
    assert rows[0]["key"] == "mit"
    assert rows[1]["key"] is None
