"""Multi-host (DCN) batch classification: manifest striping, per-host
output shards, env-driven `jax.distributed` bootstrap, and per-shard
resume — validated with a real 2-process CPU cluster (the fake-backend
discipline of the reference's WebMock tests, applied to multi-node)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from licensee_tpu.parallel.distributed import manifest_stripe, shard_output_path
from tests.conftest import fixture_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- pure striping math --

def test_manifest_stripe_covers_everything_contiguously():
    for n in (0, 1, 7, 8, 64, 65):
        for world in (1, 2, 3, 8):
            spans = [manifest_stripe(n, i, world) for i in range(world)]
            # contiguous, ordered, disjoint, complete
            assert spans[0][0] == 0
            assert spans[-1][1] == n
            for (a_lo, a_hi), (b_lo, b_hi) in zip(spans, spans[1:]):
                assert a_hi == b_lo
            sizes = [hi - lo for lo, hi in spans]
            assert max(sizes) - min(sizes) <= 1  # balanced


def test_manifest_stripe_rejects_bad_rank():
    with pytest.raises(ValueError):
        manifest_stripe(10, 2, 2)
    with pytest.raises(ValueError):
        manifest_stripe(10, -1, 2)


def test_shard_output_path():
    assert shard_output_path("out.jsonl", 0, 1) == "out.jsonl"
    assert (
        shard_output_path("out.jsonl", 1, 2) == "out.jsonl.shard-00001-of-00002"
    )


def test_batch_project_stripes_manifest():
    from licensee_tpu.projects.batch_project import BatchProject

    paths = [f"/nope/LICENSE_{i}" for i in range(10)]
    p0 = BatchProject(paths, process_index=0, process_count=2, mesh=None)
    p1 = BatchProject(paths, process_index=1, process_count=2, mesh=None)
    assert p0.paths == paths[:5]
    assert p1.paths == paths[5:]


# -- the real 2-process cluster --

CHILD = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})

    from licensee_tpu.parallel.distributed import maybe_initialize

    # the chips-split-per-process recipe: LICENSEE_TPU_VISIBLE_CHIPS (set
    # by the launcher per rank) gives THIS process its chip subset; on
    # CPU the same plumbing rehearses it as a virtual local device count,
    # so each child builds a real >=2-device local data mesh and scores
    # its stripe through the sharded scorer
    process_index, process_count = maybe_initialize()
    assert process_count == 2, process_count
    n_chips = len(os.environ["LICENSEE_TPU_VISIBLE_CHIPS"].split(","))
    assert len(jax.local_devices()) == n_chips, jax.local_devices()

    from licensee_tpu.projects.batch_project import BatchProject

    with open(sys.argv[1], encoding="utf-8") as f:
        paths = [line.strip() for line in f if line.strip()]
    mode = sys.argv[3] if len(sys.argv) > 3 else "license"
    project = BatchProject(paths, batch_size=4, mesh="auto", mode=mode)
    assert project.process_index == process_index
    mesh = project.classifier.mesh
    if mode != "package":  # package mode is host-only by design
        assert mesh is not None and mesh.shape["data"] == n_chips, mesh
    stats = project.run(sys.argv[2], resume=True)
    print(json.dumps({{"rank": process_index, "total": stats.total,
                       "routed": stats.routed}}))
    """
).format(repo=REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_cluster(manifest: str, output: str, port: int, mode="license"):
    procs = []
    for rank in (0, 1):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "LICENSEE_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LICENSEE_TPU_NUM_PROCESSES": "2",
            "LICENSEE_TPU_PROCESS_ID": str(rank),
            # chips split per process: rank 0 gets chips 0-1, rank 1
            # gets 2-3 (on CPU this becomes 2 virtual local devices per
            # child — the v5e-8 co-located-process launch, rehearsed)
            "LICENSEE_TPU_VISIBLE_CHIPS": "0,1" if rank == 0 else "2,3",
        }
        env.pop("XLA_FLAGS", None)  # the child derives its own count
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", CHILD, manifest, output, mode],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO,
            )
        )
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"rank failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return outs


def test_two_process_cluster_classifies_split_manifest(tmp_path):
    # a manifest whose rows are known fixtures
    contents = [
        fixture_path("mit/LICENSE.txt"),
        fixture_path("bsd-2-author/LICENSE"),
        fixture_path("cc-by-nd/LICENSE"),
        fixture_path("mit-with-copyright/LICENSE"),
        fixture_path("mit/LICENSE.txt"),
        fixture_path("bsd-2-author/LICENSE"),
    ]
    manifest = tmp_path / "manifest.txt"
    manifest.write_text("\n".join(contents) + "\n")
    output = str(tmp_path / "out.jsonl")

    stats = _run_cluster(str(manifest), output, _free_port())
    assert sorted(s["rank"] for s in stats) == [0, 1]
    assert sum(s["total"] for s in stats) == len(contents)

    shard0 = f"{output}.shard-00000-of-00002"
    shard1 = f"{output}.shard-00001-of-00002"
    rows0 = [json.loads(l) for l in open(shard0, encoding="utf-8")]
    rows1 = [json.loads(l) for l in open(shard1, encoding="utf-8")]
    assert [r["path"] for r in rows0] == contents[:3]
    assert [r["path"] for r in rows1] == contents[3:]

    # the union agrees with a single-process run
    from licensee_tpu.projects.batch_project import BatchProject

    single_out = str(tmp_path / "single.jsonl")
    BatchProject(contents, batch_size=4, mesh=None).run(single_out)
    single = [json.loads(l) for l in open(single_out, encoding="utf-8")]
    assert rows0 + rows1 == single

    # -- per-shard resume: tear shard 1's tail, rerun the cluster --
    full1 = open(shard1, encoding="utf-8").read()
    torn = full1[: full1.rindex('{"path"') + 15]  # torn final record
    with open(shard1, "w", encoding="utf-8") as f:
        f.write(torn)

    stats2 = _run_cluster(str(manifest), output, _free_port())
    by_rank = {s["rank"]: s for s in stats2}
    assert by_rank[0]["total"] == 0  # shard 0 complete: nothing re-done
    assert by_rank[1]["total"] == 1  # only the torn row was re-classified
    rows1b = [json.loads(l) for l in open(shard1, encoding="utf-8")]
    assert rows1b == rows1


def test_two_process_cluster_mode_auto_mixed_manifest(tmp_path):
    """BASELINE config 5, multi-host: a MIXED manifest stripes across two
    processes, each routing per filename (--mode auto), shards union to
    the single-process answer, per-route stats split per host."""
    (tmp_path / "LICENSE").write_bytes(
        open(fixture_path("mit/LICENSE.txt"), "rb").read()
    )
    (tmp_path / "package.json").write_text('{"license": "Apache-2.0"}\n')
    (tmp_path / "README").write_bytes(
        open(
            fixture_path("license-with-readme-reference/README"), "rb"
        ).read()
    )
    (tmp_path / "main.c").write_text("int main(void) { return 0; }\n")
    contents = [
        str(tmp_path / "LICENSE"),
        str(tmp_path / "main.c"),
        str(tmp_path / "package.json"),
        str(tmp_path / "README"),
        str(tmp_path / "gone.h"),  # unrouted AND missing: never read
        str(tmp_path / "LICENSE"),
    ]
    manifest = tmp_path / "manifest.txt"
    manifest.write_text("\n".join(contents) + "\n")
    output = str(tmp_path / "out.jsonl")

    stats = _run_cluster(str(manifest), output, _free_port(), mode="auto")
    by_rank = {s["rank"]: s for s in stats}
    assert by_rank[0]["routed"] == {"license": 1, "none": 1, "package": 1}
    assert by_rank[1]["routed"] == {"readme": 1, "none": 1, "license": 1}

    rows = []
    for shard in (0, 1):
        path = f"{output}.shard-0000{shard}-of-00002"
        rows += [json.loads(l) for l in open(path, encoding="utf-8")]
    assert [r["path"] for r in rows] == contents
    assert [(r["key"], r["matcher"]) for r in rows] == [
        ("mit", "exact"),
        (None, None),
        ("apache-2.0", "npmbower"),
        ("mit", "reference"),
        (None, None),
        ("mit", "exact"),
    ]
    assert "error" not in rows[4]  # gone.h skipped unread on its host

    # union agrees with one single-process auto pass
    from licensee_tpu.projects.batch_project import BatchProject

    single_out = str(tmp_path / "single.jsonl")
    BatchProject(contents, batch_size=4, mesh=None, mode="auto").run(
        single_out, resume=False
    )
    single = [json.loads(l) for l in open(single_out, encoding="utf-8")]
    assert rows == single


def test_from_manifest_file_materializes_only_the_stripe(tmp_path):
    """Each host loads only its own span of the manifest (the 50M-line
    config must not cost every host the whole path list)."""
    from licensee_tpu.projects.batch_project import BatchProject

    manifest = tmp_path / "m.txt"
    manifest.write_text(
        "\n".join(f"/nope/L_{i}" for i in range(10)) + "\n\n"
    )
    p0 = BatchProject.from_manifest_file(
        str(manifest), process_index=0, process_count=2, mesh=None
    )
    p1 = BatchProject.from_manifest_file(
        str(manifest), process_index=1, process_count=2, mesh=None
    )
    assert p0.paths == [f"/nope/L_{i}" for i in range(5)]
    assert p1.paths == [f"/nope/L_{i}" for i in range(5, 10)]

    single = BatchProject.from_manifest_file(str(manifest), mesh=None)
    assert single.paths == p0.paths + p1.paths


# -- per-process chip visibility (the chips-split-per-process recipe) --

def test_apply_visible_chips_unset_is_noop():
    from licensee_tpu.parallel import distributed

    assert distributed.apply_visible_chips(env={}) is None


def test_apply_visible_chips_rejects_empty_and_live_backend(monkeypatch):
    from licensee_tpu.parallel import distributed

    with pytest.raises(ValueError):
        distributed.apply_visible_chips(
            env={"LICENSEE_TPU_VISIBLE_CHIPS": " , "}
        )
    # this test process has a live CPU backend (conftest) and no prior
    # successful apply: setting chips on the PROCESS env now must
    # refuse loudly, not silently fail to take effect
    if distributed._chips_applied is None:
        import jax

        jax.devices()  # ensure the backend really is live
        monkeypatch.setenv("LICENSEE_TPU_VISIBLE_CHIPS", "0")
        with pytest.raises(RuntimeError):
            distributed.apply_visible_chips()
    # a DICT env is a dry run or a CHILD's environment (the fleet
    # supervisor derives worker envs from a process whose own backend
    # is live): the guard must NOT fire, and the derivation lands in
    # the dict only
    env = {"LICENSEE_TPU_VISIBLE_CHIPS": "0,1"}
    assert distributed.apply_visible_chips(env=env) == ["0", "1"]
    assert env["TPU_VISIBLE_DEVICES"] == "0,1"
    assert os.environ.get("TPU_VISIBLE_DEVICES") != "0,1"


def test_apply_visible_chips_exports_runtime_vars():
    """In a fresh interpreter the env var becomes TPU_VISIBLE_DEVICES +
    a matching CPU virtual-device count, and jax sees exactly that many
    local devices."""
    child = textwrap.dedent(
        """
        import json, os, sys
        sys.path.insert(0, %r)
        from licensee_tpu.parallel.distributed import apply_visible_chips

        # a conflicting pre-set TPU_VISIBLE_DEVICES must refuse loudly
        os.environ["TPU_VISIBLE_DEVICES"] = "9"
        try:
            apply_visible_chips()
        except RuntimeError:
            pass
        else:
            raise AssertionError("conflict not refused")
        del os.environ["TPU_VISIBLE_DEVICES"]

        # a leaked virtual-device count is rewritten, not kept
        os.environ["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8"
        )
        chips = apply_visible_chips()
        assert chips == ["4", "5", "6"], chips
        assert os.environ["TPU_VISIBLE_DEVICES"] == "4,5,6"
        assert "device_count=3" in os.environ["XLA_FLAGS"], (
            os.environ["XLA_FLAGS"]
        )
        assert apply_visible_chips() == chips  # idempotent

        # the libtpu co-location set (real-host contract)
        assert os.environ["TPU_PROCESS_PORT"] == "8477"
        assert os.environ["TPU_PROCESS_ADDRESSES"] == (
            "localhost:8476,localhost:8477"
        )
        assert os.environ["CLOUD_TPU_TASK_ID"] == "1"
        assert os.environ["TPU_PROCESS_BOUNDS"] == "1,2,1"
        assert os.environ["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "3,1,1"

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps({"n_local": len(jax.local_devices())}))
        """
        % REPO
    )
    env = {
        **os.environ,
        "LICENSEE_TPU_VISIBLE_CHIPS": "4,5,6",
        "LICENSEE_TPU_NUM_PROCESSES": "2",
        "LICENSEE_TPU_PROCESS_ID": "1",
        "LICENSEE_TPU_PROCESS_BOUNDS": "1,2,1",
        "LICENSEE_TPU_CHIPS_PER_PROCESS_BOUNDS": "3,1,1",
    }
    for k in ("XLA_FLAGS", "TPU_VISIBLE_DEVICES", "TPU_PROCESS_PORT",
              "TPU_PROCESS_ADDRESSES", "CLOUD_TPU_TASK_ID",
              "TPU_PROCESS_BOUNDS", "TPU_CHIPS_PER_PROCESS_BOUNDS",
              "LICENSEE_TPU_COORDINATOR"):
        env.pop(k, None)
    result = subprocess.run(
        [sys.executable, "-c", child], env=env, capture_output=True,
        text=True, cwd=REPO, timeout=120,
    )
    assert result.returncode == 0, result.stderr[-2000:]
    assert json.loads(result.stdout.strip().splitlines()[-1]) == {
        "n_local": 3
    }


def test_apply_visible_chips_dict_env_never_touches_os_environ():
    """Regression (ADVICE r5): with a caller-supplied dict env, the chip
    spec, the conflict check, and every write must go through THAT
    mapping — a dict-env dry run used to validate against (and mutate)
    os.environ instead."""
    child = textwrap.dedent(
        """
        import json, os, sys
        sys.path.insert(0, %r)
        from licensee_tpu.parallel import distributed

        # conflict inside the DICT env must refuse, even though
        # os.environ has no TPU_VISIBLE_DEVICES at all
        env = {
            "LICENSEE_TPU_VISIBLE_CHIPS": "4,5",
            "TPU_VISIBLE_DEVICES": "9",
        }
        try:
            distributed.apply_visible_chips(env=env)
        except RuntimeError:
            pass
        else:
            raise AssertionError("dict-env conflict not refused")
        assert "TPU_VISIBLE_DEVICES" not in os.environ

        # a consistent dict env is applied INTO the dict, with
        # os.environ untouched (including the co-location var set)
        env = {
            "LICENSEE_TPU_VISIBLE_CHIPS": "4,5",
            "LICENSEE_TPU_NUM_PROCESSES": "2",
            "LICENSEE_TPU_PROCESS_ID": "0",
        }
        before = dict(os.environ)
        chips = distributed.apply_visible_chips(env=env)
        assert chips == ["4", "5"], chips
        assert env["TPU_VISIBLE_DEVICES"] == "4,5"
        assert "device_count=2" in env["XLA_FLAGS"], env
        assert env["TPU_PROCESS_PORT"] == "8476"
        assert env["CLOUD_TPU_TASK_ID"] == "0"
        assert dict(os.environ) == before, "os.environ was mutated"
        print(json.dumps({"ok": True}))
        """
        % REPO
    )
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("LICENSEE_TPU_", "TPU_", "XLA_FLAGS"))
    }
    result = subprocess.run(
        [sys.executable, "-c", child],
        env=env,
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stderr[-2000:]
    assert json.loads(result.stdout.strip().splitlines()[-1]) == {"ok": True}


def test_chips_for_worker_partitions_disjoint_contiguous_ranges():
    """The fleet supervisor and the offline co-located launch derive
    worker chip subsets from ONE function: contiguous, disjoint,
    complete, in LICENSEE_TPU_VISIBLE_CHIPS string form."""
    from licensee_tpu.parallel.distributed import chips_for_worker

    assert chips_for_worker(0, 2) == ["0", "1"]
    assert chips_for_worker(3, 2) == ["6", "7"]
    assert chips_for_worker(1, 1) == ["1"]
    # a 4-worker x 2-chip fleet tiles the v5e-8 host exactly
    claimed = [c for w in range(4) for c in chips_for_worker(w, 2)]
    assert claimed == [str(c) for c in range(8)]
    with pytest.raises(ValueError):
        chips_for_worker(-1, 2)
    with pytest.raises(ValueError):
        chips_for_worker(0, 0)
