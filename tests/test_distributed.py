"""Multi-host (DCN) batch classification: manifest striping, per-host
output shards, env-driven `jax.distributed` bootstrap, and per-shard
resume — validated with a real 2-process CPU cluster (the fake-backend
discipline of the reference's WebMock tests, applied to multi-node)."""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from licensee_tpu.parallel.distributed import manifest_stripe, shard_output_path
from tests.conftest import fixture_path

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- pure striping math --

def test_manifest_stripe_covers_everything_contiguously():
    for n in (0, 1, 7, 8, 64, 65):
        for world in (1, 2, 3, 8):
            spans = [manifest_stripe(n, i, world) for i in range(world)]
            # contiguous, ordered, disjoint, complete
            assert spans[0][0] == 0
            assert spans[-1][1] == n
            for (a_lo, a_hi), (b_lo, b_hi) in zip(spans, spans[1:]):
                assert a_hi == b_lo
            sizes = [hi - lo for lo, hi in spans]
            assert max(sizes) - min(sizes) <= 1  # balanced


def test_manifest_stripe_rejects_bad_rank():
    with pytest.raises(ValueError):
        manifest_stripe(10, 2, 2)
    with pytest.raises(ValueError):
        manifest_stripe(10, -1, 2)


def test_shard_output_path():
    assert shard_output_path("out.jsonl", 0, 1) == "out.jsonl"
    assert (
        shard_output_path("out.jsonl", 1, 2) == "out.jsonl.shard-00001-of-00002"
    )


def test_batch_project_stripes_manifest():
    from licensee_tpu.projects.batch_project import BatchProject

    paths = [f"/nope/LICENSE_{i}" for i in range(10)]
    p0 = BatchProject(paths, process_index=0, process_count=2, mesh=None)
    p1 = BatchProject(paths, process_index=1, process_count=2, mesh=None)
    assert p0.paths == paths[:5]
    assert p1.paths == paths[5:]


# -- the real 2-process cluster --

CHILD = textwrap.dedent(
    """
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})

    from licensee_tpu.parallel.distributed import maybe_initialize

    process_index, process_count = maybe_initialize()
    assert process_count == 2, process_count

    from licensee_tpu.projects.batch_project import BatchProject

    with open(sys.argv[1], encoding="utf-8") as f:
        paths = [line.strip() for line in f if line.strip()]
    mode = sys.argv[3] if len(sys.argv) > 3 else "license"
    project = BatchProject(paths, batch_size=4, mesh=None, mode=mode)
    assert project.process_index == process_index
    stats = project.run(sys.argv[2], resume=True)
    print(json.dumps({{"rank": process_index, "total": stats.total,
                       "routed": stats.routed}}))
    """
).format(repo=REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_cluster(manifest: str, output: str, port: int, mode="license"):
    procs = []
    for rank in (0, 1):
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "LICENSEE_TPU_COORDINATOR": f"127.0.0.1:{port}",
            "LICENSEE_TPU_NUM_PROCESSES": "2",
            "LICENSEE_TPU_PROCESS_ID": str(rank),
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", CHILD, manifest, output, mode],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO,
            )
        )
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"rank failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))
    return outs


def test_two_process_cluster_classifies_split_manifest(tmp_path):
    # a manifest whose rows are known fixtures
    contents = [
        fixture_path("mit/LICENSE.txt"),
        fixture_path("bsd-2-author/LICENSE"),
        fixture_path("cc-by-nd/LICENSE"),
        fixture_path("mit-with-copyright/LICENSE"),
        fixture_path("mit/LICENSE.txt"),
        fixture_path("bsd-2-author/LICENSE"),
    ]
    manifest = tmp_path / "manifest.txt"
    manifest.write_text("\n".join(contents) + "\n")
    output = str(tmp_path / "out.jsonl")

    stats = _run_cluster(str(manifest), output, _free_port())
    assert sorted(s["rank"] for s in stats) == [0, 1]
    assert sum(s["total"] for s in stats) == len(contents)

    shard0 = f"{output}.shard-00000-of-00002"
    shard1 = f"{output}.shard-00001-of-00002"
    rows0 = [json.loads(l) for l in open(shard0, encoding="utf-8")]
    rows1 = [json.loads(l) for l in open(shard1, encoding="utf-8")]
    assert [r["path"] for r in rows0] == contents[:3]
    assert [r["path"] for r in rows1] == contents[3:]

    # the union agrees with a single-process run
    from licensee_tpu.projects.batch_project import BatchProject

    single_out = str(tmp_path / "single.jsonl")
    BatchProject(contents, batch_size=4, mesh=None).run(single_out)
    single = [json.loads(l) for l in open(single_out, encoding="utf-8")]
    assert rows0 + rows1 == single

    # -- per-shard resume: tear shard 1's tail, rerun the cluster --
    full1 = open(shard1, encoding="utf-8").read()
    torn = full1[: full1.rindex('{"path"') + 15]  # torn final record
    with open(shard1, "w", encoding="utf-8") as f:
        f.write(torn)

    stats2 = _run_cluster(str(manifest), output, _free_port())
    by_rank = {s["rank"]: s for s in stats2}
    assert by_rank[0]["total"] == 0  # shard 0 complete: nothing re-done
    assert by_rank[1]["total"] == 1  # only the torn row was re-classified
    rows1b = [json.loads(l) for l in open(shard1, encoding="utf-8")]
    assert rows1b == rows1


def test_two_process_cluster_mode_auto_mixed_manifest(tmp_path):
    """BASELINE config 5, multi-host: a MIXED manifest stripes across two
    processes, each routing per filename (--mode auto), shards union to
    the single-process answer, per-route stats split per host."""
    (tmp_path / "LICENSE").write_bytes(
        open(fixture_path("mit/LICENSE.txt"), "rb").read()
    )
    (tmp_path / "package.json").write_text('{"license": "Apache-2.0"}\n')
    (tmp_path / "README").write_bytes(
        open(
            fixture_path("license-with-readme-reference/README"), "rb"
        ).read()
    )
    (tmp_path / "main.c").write_text("int main(void) { return 0; }\n")
    contents = [
        str(tmp_path / "LICENSE"),
        str(tmp_path / "main.c"),
        str(tmp_path / "package.json"),
        str(tmp_path / "README"),
        str(tmp_path / "gone.h"),  # unrouted AND missing: never read
        str(tmp_path / "LICENSE"),
    ]
    manifest = tmp_path / "manifest.txt"
    manifest.write_text("\n".join(contents) + "\n")
    output = str(tmp_path / "out.jsonl")

    stats = _run_cluster(str(manifest), output, _free_port(), mode="auto")
    by_rank = {s["rank"]: s for s in stats}
    assert by_rank[0]["routed"] == {"license": 1, "none": 1, "package": 1}
    assert by_rank[1]["routed"] == {"readme": 1, "none": 1, "license": 1}

    rows = []
    for shard in (0, 1):
        path = f"{output}.shard-0000{shard}-of-00002"
        rows += [json.loads(l) for l in open(path, encoding="utf-8")]
    assert [r["path"] for r in rows] == contents
    assert [(r["key"], r["matcher"]) for r in rows] == [
        ("mit", "exact"),
        (None, None),
        ("apache-2.0", "npmbower"),
        ("mit", "reference"),
        (None, None),
        ("mit", "exact"),
    ]
    assert "error" not in rows[4]  # gone.h skipped unread on its host

    # union agrees with one single-process auto pass
    from licensee_tpu.projects.batch_project import BatchProject

    single_out = str(tmp_path / "single.jsonl")
    BatchProject(contents, batch_size=4, mesh=None, mode="auto").run(
        single_out, resume=False
    )
    single = [json.loads(l) for l in open(single_out, encoding="utf-8")]
    assert rows == single


def test_from_manifest_file_materializes_only_the_stripe(tmp_path):
    """Each host loads only its own span of the manifest (the 50M-line
    config must not cost every host the whole path list)."""
    from licensee_tpu.projects.batch_project import BatchProject

    manifest = tmp_path / "m.txt"
    manifest.write_text(
        "\n".join(f"/nope/L_{i}" for i in range(10)) + "\n\n"
    )
    p0 = BatchProject.from_manifest_file(
        str(manifest), process_index=0, process_count=2, mesh=None
    )
    p1 = BatchProject.from_manifest_file(
        str(manifest), process_index=1, process_count=2, mesh=None
    )
    assert p0.paths == [f"/nope/L_{i}" for i in range(5)]
    assert p1.paths == [f"/nope/L_{i}" for i in range(5, 10)]

    single = BatchProject.from_manifest_file(str(manifest), mesh=None)
    assert single.paths == p0.paths + p1.paths
