"""Fields / rules / meta model parity — ports of the reference's
`license_field_spec.rb`, `rule_spec.rb`, and `license_meta_spec.rb`
behavior pins that the fixture/golden suites don't already cover."""

from __future__ import annotations

from licensee_tpu.corpus.fields import LicenseField
from licensee_tpu.corpus.license import License
from licensee_tpu.corpus.meta import LicenseMeta
from licensee_tpu.corpus.rules import LicenseRules, Rule

# -- LicenseField (license_field_spec.rb) --


def test_field_all_and_keys():
    assert len(LicenseField.all()) == 7
    assert isinstance(LicenseField.all()[0], LicenseField)
    keys = LicenseField.keys()
    assert len(keys) == 7
    assert keys[0] == "fullname"


def test_field_find():
    assert LicenseField.find("year").description == "The current year"


def test_field_from_array():
    fields = LicenseField.from_array(["year", "fullname"])
    assert [f.name for f in fields] == ["year", "fullname"]


def test_field_from_content_pulls_known_fields_in_order():
    fields = LicenseField.from_content("Foo [year] bar [baz] [fullname]")
    assert [f.key for f in fields] == ["year", "fullname"]


def test_field_labels():
    assert LicenseField("foo", "bar").label == "Foo"
    assert str(LicenseField("foo", "bar")) == "Foo"
    # fullname converts to two words (license_field.rb label special case)
    assert LicenseField("fullname", "x").label == "Full name"


def test_field_raw_text():
    assert LicenseField("fullname").raw_text == "[fullname]"


def test_no_fields_for_bodyless_license():
    assert License.find("other").fields == []


# -- Rule (rule_spec.rb) --


def test_rule_groups_and_raw_rules():
    groups = ["permissions", "conditions", "limitations"]
    assert Rule.groups() == groups
    for g in groups:
        assert g in Rule.raw_rules()


def test_rule_all_count_and_order():
    rules = Rule.all()
    assert len(rules) == 17
    assert rules[0].tag == "commercial-use"


def test_rule_find_by_tag_and_group_disambiguates():
    # patent-use exists in BOTH limitations and permissions with
    # different descriptions (rule_spec.rb:44-53)
    lim = Rule.find_by_tag_and_group("patent-use", "limitations")
    assert "does NOT grant" in lim.description
    per = Rule.find_by_tag_and_group("patent-use", "permissions")
    assert "an express grant of patent rights" in per.description


def test_rule_to_h():
    h = Rule.all()[0].to_h()
    assert h == {
        "tag": "commercial-use",
        "label": "Commercial use",
        "description": (
            "The licensed material and derivatives may be used for "
            "commercial purposes."
        ),
    }


# -- LicenseMeta (license_meta_spec.rb) --


def test_meta_defaults():
    meta = LicenseMeta.from_hash({})
    assert meta["featured"] is False
    assert meta["hidden"] is True


def test_meta_from_hash_sets_values():
    meta = LicenseMeta.from_hash(
        {"title": "Test license", "description": "A test license"}
    )
    assert meta.title == "Test license"
    assert meta.description == "A test license"


def test_meta_hash_and_predicate_access():
    meta = License.find("mit").meta
    assert meta["spdx-id"] == "MIT"
    assert meta.hidden_q is False
    assert meta.featured_q in (True, False)


# -- LicenseRules resolution (license_rules_spec.rb) --


def test_license_rules_from_meta_resolves_groups():
    rules = LicenseRules.from_license(License.find("mit"))
    assert [r.tag for r in rules["permissions"]]
    assert all(isinstance(r, Rule) for r in rules.flatten())
    # key_q mirrors Ruby's respond_to handling for rule groups
    assert rules.key_q("permissions")
    assert not rules.key_q("nonsense")
