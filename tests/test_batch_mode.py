"""batch-detect --mode readme|package: the ReadmeFile / PackageManagerFile
chains at batch scale (north-star config 5: 50M mixed files).

Parity targets: `readme_file.rb` (section extraction + Reference fallback,
exercised by spec/licensee/project_files/readme_file_spec.rb) and
`package_manager_file.rb` (filename-dispatched package matchers).
"""

from __future__ import annotations

import json
import os

import pytest

from licensee_tpu.kernels.batch import BatchClassifier
from licensee_tpu.projects.batch_project import BatchProject
from tests.conftest import fixture_path


def fixture_bytes(name: str) -> bytes:
    with open(fixture_path(name), "rb") as f:
        return f.read()


@pytest.fixture(scope="module")
def readme_clf():
    return BatchClassifier(pad_batch_to=16, mesh=None, mode="readme")


@pytest.fixture(scope="module")
def package_clf():
    return BatchClassifier(mode="package")


# -- readme mode --


def test_readme_full_text_section_dices(readme_clf):
    # full MIT text under "## License" -> extracted, then Exact fires
    # first in the chain (license_file.rb order: Copyright, Exact, Dice)
    results = readme_clf.classify_blobs([fixture_bytes("readme/README.md")])
    assert results[0].key == "mit"
    assert results[0].matcher == "exact"
    assert results[0].confidence >= 98


def test_readme_reference_fallback(readme_clf):
    # a title-only mention matches via the Reference matcher at 90
    # (readme_file.rb:32-34; matchers/reference.rb)
    results = readme_clf.classify_blobs(
        [fixture_bytes("license-with-readme-reference/README")]
    )
    assert results[0].key == "mit"
    assert results[0].matcher == "reference"
    assert results[0].confidence == 90.0


def test_readme_without_license_section_is_unmatched(readme_clf):
    results = readme_clf.classify_blobs(
        [b"# Project\n\nJust a readme, no license header.\n"]
    )
    assert results[0].key is None
    assert results[0].matcher is None


def test_readme_mode_agrees_with_scalar_chain(readme_clf):
    """Every README fixture through the batch readme chain must equal the
    scalar ReadmeFile chain (the project wiring of project.rb:74-80)."""
    from licensee_tpu.project_files.project_file import sanitize_content
    from licensee_tpu.project_files.readme_file import ReadmeFile

    names = [
        "readme/README.md",
        "mit/README.md",
        "license-with-readme-reference/README",
        "apache-with-readme-notice/README.md",
        "readme-invalid-encoding/README.md",
        "license-folder/README.md",
    ]
    contents = [fixture_bytes(n) for n in names]
    batch = readme_clf.classify_blobs(contents)
    for name, raw, got in zip(names, contents, batch):
        section = ReadmeFile.license_content(sanitize_content(raw))
        if not section:
            want_key, want_matcher = None, None
        else:
            file = ReadmeFile(section, os.path.basename(name))
            matcher = file.matcher
            want_key = file.license.key if file.license else None
            want_matcher = matcher.name if matcher else None
        assert got.key == want_key, name
        assert got.matcher == want_matcher, name


def test_readme_html_converted_before_extraction(readme_clf):
    """An HTML readme is markdown-converted BEFORE the CONTENT_REGEX scan
    (the header regex understands markdown, not <h2> tags), and the
    extracted section is not converted a second time.  The reference
    never scores .html as a README (readme_file.rb:6-12), so this corner
    is ours to define: convert-then-extract is the consistent order."""
    html = (
        b"<html><body><h1>Project</h1><p>stuff</p>"
        b"<h2>License</h2>"
        b"<p>Licensed under the MIT License.</p>"
        b"</body></html>"
    )
    results = readme_clf.classify_blobs([html], filenames=["README.html"])
    assert results[0].key == "mit"
    assert results[0].matcher == "reference"

    # same content under a non-HTML name: raw angle brackets, no
    # markdown header -> no section -> unmatched (order-consistency
    # check: the HTML path must come from the conversion, not luck)
    results = readme_clf.classify_blobs([html], filenames=["README.md"])
    assert results[0].key is None


def test_reference_match_union_agrees_with_naive_chain(monkeypatch):
    """The batched union fast path must answer EXACTLY like the naive
    first-in-pool-order chain (matchers/reference.rb:7-11) — including
    shadow cases where an early-pool license's only hit lies inside
    another alternative's matched span, and non-ASCII adjacency where
    rb()'s re.A word boundaries differ from Unicode ones ('MITライセンス'
    is the standard Japanese README phrasing: ASCII \\b sees a boundary
    before 'ラ', Unicode \\b does not).  Both the native-PCRE2 and the
    pure-Python scan paths are pinned."""
    import licensee_tpu.kernels.batch as batch_mod
    from licensee_tpu.corpus.license import License

    def naive(section):
        for lic in License.all(hidden=True, pseudo=False):
            if lic.reference_regex.search(section):
                return lic
        return None

    pool = License.all(hidden=True, pseudo=False)
    sections = []
    for lic in pool:
        sections.append(f"Licensed under the {lic.name}.")
        if lic.meta.source:
            sections.append(f"See {lic.meta.source} for details.")
    sections += [
        "",
        "no license mentioned here at all",
        "see the LICENSE file",
        "GNU Affero General Public License v3.0",
        "GNU General Public License as published by the FSF",
        "dual-licensed: MIT License or Apache License 2.0",
        "the gnu lesser general public license, version 2.1 only",
        "BSD 3-Clause Clear License",
        "Creative Commons Attribution Share Alike 4.0 International",
        "MITライセンス",
        "ライセンスはMIT Licenseです",
        "über die Apache License 2.0 lizenziert",
        "KMIT License",  # Kelvin sign abutting the title
    ]
    paths = [None]  # the pure-Python union scan
    if batch_mod._refscan_native() is not None:
        paths.append(batch_mod._refscan_native())
    for path in paths:
        monkeypatch.setattr(
            batch_mod, "_refscan_native", lambda p=path: p
        )
        for s in sections:
            got = BatchClassifier._reference_match(s)
            want = naive(s)
            assert (got.key if got else None) == (
                want.key if want else None
            ), (s, "native" if path else "python")


def test_reference_match_thread_safe():
    """The process-global refscan handle must serve concurrent scans:
    pipe_refscan_min allocates per-call match data, so parallel
    classify_blobs callers cannot tear each other's ovectors."""
    from concurrent.futures import ThreadPoolExecutor

    sections = [
        "Released under the MIT License.",
        "see the LICENSE file",
        "GNU Affero General Public License v3.0",
        "Licensed under the Apache License 2.0.",
        "no license mentioned here at all " * 20,
        "BSD 3-Clause Clear License",
    ] * 40

    def key(s):
        lic = BatchClassifier._reference_match(s)
        return lic.key if lic else None

    want = [key(s) for s in sections]
    with ThreadPoolExecutor(max_workers=8) as pool:
        got = list(pool.map(key, sections))
    assert got == want


# -- package mode --


def test_package_gemspec(package_clf):
    results = package_clf.classify_blobs(
        [fixture_bytes("gemspec/project._gemspec")],
        filenames=["project.gemspec"],
    )
    assert results[0].key == "mit"
    assert results[0].matcher == "gemspec"
    assert results[0].confidence == 90.0


def test_package_mixed_filenames(package_clf):
    contents = [
        b'{\n  "license": "MIT"\n}\n',
        b'[package]\nname = "x"\nlicense = "Apache-2.0"\n',
        b"Package: xyz\nLicense: MIT + file LICENSE\n",
        b'{\n  "license": "NotARealLicense"\n}\n',
        b"no matcher claims this filename",
    ]
    filenames = [
        "package.json",
        "Cargo.toml",
        "DESCRIPTION",
        "package.json",
        "README.md",
    ]
    results = package_clf.classify_blobs(contents, filenames=filenames)
    assert [(r.key, r.matcher) for r in results] == [
        ("mit", "npmbower"),
        ("apache-2.0", "cargo"),
        ("mit", "cran"),
        ("other", "npmbower"),  # declared-but-unknown -> other (package.rb)
        (None, None),
    ]


def test_package_mode_needs_no_device(package_clf):
    # the device scorer is never built: package matching is host regexes
    assert package_clf._fn is None
    assert package_clf.arrays is None


# -- BatchProject pipeline + CLI --


def test_batch_project_readme_pipeline(tmp_path):
    import shutil

    paths = []
    for i, name in enumerate(
        ["readme/README.md", "license-with-readme-reference/README"]
    ):
        dst = tmp_path / f"README_{i}.md"
        shutil.copy(fixture_path(name), dst)
        paths.append(str(dst))
    out = tmp_path / "out.jsonl"
    project = BatchProject(paths, batch_size=4, mesh=None, mode="readme")
    stats = project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["key"] for r in rows] == ["mit", "mit"]
    assert [r["matcher"] for r in rows] == ["exact", "reference"]
    assert stats.prefiltered_exact == 1
    assert stats.reference_matched == 1


def test_cli_batch_detect_package_mode(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    pkg = tmp_path / "package.json"
    pkg.write_text('{"license": "MIT"}\n')
    manifest = tmp_path / "manifest.txt"
    manifest.write_text(f"{pkg}\n")
    assert main(["batch-detect", str(manifest), "--mode", "package"]) == 0
    rows = [
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    ]
    assert rows[0]["key"] == "mit"
    assert rows[0]["matcher"] == "npmbower"
