"""Corpus refresh tooling (script/vendor-* + golden regeneration).

Parity targets: /root/reference/script/vendor-licenses:1-11,
vendor-spdx:1-20, hash-licenses:1-14, dump-fixture-licenses:1-25.  The
drift tests make the shipped corpus provably reproducible: regenerated
goldens equal the shipped bytes, and re-vendoring from a checkout
shaped like the current vendor tree is byte-identical.
"""

from __future__ import annotations

import filecmp
import os
import shutil
import subprocess
import sys

import yaml

from licensee_tpu.corpus import vendoring


def _trees_identical(a: str, b: str) -> bool:
    cmp = filecmp.dircmp(a, b)
    if cmp.left_only or cmp.right_only or cmp.funny_files:
        return False
    # shallow=False: copytree preserves mtimes, so the default size+mtime
    # comparison would never read a byte — the claim here is BYTE parity
    _, mismatch, errors = filecmp.cmpfiles(
        a, b, cmp.common_files, shallow=False
    )
    if mismatch or errors:
        return False
    return all(
        _trees_identical(os.path.join(a, d), os.path.join(b, d))
        for d in cmp.common_dirs
    )


def test_license_hashes_golden_is_regenerable():
    with open(
        os.path.join(vendoring.FIXTURES_DIR, "license-hashes.json"),
        encoding="utf-8",
    ) as f:
        shipped = f.read()
    assert vendoring.license_hashes_json() == shipped


def test_fixtures_yml_golden_is_regenerable():
    with open(
        os.path.join(vendoring.FIXTURES_DIR, "fixtures.yml"),
        encoding="utf-8",
    ) as f:
        shipped = f.read()
    regenerated = vendoring.fixtures_yml()
    assert regenerated == shipped
    # and it parses to the exact mapping the fixture tests consume
    assert yaml.safe_load(regenerated) == yaml.safe_load(shipped)


def test_vendor_licenses_roundtrip(tmp_path):
    """A checkout holding the current vendored trees re-vendors to a
    byte-identical vendor dir (wipe-and-replace semantics included)."""
    checkout = tmp_path / "choosealicense.com"
    checkout.mkdir()
    for sub in ("_data", "_licenses"):
        shutil.copytree(
            os.path.join(vendoring.VENDOR_LICENSES_DIR, sub),
            checkout / sub,
        )
    out = tmp_path / "vendored"
    (out / "stale").mkdir(parents=True)  # must be wiped
    copied = vendoring.vendor_licenses(str(checkout), str(out))
    assert copied and _trees_identical(
        str(out), vendoring.VENDOR_LICENSES_DIR
    )


def test_vendor_spdx_roundtrip(tmp_path):
    checkout = tmp_path / "license-list-XML"
    shutil.copytree(
        os.path.join(vendoring.VENDOR_SPDX_DIR, "src"), checkout / "src"
    )
    out = tmp_path / "vendored"
    copied = vendoring.vendor_spdx(str(checkout), str(out))
    assert copied and _trees_identical(str(out), vendoring.VENDOR_SPDX_DIR)


def test_vendor_spdx_include_list_tracks_alternate_dir(tmp_path):
    """An alternate-dir refresh must grep its OWN choosealicense tree for
    the spdx-id include list, not the repo default (which would silently
    skip newly added/removed licenses)."""
    checkout = tmp_path / "ca"
    checkout.mkdir()
    for sub in ("_data", "_licenses"):
        shutil.copytree(
            os.path.join(vendoring.VENDOR_LICENSES_DIR, sub),
            checkout / sub,
        )
    dropped = sorted((checkout / "_licenses").iterdir())[0]
    dropped_id = vendoring.vendored_spdx_ids()[0]
    dropped.unlink()
    alt = tmp_path / "alt-ca"
    vendoring.vendor_licenses(str(checkout), str(alt))

    llx = tmp_path / "llx"
    shutil.copytree(
        os.path.join(vendoring.VENDOR_SPDX_DIR, "src"), llx / "src"
    )
    out = tmp_path / "alt-spdx"
    copied = vendoring.vendor_spdx(
        str(llx), str(out), licenses_vendor_dir=str(alt)
    )
    ids = {os.path.basename(p)[:-4] for p in copied}
    assert dropped_id not in ids
    assert len(ids) == len(vendoring.vendored_spdx_ids()) - 1


def test_vendor_spdx_rejects_partial_checkout(tmp_path):
    import pytest

    checkout = tmp_path / "license-list-XML"
    shutil.copytree(
        os.path.join(vendoring.VENDOR_SPDX_DIR, "src"), checkout / "src"
    )
    ids = vendoring.vendored_spdx_ids()
    (checkout / "src" / f"{ids[0]}.xml").unlink()
    with pytest.raises(FileNotFoundError):
        vendoring.vendor_spdx(str(checkout), str(tmp_path / "out"))


def test_vendor_licenses_rejects_non_checkout(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError):
        vendoring.vendor_licenses(str(tmp_path), str(tmp_path / "out"))


def test_scripts_run_as_executables(tmp_path):
    """The thin script wrappers execute standalone (they bootstrap
    sys.path themselves); vendor-licenses end-to-end via subprocess."""
    checkout = tmp_path / "checkout"
    checkout.mkdir()
    for sub in ("_data", "_licenses"):
        shutil.copytree(
            os.path.join(vendoring.VENDOR_LICENSES_DIR, sub),
            checkout / sub,
        )
    script = os.path.join(vendoring.REPO_ROOT, "script", "vendor-licenses")
    # a scratch VENDOR_DIR: the test must never rmtree the repo's real
    # vendor tree (a mid-run failure would take the whole suite down)
    out = tmp_path / "out-vendor"
    result = subprocess.run(
        [sys.executable, script, str(checkout), str(out)],
        cwd=vendoring.REPO_ROOT, capture_output=True, text=True,
    )
    assert result.returncode == 0, result.stderr
    assert _trees_identical(str(out), vendoring.VENDOR_LICENSES_DIR)


def test_lint_is_green():
    """script/lint (the rubocop slot of script/cibuild) passes on the
    shipped tree — keeps the one-command CI gate green by construction."""
    result = subprocess.run(
        [sys.executable, os.path.join(vendoring.REPO_ROOT, "script", "lint")],
        cwd=vendoring.REPO_ROOT, capture_output=True, text=True,
    )
    assert result.returncode == 0, result.stdout + result.stderr


def test_cibuild_exists_and_is_wired():
    """script/cibuild is the documented one-command gate (reference
    script/cibuild:5-9: rspec + rubocop + gem build).  Running it here
    would recurse into pytest; assert the contract instead: executable,
    and staging pytest + lint + wheel build in that order."""
    path = os.path.join(vendoring.REPO_ROOT, "script", "cibuild")
    assert os.access(path, os.X_OK)
    with open(path, encoding="utf-8") as f:
        text = f.read()
    assert text.startswith("#!/bin/sh")
    assert "set -e" in text
    # order the real invocations, not the header comment
    code = "\n".join(
        line
        for line in text.splitlines()
        if not line.lstrip().startswith("#")
    )
    assert (
        code.index("python -m pytest")
        < code.index("serve --selftest")
        < code.index("python script/lint")
        < code.index("python -m build")
    )
