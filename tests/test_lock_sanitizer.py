"""The lock-order sanitizer (tests/lock_sanitizer.py): inversion
detection, clean-order silence, and compatibility with the stdlib
primitives the product code builds on the wrapped locks
(``threading.Condition``, re-entrant RLocks, ``queue.Queue``)."""

from __future__ import annotations

import threading

from lock_sanitizer import LockOrderSanitizer


def _run_in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


def test_detects_inversion_across_threads():
    san = LockOrderSanitizer()
    a = san.make_lock()
    b = san.make_lock()

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    _run_in_thread(ab)
    _run_in_thread(ba)
    inversions = san.check()
    assert inversions, "A->B then B->A must be reported"
    assert "lock-order inversion" in inversions[0]


def test_consistent_order_is_silent():
    san = LockOrderSanitizer()
    a = san.make_lock()
    b = san.make_lock()

    def ab():
        with a:
            with b:
                pass

    for _ in range(3):
        _run_in_thread(ab)
    assert san.check() == []


def test_same_lock_reacquire_is_not_an_edge():
    san = LockOrderSanitizer()
    r = san.make_rlock()
    with r:
        with r:
            pass
    assert san.check() == []


def test_condition_over_tracked_lock():
    """The scheduler's Condition(self._lock) shape: wait/notify through
    the wrapper must work and release the lock while waiting."""
    san = LockOrderSanitizer()
    lock = san.make_lock()
    cond = threading.Condition(lock)
    fired = []

    def waiter():
        with cond:
            while not fired:
                cond.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    for _ in range(1000):
        if t.is_alive():
            break
    with cond:
        fired.append(True)
        cond.notify_all()
    t.join(timeout=5)
    assert not t.is_alive()
    assert san.check() == []


def test_condition_over_tracked_rlock():
    san = LockOrderSanitizer()
    cond = threading.Condition(san.make_rlock())
    with cond:
        cond.notify_all()
    assert san.check() == []


def test_condition_wait_from_recursive_hold_keeps_tracking():
    """wait() while holding the RLock at depth 2 must restore the
    wrapper's recursion count — a depth mismatch would silently stop
    edge recording for that lock afterwards."""
    san = LockOrderSanitizer()
    lock = san.make_rlock()
    cond = threading.Condition(lock)
    with cond:
        with cond:
            cond.wait(timeout=0.01)
    # tracking still works: the lock still records ordering edges,
    # so a subsequent inversion through it is caught
    other = san.make_rlock()
    with lock:
        with other:
            pass
    with other:
        with lock:
            pass
    assert san.check(), "edge recording must survive a recursive wait"


def test_inversion_through_condition_held_lock():
    """Holding a tracked lock while acquiring another through BOTH
    orders is reported even when one side is a Condition's lock."""
    san = LockOrderSanitizer()
    outer = san.make_lock()
    inner = san.make_lock()
    cond = threading.Condition(inner)

    def outer_then_inner():
        with outer:
            with cond:
                pass

    def inner_then_outer():
        with cond:
            with outer:
                pass

    _run_in_thread(outer_then_inner)
    _run_in_thread(inner_then_outer)
    assert san.check(), "inversion through a Condition must be caught"


def test_fixture_patches_and_unpatches(lock_order_sanitizer):
    """The conftest fixture: threading.Lock() now returns a tracked
    wrapper, and lock semantics hold through it."""
    lock = threading.Lock()
    assert type(lock).__name__ == "_TrackedLock"
    with lock:
        assert lock.locked()
    assert not lock.locked()
