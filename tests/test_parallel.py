"""Multi-chip sharding validation on the virtual 8-device CPU mesh:
data-parallel and data×model (vocab-sharded, psum-reduced) scoring must
produce exactly the single-device results."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from licensee_tpu.corpus.compiler import default_corpus
from licensee_tpu.kernels.batch import BatchClassifier, NormalizedBlob
from licensee_tpu.kernels.dice_xla import CorpusArrays, make_best_match_fn
from licensee_tpu.parallel.mesh import build_mesh, make_sharded_scorer, shard_batch
from tests.conftest import fixture_contents, sub_copyright_info


@pytest.fixture(scope="module")
def features():
    from licensee_tpu.corpus.license import License

    corpus = default_corpus()
    classifier = BatchClassifier()
    licenses = License.all(hidden=True, pseudo=False)
    blobs = [
        NormalizedBlob(sub_copyright_info(lic)) for lic in licenses[:14]
    ] + [NormalizedBlob(fixture_contents("cc-by-nd/LICENSE"))] + [
        NormalizedBlob("not a license at all")
    ]
    bits, n_words, lengths, cc_fp = classifier.features(blobs)
    return corpus, bits, n_words, lengths, cc_fp


@pytest.fixture(scope="module")
def reference_result(features):
    corpus, bits, n_words, lengths, cc_fp = features
    arrays = CorpusArrays.from_compiled(corpus)
    fn = make_best_match_fn(arrays)
    idx, num, den = fn(bits, n_words, lengths, cc_fp)
    return np.asarray(idx), np.asarray(num), np.asarray(den)


def _assert_matches_reference(result, reference):
    for got, want in zip(result, reference):
        np.testing.assert_array_equal(np.asarray(got), want)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("n_data,n_model", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_scorer_agrees(features, reference_result, n_data, n_model):
    corpus, bits, n_words, lengths, cc_fp = features
    arrays = CorpusArrays.from_compiled(corpus)
    mesh = build_mesh(n_data=n_data, n_model=n_model)
    scorer = make_sharded_scorer(arrays, mesh, method="popcount")
    sharded = shard_batch(mesh, bits, n_words, lengths, cc_fp)
    result = scorer(*sharded)
    _assert_matches_reference(result, reference_result)


def test_sharded_matmul_agrees(features, reference_result):
    corpus, bits, n_words, lengths, cc_fp = features
    arrays = CorpusArrays.from_compiled(corpus)
    mesh = build_mesh(n_data=4, n_model=2)
    scorer = make_sharded_scorer(arrays, mesh, method="matmul")
    sharded = shard_batch(mesh, bits, n_words, lengths, cc_fp)
    result = scorer(*sharded)
    _assert_matches_reference(result, reference_result)


def _blob_contents():
    from licensee_tpu.corpus.license import License

    licenses = License.all(hidden=True, pseudo=False)
    contents = [sub_copyright_info(lic) for lic in licenses[:12]]
    contents += [
        contents[0] + "\nextra words beyond the rendered template",
        fixture_contents("cc-by-nd/LICENSE"),
        "Copyright (c) 2024 Someone",
        "not a license at all",
    ]
    return contents


def test_batch_classifier_default_mesh_is_product_path():
    """The PRODUCT path: with >1 visible device, BatchClassifier builds the
    sharded scorer by default (VERDICT r2 #2) — and its results are
    bit-identical to the single-device scorer."""
    clf = BatchClassifier(pad_batch_to=16)
    assert clf.mesh is not None
    assert clf.mesh.shape["data"] == 8

    single = BatchClassifier(pad_batch_to=16, mesh=None)
    assert single.mesh is None

    contents = _blob_contents()
    got = clf.classify_blobs(contents)
    want = single.classify_blobs(contents)
    for g, w in zip(got, want):
        assert (g.key, g.matcher, g.confidence) == (w.key, w.matcher, w.confidence)


@pytest.mark.parametrize("mesh_shape", [(4, 2), (2, 1)])
def test_batch_classifier_explicit_mesh(mesh_shape):
    clf = BatchClassifier(pad_batch_to=16, mesh=mesh_shape)
    assert dict(zip(clf.mesh.axis_names, clf.mesh.devices.shape)) == {
        "data": mesh_shape[0],
        "model": mesh_shape[1],
    }
    single = BatchClassifier(pad_batch_to=16, mesh=None)
    contents = _blob_contents()
    got = clf.classify_blobs(contents)
    want = single.classify_blobs(contents)
    for g, w in zip(got, want):
        assert (g.key, g.matcher, g.confidence) == (w.key, w.matcher, w.confidence)


def test_batch_classifier_auto_mesh_shrinks_to_divisor():
    # pad_batch_to=12 is not divisible by 8 devices; auto shrinks to 6
    clf = BatchClassifier(pad_batch_to=12)
    assert clf.mesh.shape["data"] == 6


def test_batch_classifier_pallas_rejects_mesh():
    with pytest.raises(ValueError, match="single-device"):
        BatchClassifier(method="pallas", mesh=(2, 1))


def test_batch_classifier_rejects_indivisible_batch():
    with pytest.raises(ValueError, match="not divisible"):
        BatchClassifier(pad_batch_to=10, mesh=(4, 1))


def test_batch_project_runs_on_mesh(tmp_path):
    """BatchProject end-to-end over the 8-device mesh: same rows as the
    single-device run."""
    import json

    from licensee_tpu.projects.batch_project import BatchProject

    contents = _blob_contents()
    paths = []
    for i, content in enumerate(contents):
        p = tmp_path / f"LICENSE_{i}"
        p.write_text(content)
        paths.append(str(p))

    out_mesh = tmp_path / "mesh.jsonl"
    out_single = tmp_path / "single.jsonl"
    BatchProject(paths, batch_size=8, mesh=(4, 2)).run(str(out_mesh))
    BatchProject(paths, batch_size=8, mesh=None).run(str(out_single))
    rows_mesh = [json.loads(line) for line in out_mesh.read_text().splitlines()]
    rows_single = [
        json.loads(line) for line in out_single.read_text().splitlines()
    ]
    assert rows_mesh == rows_single


def test_sharded_scorer_rejects_unknown_method(features):
    import pytest

    from licensee_tpu.corpus.compiler import default_corpus
    from licensee_tpu.kernels.dice_xla import CorpusArrays
    from licensee_tpu.parallel.mesh import build_mesh, make_sharded_scorer

    arrays = CorpusArrays.from_compiled(default_corpus())
    mesh = build_mesh(n_data=2, n_model=2)
    with pytest.raises(ValueError, match="unknown scoring method"):
        make_sharded_scorer(arrays, mesh, method="bogus")
