"""Multi-chip sharding validation on the virtual 8-device CPU mesh:
data-parallel and data×model (vocab-sharded, psum-reduced) scoring must
produce exactly the single-device results."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from licensee_tpu.corpus.compiler import default_corpus
from licensee_tpu.kernels.batch import BatchClassifier, NormalizedBlob
from licensee_tpu.kernels.dice_xla import CorpusArrays, make_best_match_fn
from licensee_tpu.parallel.mesh import build_mesh, make_sharded_scorer, shard_batch
from tests.conftest import fixture_contents, sub_copyright_info


@pytest.fixture(scope="module")
def features():
    from licensee_tpu.corpus.license import License

    corpus = default_corpus()
    classifier = BatchClassifier()
    licenses = License.all(hidden=True, pseudo=False)
    blobs = [
        NormalizedBlob(sub_copyright_info(lic)) for lic in licenses[:14]
    ] + [NormalizedBlob(fixture_contents("cc-by-nd/LICENSE"))] + [
        NormalizedBlob("not a license at all")
    ]
    bits, n_words, lengths, cc_fp = classifier.features(blobs)
    return corpus, bits, n_words, lengths, cc_fp


@pytest.fixture(scope="module")
def reference_result(features):
    corpus, bits, n_words, lengths, cc_fp = features
    arrays = CorpusArrays.from_compiled(corpus)
    fn = make_best_match_fn(arrays)
    idx, num, den = fn(bits, n_words, lengths, cc_fp)
    return np.asarray(idx), np.asarray(num), np.asarray(den)


def _assert_matches_reference(result, reference):
    for got, want in zip(result, reference):
        np.testing.assert_array_equal(np.asarray(got), want)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("n_data,n_model", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_scorer_agrees(features, reference_result, n_data, n_model):
    corpus, bits, n_words, lengths, cc_fp = features
    arrays = CorpusArrays.from_compiled(corpus)
    mesh = build_mesh(n_data=n_data, n_model=n_model)
    scorer = make_sharded_scorer(arrays, mesh, method="popcount")
    sharded = shard_batch(mesh, bits, n_words, lengths, cc_fp)
    result = scorer(*sharded)
    _assert_matches_reference(result, reference_result)


def test_sharded_matmul_agrees(features, reference_result):
    corpus, bits, n_words, lengths, cc_fp = features
    arrays = CorpusArrays.from_compiled(corpus)
    mesh = build_mesh(n_data=4, n_model=2)
    scorer = make_sharded_scorer(arrays, mesh, method="matmul")
    sharded = shard_batch(mesh, bits, n_words, lengths, cc_fp)
    result = scorer(*sharded)
    _assert_matches_reference(result, reference_result)


def test_sharded_scorer_rejects_unknown_method(features):
    import pytest

    from licensee_tpu.corpus.compiler import default_corpus
    from licensee_tpu.kernels.dice_xla import CorpusArrays
    from licensee_tpu.parallel.mesh import build_mesh, make_sharded_scorer

    arrays = CorpusArrays.from_compiled(default_corpus())
    mesh = build_mesh(n_data=2, n_model=2)
    with pytest.raises(ValueError, match="unknown scoring method"):
        make_sharded_scorer(arrays, mesh, method="bogus")
