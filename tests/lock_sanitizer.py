"""Test-only lock-order sanitizer: wrap ``threading.Lock``/``RLock``
so every acquisition records the per-thread held-lock stack, and a
lock-order INVERSION (thread 1 takes A then B while thread 2 ever took
B then A) fails the test with both acquisition stacks.

This is the dynamic companion to the static ``lock-discipline`` rule
(licensee_tpu/analysis): the analyzer proves guarded attributes stay
guarded; this sanitizer proves the locks themselves are acquired in a
consistent global order, which is the deadlock-freedom argument for
the fleet/stripe supervision paths.

Only ``threading.Lock``/``RLock`` CREATED while the fixture is active
are tracked — library locks that predate the test keep their raw
types.  The wrappers implement enough of the lock protocol for
``threading.Condition`` (both the ``Condition(Lock())`` and
``Condition(RLock())`` forms) and ``queue.Queue`` to run unmodified.
"""

from __future__ import annotations

import _thread
import threading
import traceback


def _site(depth: int = 3) -> str:
    stack = traceback.extract_stack()
    for frame in reversed(stack[:-depth]):
        if "lock_sanitizer" not in frame.filename:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _stack_snippet(limit: int = 6) -> str:
    frames = [
        f
        for f in traceback.extract_stack()
        if "lock_sanitizer" not in f.filename
    ]
    return "".join(traceback.format_list(frames[-limit:]))


class LockOrderSanitizer:
    """Factory + edge registry.  ``make_lock``/``make_rlock`` stand in
    for ``threading.Lock``/``RLock``; ``inversions`` accumulates every
    (edge, reversed-edge) pair observed with their stacks."""

    def __init__(self):
        # raw primitives on purpose: the registry must not recurse
        # through its own wrappers
        self._mu = _thread.allocate_lock()
        self._tls = threading.local()
        # (id_a, id_b) -> (site_a, site_b, stack_snippet)
        self.edges: dict[tuple[int, int], tuple[str, str, str]] = {}
        self.inversions: list[str] = []

    # -- factory entry points (patched over threading.Lock/RLock) --

    def make_lock(self):
        return _TrackedLock(self, _thread.allocate_lock(), _site())

    def make_rlock(self):
        return _TrackedRLock(self, threading._RLock(), _site())

    # -- bookkeeping --

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def on_acquire(self, lock) -> None:
        held = self._held()
        with self._mu:
            for prior in held:
                if prior is lock:
                    continue
                edge = (id(prior), id(lock))
                if edge in self.edges:
                    continue  # known edge: skip the stack extraction
                rev = (id(lock), id(prior))
                if rev in self.edges:
                    a_site, b_site, rev_stack = self.edges[rev]
                    self.inversions.append(
                        "lock-order inversion:\n"
                        f"  this thread acquired {prior.site} THEN "
                        f"{lock.site} at:\n{_stack_snippet()}"
                        f"  but another acquisition took {b_site} THEN "
                        f"{a_site} at:\n{rev_stack}"
                    )
                self.edges[edge] = (
                    prior.site, lock.site, _stack_snippet()
                )
        held.append(lock)

    def on_release(self, lock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                break

    def check(self) -> list[str]:
        with self._mu:
            return list(self.inversions)


class _TrackedLock:
    """``threading.Lock`` stand-in recording acquisition order."""

    def __init__(self, registry, inner, site):
        self._registry = registry
        self._inner = inner
        self.site = site

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._registry.on_acquire(self)
        return ok

    def release(self):
        self._registry.on_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _at_fork_reinit(self):
        # concurrent.futures.thread registers this with os.register_at_fork
        # at IMPORT time — a wrapper without it breaks any module whose
        # first import happens inside a sanitized test
        self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return f"<TrackedLock {self.site}>"


class _TrackedRLock:
    """``threading.RLock`` stand-in.  Only the OUTERMOST acquire/release
    of a recursion counts for ordering; the ``_release_save`` trio keeps
    ``threading.Condition(RLock())`` working through wait()."""

    def __init__(self, registry, inner, site):
        self._registry = registry
        self._inner = inner
        self.site = site
        self._tls = threading.local()

    def _depth(self) -> int:
        return getattr(self._tls, "depth", 0)

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            depth = self._depth() + 1
            self._tls.depth = depth
            if depth == 1:
                self._registry.on_acquire(self)
        return ok

    def release(self):
        self._inner.release()
        depth = self._depth() - 1
        self._tls.depth = depth
        if depth == 0:
            self._registry.on_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    # Condition protocol (threading.Condition duck-types these)
    def _release_save(self):
        # carry the WRAPPER depth through the opaque state so a
        # recursive holder (depth > 1) restores tracking exactly;
        # Condition passes the state back verbatim
        state = self._inner._release_save()
        depth = self._depth()
        self._tls.depth = 0
        self._registry.on_release(self)
        return (depth, state)

    def _acquire_restore(self, state):
        depth, inner_state = state
        self._inner._acquire_restore(inner_state)
        self._tls.depth = depth
        self._registry.on_acquire(self)

    def _is_owned(self):
        return self._inner._is_owned()

    def _at_fork_reinit(self):
        self._inner._at_fork_reinit()
        self._tls = threading.local()

    def __repr__(self):
        return f"<TrackedRLock {self.site}>"
