"""batch-detect --attribution: the copyright line per matched blob.

Parity target: `LicenseFile#attribution` (license_file.rb:71-77) — the
batch rows must carry exactly what the scalar CLI's Attribution field
shows for the same content.
"""

from __future__ import annotations

import json
import os

import pytest

from licensee_tpu.kernels.batch import BatchClassifier
from licensee_tpu.projects.batch_project import BatchProject
from tests.conftest import fixture_path


def fixture_bytes(name: str) -> bytes:
    with open(fixture_path(name), "rb") as f:
        return f.read()


@pytest.fixture(scope="module")
def clf():
    return BatchClassifier(pad_batch_to=16, mesh=None)


@pytest.mark.parametrize(
    "name",
    [
        "mit/LICENSE.txt",
        "apache-2.0_markdown/LICENSE.md",
        "gpl-3.0_markdown/LICENSE.md",
        "bsd-2-author/LICENSE",
        "bsd-3-clause_markdown/LICENSE.md",
        "crlf-license/LICENSE",
        "copyright-encoding/COPYING",
    ],
)
def test_attribution_matches_scalar_license_file(clf, name):
    from licensee_tpu.project_files.license_file import LicenseFile

    raw = fixture_bytes(name)
    result = clf.classify_blobs([raw])[0]
    got = clf.attribution_for(raw, os.path.basename(name), result)
    want = LicenseFile(raw, os.path.basename(name)).attribution
    assert got == want


def test_attribution_on_copyright_prefiltered_row(clf):
    """The copyright? gate needs BOTH the Copyright matcher AND a
    copyright(.ext) filename (project_file.rb:90-95): COPYRIGHT gets the
    line, the same content as LICENSE does not (no-license's pseudo
    template has no [fullname])."""
    raw = b"Copyright (c) 2024 Example Corp. All rights reserved.\n"
    result = clf.classify_blobs([raw])[0]
    assert result.matcher == "copyright"
    got = clf.attribution_for(raw, "COPYRIGHT", result)
    assert got is not None and "Example Corp" in got
    assert clf.attribution_for(raw, "COPYRIGHT.txt", result) is not None
    assert clf.attribution_for(raw, "LICENSE", result) is None


def test_attribution_absent_without_fullname_field(clf):
    # unmatched rows never report attribution
    raw = b"just some prose that matches nothing"
    result = clf.classify_blobs([raw])[0]
    assert clf.attribution_for(raw, "LICENSE", result) is None


def test_attribution_pipeline_rows_and_dedupe(tmp_path):
    mit = fixture_bytes("mit/LICENSE.txt")
    paths = []
    for i in range(4):
        d = tmp_path / f"r{i}"
        d.mkdir()
        p = d / "LICENSE"
        p.write_bytes(mit)
        paths.append(str(p))
    out = tmp_path / "out.jsonl"
    project = BatchProject(
        paths, batch_size=1, workers=1, inflight=1, attribution=True
    )
    stats = project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert all(
        r["attribution"] == "Copyright (c) 2016 Ben Balter" for r in rows
    )
    # cache hits reuse the stored attribution (computed once per unique
    # content) — and the cached snapshot carries it
    assert stats.dedupe_hits >= 1
    for cached in project._dedupe_cache.values():
        assert cached.attribution == "Copyright (c) 2016 Ben Balter"


def test_attribution_dedupe_key_carries_copyright_gate(tmp_path):
    """Identical bytes under COPYRIGHT vs LICENSE names attribute
    differently (the copyright? filename gate) — the dedupe cache must
    not share a slot across that gate, in either insertion order."""
    raw = b"Copyright (c) 2024 Example Corp. All rights reserved.\n"
    for order in (["COPYRIGHT", "LICENSE"], ["LICENSE", "COPYRIGHT"]):
        base = tmp_path / "-".join(order)
        base.mkdir()
        paths = []
        for i, name in enumerate(order * 2):
            d = base / f"r{i}"
            d.mkdir()
            (d / name).write_bytes(raw)
            paths.append(str(d / name))
        rows_by_dedupe = {}
        for dedupe in (True, False):
            out = base / f"out-{dedupe}.jsonl"
            project = BatchProject(
                paths,
                batch_size=1,
                workers=1,
                inflight=1,
                attribution=True,
                dedupe=dedupe,
            )
            project.run(str(out), resume=False)
            rows_by_dedupe[dedupe] = [
                {k: v for k, v in json.loads(line).items() if k != "path"}
                for line in out.read_text().splitlines()
            ]
        assert rows_by_dedupe[True] == rows_by_dedupe[False], order
        for row, name in zip(rows_by_dedupe[True], order * 2):
            assert ("attribution" in row) == (name == "COPYRIGHT"), order


def test_attribution_off_by_default(tmp_path):
    p = tmp_path / "LICENSE"
    p.write_bytes(fixture_bytes("mit/LICENSE.txt"))
    project = BatchProject([str(p)], batch_size=4)
    out = tmp_path / "out.jsonl"
    project.run(str(out), resume=False)
    row = json.loads(out.read_text().splitlines()[0])
    assert "attribution" not in row


def test_attribution_readme_route_scans_extracted_section(tmp_path):
    readme = (
        b"# Project\n\nCopyright (c) 1999 Wrong Section\n\n"
        b"## License\n\n" + fixture_bytes("mit/LICENSE.txt")
    )
    (tmp_path / "README.md").write_bytes(readme)
    out = tmp_path / "out.jsonl"
    project = BatchProject(
        [str(tmp_path / "README.md")],
        batch_size=4,
        mode="auto",
        attribution=True,
    )
    project.run(str(out), resume=False)
    row = json.loads(out.read_text().splitlines()[0])
    assert row["key"] == "mit"
    # the line comes from the extracted License section, not the README
    # preamble (project.rb:74-80 builds the ReadmeFile from the section)
    assert row["attribution"] == "Copyright (c) 2016 Ben Balter"


def test_cli_batch_detect_attribution(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    (tmp_path / "LICENSE").write_bytes(fixture_bytes("mit/LICENSE.txt"))
    manifest = tmp_path / "manifest.txt"
    manifest.write_text(f"{tmp_path / 'LICENSE'}\n")
    assert main(["batch-detect", str(manifest), "--attribution"]) == 0
    row = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert row["attribution"] == "Copyright (c) 2016 Ben Balter"
