"""Streaming container ingestion (licensee_tpu/ingest/): the ``::``
manifest grammar, tar/zip/git blob sources, the 64 KiB skip-not-
truncate cap, loose-vs-container output parity (the golden gate),
torn-container refusal, resume at container granularity, and the
container-level verdict algebra's parity with projects/project.py.
"""

from __future__ import annotations

import io
import json
import os
import re
import subprocess
import tarfile
import zipfile

import pytest

from licensee_tpu.ingest import OVERSIZED, SkippedBlob
from licensee_tpu.ingest.sources import (
    IngestError,
    expand_manifest,
    is_container_entry,
    split_entry,
)
from licensee_tpu.ingest.verdict import container_verdict


def _body(key: str) -> str:
    from licensee_tpu.corpus.license import License

    return re.sub(r"\[(\w+)\]", "example", License.find(key).content or "")


def _make_tar(path, files: dict[str, bytes]) -> str:
    with tarfile.open(path, "w") as tf:
        for name, data in files.items():
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return str(path)


def _make_zip(path, files: dict[str, bytes]) -> str:
    with zipfile.ZipFile(path, "w") as zf:
        for name, data in files.items():
            zf.writestr(name, data)
    return str(path)


# -- the :: entry grammar --


def test_entry_grammar():
    assert split_entry("/x/archive.tar::LICENSE") == (
        "/x/archive.tar", "LICENSE",
    )
    assert split_entry("/x/a.zip::*") == ("/x/a.zip", "*")
    assert split_entry("/x/repo.git::HEAD") == ("/x/repo.git", "HEAD")
    # member names may contain further colons: split on the FIRST ::
    assert split_entry("a.tar::weird::name") == ("a.tar", "weird::name")
    # plain paths — even with a lone "::" whose prefix is no container
    assert split_entry("/plain/file.txt") is None
    assert split_entry("/not-an-archive.bin::x") is None
    assert not is_container_entry("/plain/file.txt")
    assert is_container_entry("a.tar::*")


def test_plain_directory_with_separator_stays_loose(tmp_path):
    """A '::' entry whose prefix is an ordinary directory (no git
    layout) is NOT a container claim: it stays a loose path whose
    failed read is row-contained — one read_error row, never a fatal
    IngestError for the whole run."""
    from licensee_tpu.projects.batch_project import BatchProject

    d = tmp_path / "data"
    d.mkdir()
    (d / "v2").mkdir()
    entry = f"{d}::v2/file.txt"
    assert split_entry(entry) is None
    assert not is_container_entry(entry)
    project = BatchProject([entry], batch_size=8, mesh=None)
    out = str(tmp_path / "out.jsonl")
    try:
        stats = project.run(out, resume=False)
    finally:
        project.close()
    rows = [json.loads(line) for line in open(out)]
    assert rows[0]["error"] == "read_error"
    assert stats.read_errors == 1


def test_explicit_member_routes_by_member_name(tmp_path):
    """--mode auto must route an explicit `a.tar::LICENSE` entry by
    the MEMBER's basename (its display string stays as written) —
    the same blob must score identically however it is addressed."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(tmp_path / "a.tar", {"LICENSE": _body("mit").encode()})
    out = str(tmp_path / "out.jsonl")
    project = BatchProject(
        [f"{tar}::LICENSE"], batch_size=8, mesh=None, mode="auto"
    )
    try:
        stats = project.run(out, resume=False)
    finally:
        project.close()
    row = json.loads(open(out).readline())
    assert row["path"] == f"{tar}::LICENSE"  # display as written
    assert row["key"] == "mit"  # routed + scored like a loose LICENSE
    assert stats.routed == {"license": 1}


def test_zip_duplicate_members_collapse_to_last(tmp_path):
    """Duplicate member names INSIDE one zip (an appended archive)
    collapse to one row of the archive's effective copy — last wins,
    like extraction — instead of emitting rows whose bytes silently
    all come from the last occurrence."""
    zp = str(tmp_path / "dup.zip")
    with zipfile.ZipFile(zp, "w") as zf:
        zf.writestr("LICENSE", "first copy")
        zf.writestr("LICENSE", "second copy")
    ex = expand_manifest([f"{zp}::*"])
    try:
        assert ex.paths == ["LICENSE"]
        assert ex.read_at(0) == b"second copy"
        assert ex.spans == [(f"{zp}::*", 0, 1)]
    finally:
        ex.close()


def test_empty_selector_refused(tmp_path):
    tar = _make_tar(tmp_path / "a.tar", {"LICENSE": b"x"})
    with pytest.raises(IngestError, match="empty selector"):
        expand_manifest([f"{tar}::"])


def test_compressed_tar_refused(tmp_path):
    import gzip

    plain = _make_tar(tmp_path / "a.tar", {"LICENSE": b"x"})
    gz = tmp_path / "a.tar.gz"
    with open(plain, "rb") as src, gzip.open(gz, "wb") as dst:
        dst.write(src.read())
    with pytest.raises(IngestError, match="compressed tar"):
        expand_manifest([f"{gz}::*"])


# -- readers: members, caps, positional reads --


def test_tar_reader_order_cap_and_missing(tmp_path):
    tar = _make_tar(
        tmp_path / "a.tar",
        {
            "z_first": b"zz",
            "a_second": b"aa",
            "BIG": b"x" * (64 * 1024 + 1),
        },
    )
    ex = expand_manifest([f"{tar}::*"])
    try:
        # archive order, not sorted
        assert ex.paths == ["z_first", "a_second", "BIG"]
        assert ex.read_at(0) == b"zz"
        big = ex.read_at(2)
        assert isinstance(big, SkippedBlob) and big.error == OVERSIZED
        assert ex.spans == [(f"{tar}::*", 0, 3)]
    finally:
        ex.close()
    # an explicit member that does not exist: a read_error row, not a
    # refusal — the container itself is sound
    ex = expand_manifest([f"{tar}::nope"])
    try:
        assert ex.paths == [f"{tar}::nope"]
        assert ex.read_at(0) is None
        assert ex.spans == []  # single members get no container span
    finally:
        ex.close()


def test_zip_reader_and_cap(tmp_path):
    zp = _make_zip(
        tmp_path / "a.zip",
        {"LICENSE": _body("mit").encode(), "BIG": b"y" * (65 * 1024)},
    )
    ex = expand_manifest([f"{zp}::*"])
    try:
        assert ex.paths == ["LICENSE", "BIG"]
        assert ex.read_at(0) == _body("mit").encode()
        assert isinstance(ex.read_at(1), SkippedBlob)
    finally:
        ex.close()


def test_duplicate_member_names_across_containers(tmp_path):
    """Two containers holding the same member name: reads are
    positional, so each row gets its own container's bytes."""
    t1 = _make_tar(tmp_path / "one.tar", {"LICENSE": b"first"})
    t2 = _make_tar(tmp_path / "two.tar", {"LICENSE": b"second"})
    ex = expand_manifest([f"{t1}::*", f"{t2}::*"])
    try:
        assert ex.paths == ["LICENSE", "LICENSE"]
        assert ex.read_at(0) == b"first"
        assert ex.read_at(1) == b"second"
    finally:
        ex.close()


def test_mixed_manifest_spans(tmp_path):
    loose = tmp_path / "loose.txt"
    loose.write_bytes(b"loose bytes")
    tar = _make_tar(tmp_path / "a.tar", {"m1": b"1", "m2": b"2"})
    ex = expand_manifest([str(loose), f"{tar}::m1", f"{tar}::*"])
    try:
        assert ex.paths == [str(loose), f"{tar}::m1", "m1", "m2"]
        assert ex.read_at(0) == b"loose bytes"
        assert ex.read_at(1) == b"1"
        assert ex.spans == [(f"{tar}::*", 2, 2)]
    finally:
        ex.close()


def test_oversized_loose_file_skipped(tmp_path):
    from licensee_tpu.serve.featurize import read_capped

    big = tmp_path / "BIG_LICENSE"
    big.write_bytes(b"z" * (64 * 1024 + 1))
    got = read_capped(str(big))
    assert isinstance(got, SkippedBlob) and got.error == OVERSIZED
    ok = tmp_path / "ok"
    ok.write_bytes(b"z" * (64 * 1024))  # exactly at the cap: kept
    assert read_capped(str(ok)) == b"z" * (64 * 1024)


# -- torn-container refusal --


def test_failed_expansion_leaks_no_handles(tmp_path):
    """A torn container midway through a manifest must close the
    handles already opened for the containers before it."""
    good = _make_tar(tmp_path / "good.tar", {"LICENSE": b"x"})
    torn = str(tmp_path / "torn.tar")
    _make_tar(torn, {"LICENSE": _body("mit").encode() * 4})
    with open(torn, "r+b") as f:
        f.truncate(1000)
    before = len(os.listdir("/proc/self/fd"))
    with pytest.raises(IngestError):
        expand_manifest([f"{good}::*", f"{torn}::*"])
    assert len(os.listdir("/proc/self/fd")) == before


def test_oversized_prom_kind_exported(tmp_path, capsys):
    """The skipped_oversized counter reaches the --prom-file
    exposition beside every other result kind."""
    from licensee_tpu.cli.main import main

    big = tmp_path / "BIG_LICENSE"
    big.write_bytes(b"x" * (70 * 1024))
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{big}\n")
    prom = tmp_path / "run.prom"
    rc = main([
        "batch-detect", str(manifest), "--output",
        str(tmp_path / "o.jsonl"), "--mesh", "none",
        "--prom-file", str(prom),
    ])
    assert rc == 0
    text = prom.read_text()
    assert 'batch_rows{kind="skipped_oversized"} 1' in text


def test_torn_tar_refused(tmp_path):
    tar = _make_tar(
        tmp_path / "a.tar", {"LICENSE": _body("mit").encode() * 4}
    )
    with open(tar, "r+b") as f:
        f.truncate(1000)  # keep the header, tear the member data
    with pytest.raises(IngestError):
        expand_manifest([f"{tar}::*"])


def test_garbage_zip_refused(tmp_path):
    bad = tmp_path / "bad.zip"
    bad.write_bytes(b"this is not a zip central directory")
    with pytest.raises(IngestError, match="cannot read zip"):
        expand_manifest([f"{bad}::*"])


def test_truncated_git_pack_refused(git_repo):
    repo = git_repo
    # corrupt every packfile and loose object: the revision's root tree
    # becomes unreachable and expansion must refuse, not emit rows
    for root, _dirs, files in os.walk(os.path.join(repo, ".git", "objects")):
        for name in files:
            p = os.path.join(root, name)
            os.chmod(p, 0o644)
            with open(p, "r+b") as f:
                f.truncate(max(1, os.path.getsize(p) // 4))
    with pytest.raises(IngestError):
        expand_manifest([f"{repo}::HEAD"])


# -- git containers --


@pytest.fixture
def git_repo(tmp_path):
    repo = str(tmp_path / "proj.git")
    os.makedirs(repo)
    env = {
        **os.environ,
        "GIT_CONFIG_GLOBAL": "/dev/null",
        "GIT_CONFIG_SYSTEM": "/dev/null",
    }

    def git(*args):
        subprocess.run(
            ["git", "-C", repo, *args],
            check=True, capture_output=True, env=env,
        )

    git("init", "-q")
    with open(os.path.join(repo, "LICENSE"), "w", encoding="utf-8") as f:
        f.write(_body("isc"))
    with open(os.path.join(repo, "BIG"), "wb") as f:
        f.write(b"x" * (80 * 1024))
    os.makedirs(os.path.join(repo, "src"))
    with open(os.path.join(repo, "src", "x.py"), "w") as f:
        f.write("pass\n")
    git("add", ".")
    git("-c", "user.email=a@b", "-c", "user.name=n", "commit", "-qm", "x")
    # repack so the blobs live in a packfile, the forge-scan shape
    git("gc", "-q", "--aggressive")
    return repo


def test_git_container_root_tree_and_cap(git_repo):
    ex = expand_manifest([f"{git_repo}::HEAD"])
    try:
        # root-level blobs only (git_project.rb:64-76) — src/x.py is not
        # a root entry
        assert set(ex.paths) == {"LICENSE", "BIG"}
        i_lic = ex.paths.index("LICENSE")
        i_big = ex.paths.index("BIG")
        assert ex.read_at(i_lic).decode("utf-8") == _body("isc")
        assert isinstance(ex.read_at(i_big), SkippedBlob)  # the 64 KiB cap
    finally:
        ex.close()


def test_git_container_end_to_end(git_repo, tmp_path):
    from licensee_tpu.projects.batch_project import BatchProject

    out = str(tmp_path / "git.jsonl")
    project = BatchProject([f"{git_repo}::HEAD"], batch_size=8, mesh=None)
    try:
        stats = project.run(out, resume=False)
    finally:
        project.close()
    rows = {r["path"]: r for r in map(json.loads, open(out))}
    assert rows["LICENSE"]["key"] == "isc"
    assert rows["BIG"]["error"] == "oversized"
    assert stats.skipped_oversized == 1
    containers = [
        json.loads(line) for line in open(f"{out}.containers.jsonl")
    ]
    assert containers == [
        {
            "container": f"{git_repo}::HEAD",
            "files": 2,
            "license": "isc",
            "licenses": ["isc"],
            "matched_files": ["LICENSE"],
        }
    ]


# -- the golden parity gate: containers of the vendored corpus --


@pytest.mark.slow
def test_vendored_corpus_container_parity(tmp_path):
    """A tarball AND a zip of the vendored corpus must yield
    byte-identical (sha256) per-blob JSONL to the loose-file manifest
    run — the acceptance gate for the streaming sources."""
    import hashlib

    from licensee_tpu.projects.batch_project import BatchProject
    from licensee_tpu.vendor_paths import LICENSE_DIR

    paths = sorted(
        os.path.join(LICENSE_DIR, n)
        for n in os.listdir(LICENSE_DIR)
        if n.endswith(".txt")
    )
    assert len(paths) >= 40
    files = {}
    for p in paths:
        with open(p, "rb") as f:
            files[p] = f.read()  # members stored under the loose names
    tar = _make_tar(tmp_path / "corpus.tar", files)
    zp = _make_zip(tmp_path / "corpus.zip", files)

    digests = {}
    for label, manifest in (
        ("loose", paths),
        ("tar", [f"{tar}::*"]),
        ("zip", [f"{zp}::*"]),
    ):
        out = str(tmp_path / f"{label}.jsonl")
        project = BatchProject(manifest, batch_size=16, mesh=None)
        try:
            project.run(out, resume=False)
        finally:
            project.close()
        with open(out, "rb") as f:
            digests[label] = hashlib.sha256(f.read()).hexdigest()
    assert digests["tar"] == digests["loose"]
    assert digests["zip"] == digests["loose"]


# -- resume at container granularity --


@pytest.mark.slow
def test_resume_mid_container(tmp_path):
    """A run killed mid-container (simulated as the torn output a
    SIGKILL leaves: a complete prefix plus half a row) must resume to
    byte-identical per-blob output AND an identical container-verdict
    sidecar."""
    from licensee_tpu.projects.batch_project import BatchProject

    files = {
        f"repo/LICENSE_{i:02d}": (
            f"Copyright (c) {2000 + i}\n\n{_body('mit')}"
        ).encode()
        for i in range(24)
    }
    tar = _make_tar(tmp_path / "r.tar", files)
    entry = f"{tar}::*"

    golden = str(tmp_path / "golden.jsonl")
    project = BatchProject([entry], batch_size=8, mesh=None, dedupe=False)
    try:
        project.run(golden, resume=False)
    finally:
        project.close()
    with open(golden, "rb") as f:
        golden_bytes = f.read()
    with open(f"{golden}.containers.jsonl", "rb") as f:
        golden_containers = f.read()

    # fabricate the crash artifact: 10 complete rows + a torn 11th,
    # beside the sidecar the dead run wrote at open
    out = str(tmp_path / "resumed.jsonl")
    lines = golden_bytes.split(b"\n")
    with open(out, "wb") as f:
        f.write(b"\n".join(lines[:10]) + b"\n" + lines[10][: len(lines[10]) // 2])
    with open(f"{golden}.meta.json", "rb") as f:
        meta = f.read()
    with open(f"{out}.meta.json", "wb") as f:
        f.write(meta)

    project = BatchProject([entry], batch_size=8, mesh=None, dedupe=False)
    try:
        project.run(out, resume=True)
    finally:
        project.close()
    with open(out, "rb") as f:
        assert f.read() == golden_bytes
    with open(f"{out}.containers.jsonl", "rb") as f:
        assert f.read() == golden_containers


def test_rewritten_container_refuses_resume(tmp_path):
    """The expansion fingerprint in the resume sidecar: an archive
    rewritten between runs (different member set) must refuse to
    resume instead of appending rows of a foreign container."""
    from licensee_tpu.projects.batch_project import (
        BatchProject,
        ResumeConfigError,
    )

    tar = str(tmp_path / "a.tar")
    _make_tar(tar, {"LICENSE": _body("mit").encode(), "A": b"a"})
    out = str(tmp_path / "out.jsonl")
    project = BatchProject([f"{tar}::*"], batch_size=8, mesh=None)
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    _make_tar(tar, {"LICENSE": _body("mit").encode(), "B": b"b"})
    project = BatchProject([f"{tar}::*"], batch_size=8, mesh=None)
    try:
        with pytest.raises(ResumeConfigError, match="ingest"):
            project.run(out, resume=True)
    finally:
        project.close()


def test_rewritten_content_same_names_refuses_resume(tmp_path):
    """Same member NAMES, different bytes: the fingerprint folds
    content evidence (tar layout/mtimes, zip CRCs, git oids), so a
    repacked archive still refuses instead of appending rows scored
    from different content."""
    from licensee_tpu.projects.batch_project import (
        BatchProject,
        ResumeConfigError,
    )

    zp = str(tmp_path / "a.zip")
    _make_zip(zp, {"LICENSE": _body("mit").encode(), "A": b"old bytes"})
    out = str(tmp_path / "out.jsonl")
    project = BatchProject([f"{zp}::*"], batch_size=8, mesh=None)
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    _make_zip(zp, {"LICENSE": _body("mit").encode(), "A": b"NEW BYTES"})
    project = BatchProject([f"{zp}::*"], batch_size=8, mesh=None)
    try:
        with pytest.raises(ResumeConfigError, match="ingest"):
            project.run(out, resume=True)
    finally:
        project.close()


# -- guardrails --


def test_containers_refuse_striping_and_procs(tmp_path):
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(tmp_path / "a.tar", {"LICENSE": b"x"})
    with pytest.raises(ValueError, match="striping"):
        BatchProject(
            [f"{tar}::*"], mesh=None,
            process_index=0, process_count=2,
        )
    with pytest.raises(ValueError, match="featurize-procs"):
        BatchProject([f"{tar}::*"], mesh=None, featurize_procs=2)


def test_cli_stripes_refuses_containers(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    tar = _make_tar(tmp_path / "a.tar", {"LICENSE": b"x"})
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{tar}::*\n")
    rc = main([
        "batch-detect", str(manifest), "--stripes", "2",
        "--output", str(tmp_path / "o.jsonl"),
    ])
    assert rc == 1
    assert "not supported with --stripes" in capsys.readouterr().err


def test_cli_stdout_mode_prints_container_rows(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    tar = _make_tar(
        tmp_path / "a.tar",
        {
            "r/LICENSE-MIT": _body("mit").encode(),
            "r/LICENSE-APACHE": _body("apache-2.0").encode(),
            "r/BIG": b"x" * (70 * 1024),
        },
    )
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{tar}::*\n")
    rc = main(["batch-detect", str(manifest), "--mesh", "none"])
    assert rc == 0
    rows = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
    ]
    blob_rows = {r["path"]: r for r in rows if "path" in r}
    assert blob_rows["r/LICENSE-MIT"]["key"] == "mit"
    assert blob_rows["r/BIG"]["error"] == "oversized"
    container_rows = [r for r in rows if "container" in r]
    assert len(container_rows) == 1
    assert container_rows[0]["license"] == "other"
    assert container_rows[0]["spdx_expression"] == "MIT OR Apache-2.0"


# -- the container verdict algebra (parity with projects/project.py) --


def _fs_verdict(tmp_path, files: dict[str, bytes]):
    from licensee_tpu.projects.fs_project import FSProject

    d = tmp_path / "fsproj"
    os.makedirs(d, exist_ok=True)
    for name, data in files.items():
        with open(d / name, "wb") as f:
            f.write(data)
    project = FSProject(str(d))
    return (
        project.license.key if project.license else None,
        sorted(lic.key for lic in project.licenses),
    )


def _rows_for(files: dict[str, bytes], tmp_path, tag: str):
    """Finished per-blob rows for a file set, via the real batch path."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(tmp_path / f"{tag}.tar", files)
    out = str(tmp_path / f"{tag}.jsonl")
    project = BatchProject([f"{tar}::*"], batch_size=8, mesh=None)
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    with open(f"{out}.containers.jsonl", encoding="utf-8") as f:
        return json.load(f)


VERDICT_SHAPES = {
    "single": {"LICENSE": "mit"},
    "dual": {"LICENSE-APACHE": "apache-2.0", "LICENSE-MIT": "mit"},
    "lgpl_pair": {"COPYING.lesser": "lgpl-3.0", "COPYING": "gpl-3.0"},
    "none": {},
}


@pytest.mark.parametrize("shape", sorted(VERDICT_SHAPES))
def test_container_verdict_matches_project(shape, tmp_path):
    """The acceptance gate: container licenses[] rows must match the
    projects/project.py verdict on the same file set."""
    files = {
        name: _body(key).encode()
        for name, key in VERDICT_SHAPES[shape].items()
    }
    files["README.md"] = b"# a readme\n"
    row = _rows_for(files, tmp_path, shape)
    fs_license, fs_keys = _fs_verdict(tmp_path, files)
    assert row["license"] == fs_license
    assert sorted(row["licenses"]) == fs_keys


def test_verdict_dual_license_spdx_expression(tmp_path):
    row = _rows_for(
        {
            "LICENSE-APACHE": _body("apache-2.0").encode(),
            "LICENSE-MIT": _body("mit").encode(),
        },
        tmp_path,
        "dual_spdx",
    )
    # reference verdict preserved (multi-license -> other), expression
    # composed on top — archive order decides the operand order
    assert row["license"] == "other"
    assert row["spdx_expression"] == "Apache-2.0 OR MIT"


def test_verdict_unmatched_license_file_is_other():
    # license_file.rb:92-98: a scored license file failing every
    # matcher still counts as 'other'
    row = container_verdict(
        "c", [("LICENSE", {"key": None, "matcher": None, "confidence": 0.0})]
    )
    assert row["license"] == "other"
    assert row["licenses"] == ["other"]
    assert "spdx_expression" not in row


def test_verdict_copyright_only_excluded():
    # project.rb:153-155: COPYRIGHT-only files never decide the verdict
    row = container_verdict(
        "c",
        [
            ("COPYRIGHT", {
                "key": "no-license", "matcher": "copyright",
                "confidence": 100.0,
            }),
            ("LICENSE", {
                "key": "mit", "matcher": "exact", "confidence": 100.0,
            }),
        ],
    )
    assert row["license"] == "mit"
    # score order: LICENSE (1.0) before COPYRIGHT (0.35), project.rb:111
    assert row["licenses"] == ["mit", "no-license"]


def test_verdict_shared_prefix_root_only():
    # nested members never count as root candidates; the shared
    # top-level wrapper (forge tarball shape) is stripped first
    row = container_verdict(
        "c",
        [
            ("repo-1.0/LICENSE", {
                "key": "mit", "matcher": "exact", "confidence": 100.0,
            }),
            ("repo-1.0/vendor/LICENSE", {
                "key": "apache-2.0", "matcher": "exact",
                "confidence": 100.0,
            }),
        ],
    )
    assert row["license"] == "mit"
    assert row["matched_files"] == ["LICENSE"]


def test_verdict_errored_rows_never_candidates():
    row = container_verdict(
        "c",
        [
            ("LICENSE", {
                "key": None, "matcher": None, "confidence": 0.0,
                "error": "oversized",
            }),
            ("COPYING", {
                "key": "gpl-3.0", "matcher": "exact", "confidence": 100.0,
            }),
        ],
    )
    assert row["license"] == "gpl-3.0"
    assert row["matched_files"] == ["COPYING"]
