"""Streaming container ingestion (licensee_tpu/ingest/): the ``::``
manifest grammar, tar/zip/git blob sources, the 64 KiB skip-not-
truncate cap, loose-vs-container output parity (the golden gate),
torn-container refusal, resume at container granularity, and the
container-level verdict algebra's parity with projects/project.py.
"""

from __future__ import annotations

import io
import json
import os
import re
import subprocess
import tarfile
import zipfile

import pytest

from licensee_tpu.ingest import OVERSIZED, SkippedBlob
from licensee_tpu.ingest.sources import (
    IngestError,
    expand_manifest,
    is_container_entry,
    split_entry,
)
from licensee_tpu.ingest.verdict import container_verdict


def _body(key: str) -> str:
    from licensee_tpu.corpus.license import License

    return re.sub(r"\[(\w+)\]", "example", License.find(key).content or "")


def _make_tar(path, files: dict[str, bytes]) -> str:
    with tarfile.open(path, "w") as tf:
        for name, data in files.items():
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return str(path)


def _make_zip(path, files: dict[str, bytes]) -> str:
    with zipfile.ZipFile(path, "w") as zf:
        for name, data in files.items():
            zf.writestr(name, data)
    return str(path)


# -- the :: entry grammar --


def test_entry_grammar():
    assert split_entry("/x/archive.tar::LICENSE") == (
        "/x/archive.tar", "LICENSE",
    )
    assert split_entry("/x/a.zip::*") == ("/x/a.zip", "*")
    assert split_entry("/x/repo.git::HEAD") == ("/x/repo.git", "HEAD")
    # member names may contain further colons: split on the FIRST ::
    assert split_entry("a.tar::weird::name") == ("a.tar", "weird::name")
    # plain paths — even with a lone "::" whose prefix is no container
    assert split_entry("/plain/file.txt") is None
    assert split_entry("/not-an-archive.bin::x") is None
    assert not is_container_entry("/plain/file.txt")
    assert is_container_entry("a.tar::*")


def test_plain_directory_with_separator_stays_loose(tmp_path):
    """A '::' entry whose prefix is an ordinary directory (no git
    layout) is NOT a container claim: it stays a loose path whose
    failed read is row-contained — one read_error row, never a fatal
    IngestError for the whole run."""
    from licensee_tpu.projects.batch_project import BatchProject

    d = tmp_path / "data"
    d.mkdir()
    (d / "v2").mkdir()
    entry = f"{d}::v2/file.txt"
    assert split_entry(entry) is None
    assert not is_container_entry(entry)
    project = BatchProject([entry], batch_size=8, mesh=None)
    out = str(tmp_path / "out.jsonl")
    try:
        stats = project.run(out, resume=False)
    finally:
        project.close()
    rows = [json.loads(line) for line in open(out)]
    assert rows[0]["error"] == "read_error"
    assert stats.read_errors == 1


def test_explicit_member_routes_by_member_name(tmp_path):
    """--mode auto must route an explicit `a.tar::LICENSE` entry by
    the MEMBER's basename (its display string stays as written) —
    the same blob must score identically however it is addressed."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(tmp_path / "a.tar", {"LICENSE": _body("mit").encode()})
    out = str(tmp_path / "out.jsonl")
    project = BatchProject(
        [f"{tar}::LICENSE"], batch_size=8, mesh=None, mode="auto"
    )
    try:
        stats = project.run(out, resume=False)
    finally:
        project.close()
    row = json.loads(open(out).readline())
    assert row["path"] == f"{tar}::LICENSE"  # display as written
    assert row["key"] == "mit"  # routed + scored like a loose LICENSE
    assert stats.routed == {"license": 1}


def test_zip_duplicate_members_collapse_to_last(tmp_path):
    """Duplicate member names INSIDE one zip (an appended archive)
    collapse to one row of the archive's effective copy — last wins,
    like extraction — instead of emitting rows whose bytes silently
    all come from the last occurrence."""
    zp = str(tmp_path / "dup.zip")
    with zipfile.ZipFile(zp, "w") as zf:
        zf.writestr("LICENSE", "first copy")
        zf.writestr("LICENSE", "second copy")
    ex = expand_manifest([f"{zp}::*"])
    try:
        assert ex.paths == ["LICENSE"]
        assert ex.read_at(0) == b"second copy"
        assert ex.spans == [(f"{zp}::*", 0, 1)]
    finally:
        ex.close()


def test_empty_selector_refused(tmp_path):
    tar = _make_tar(tmp_path / "a.tar", {"LICENSE": b"x"})
    with pytest.raises(IngestError, match="empty selector"):
        expand_manifest([f"{tar}::"])


def _gzip_of(plain: str, gz) -> str:
    import gzip

    with open(plain, "rb") as src, gzip.open(gz, "wb") as dst:
        dst.write(src.read())
    return str(gz)


def test_compressed_tar_streams(tmp_path):
    """`archive.tar.gz::*` is a real path now: the sequential-window
    reader answers the same members, bytes, caps, and spans as the
    plain tar it wraps."""
    files = {
        "repo/LICENSE": _body("mit").encode(),
        "repo/BIG": b"x" * (64 * 1024 + 1),
        "repo/README": b"hello",
    }
    plain = _make_tar(tmp_path / "a.tar", files)
    gz = _gzip_of(plain, tmp_path / "a.tar.gz")
    ex = expand_manifest([f"{gz}::*"])
    try:
        assert ex.paths == list(files)
        assert ex.read_at(0) == files["repo/LICENSE"]
        big = ex.read_at(1)
        assert isinstance(big, SkippedBlob) and big.error == OVERSIZED
        assert ex.read_at(2) == b"hello"
        assert ex.spans == [(f"{gz}::*", 0, 3)]
    finally:
        ex.close()


def test_compressed_tar_window_reorder_never_rescans(tmp_path):
    """The batch pipeline's bounded read reordering (inflight produce
    batches) must pop the forward window's cache, never rescan the
    stream from zero."""
    files = {f"m{i}": f"blob {i}".encode() for i in range(6)}
    plain = _make_tar(tmp_path / "a.tar", files)
    gz = _gzip_of(plain, tmp_path / "a.tar.gz")
    ex = expand_manifest([f"{gz}::*"])
    try:
        # read ahead, then behind (the laggard in-flight batch)
        assert ex.read_at(4) == b"blob 4"
        assert ex.read_at(0) == b"blob 0"
        assert ex.read_at(2) == b"blob 2"
        assert ex.read_at(1) == b"blob 1"
        assert ex.read_at(3) == b"blob 3"
        assert ex.read_at(5) == b"blob 5"
        assert ex._containers[0].rescans == 0
    finally:
        ex.close()


def test_compressed_tar_end_to_end_matches_plain(tmp_path):
    """The golden gate for the .tar.gz path: byte-identical per-blob
    JSONL and container sidecar to the plain-tar run of the same
    blobs."""
    from licensee_tpu.projects.batch_project import BatchProject

    files = {
        f"r/LICENSE_{i:02d}": (
            f"Copyright (c) {2000 + i}\n\n{_body('mit')}"
        ).encode()
        for i in range(12)
    }
    plain = _make_tar(tmp_path / "a.tar", files)
    gz = _gzip_of(plain, tmp_path / "a.tar.gz")
    outs = {}
    for label, entry in (("tar", f"{plain}::*"), ("gz", f"{gz}::*")):
        out = str(tmp_path / f"{label}.jsonl")
        project = BatchProject([entry], batch_size=4, mesh=None)
        try:
            project.run(out, resume=False)
        finally:
            project.close()
        with open(out, "rb") as f:
            outs[label] = f.read()
        with open(f"{out}.containers.jsonl", "rb") as f:
            outs[f"{label}_containers"] = f.read()
    assert outs["gz"] == outs["tar"]
    assert outs["gz_containers"] == outs["tar_containers"].replace(
        b".tar::", b".tar.gz::"
    )


def test_empty_container_still_emits_verdict_row(tmp_path):
    """A container with zero regular members (directories only) gets
    a {"files": 0, "license": null} row — never a does-not-cover
    refusal after a complete run."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = str(tmp_path / "empty.tar")
    with tarfile.open(tar, "w") as tf:
        info = tarfile.TarInfo(name="only-a-dir/")
        info.type = tarfile.DIRTYPE
        tf.addfile(info)
    loose = tmp_path / "LICENSE"
    loose.write_bytes(_body("mit").encode())
    out = str(tmp_path / "out.jsonl")
    project = BatchProject(
        [f"{tar}::*", str(loose)], batch_size=8, mesh=None
    )
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    with open(f"{out}.containers.jsonl", encoding="utf-8") as f:
        containers = [json.loads(line) for line in f]
    assert containers == [
        {
            "container": f"{tar}::*",
            "files": 0,
            "license": None,
            "licenses": [],
            "matched_files": [],
        }
    ]


def test_seq_tar_cache_hard_bound_degrades_to_rescan(tmp_path):
    """The sequential window is byte-bounded: a read order that
    strands entries (a procs pool's partial chunk view) evicts FIFO
    and pays the counted rescan fallback instead of holding the
    archive in memory."""
    files = {f"m{i}": bytes([65 + i]) * 3000 for i in range(8)}
    plain = _make_tar(tmp_path / "a.tar", files)
    gz = _gzip_of(plain, tmp_path / "a.tar.gz")
    ex = expand_manifest([f"{gz}::*"])
    try:
        c = ex._containers[0]
        c.cache_bytes_max = 10_000  # fits ~3 members
        assert ex.read_at(7) == files["m7"]  # walk caches 0..6, evicts
        assert c._cache_bytes <= 10_000
        # the evicted early ordinals still read correctly (one rescan)
        assert ex.read_at(0) == files["m0"]
        assert c.rescans >= 1
        # and a cached-late ordinal pops without another rescan
        before = c.rescans
        assert ex.read_at(6) == files["m6"]
        assert c.rescans >= before  # correctness either way
    finally:
        ex.close()


def test_mark_done_prefix_skips_completed_rows(tmp_path):
    """Resume: the completed prefix is dropped from the wants, so the
    forward walk to the first unread row caches nothing from it (and
    the descriptor carries the narrowing to procs workers)."""
    files = {f"m{i}": f"blob {i}".encode() for i in range(6)}
    plain = _make_tar(tmp_path / "a.tar", files)
    gz = _gzip_of(plain, tmp_path / "a.tar.gz")
    ex = expand_manifest([f"{gz}::*"])
    try:
        ex.mark_done_prefix(4)
        assert ex.descriptor()["done_prefix"] == 4
        c = ex._containers[0]
        assert ex.read_at(4) == b"blob 4"
        # the walk passed ordinals 0..3 without caching them
        assert c._cache == {}
        assert ex.read_at(5) == b"blob 5"
        assert c.rescans == 0
    finally:
        ex.close()


def test_torn_gzip_fails_closed(tmp_path):
    """A truncated .tar.gz must refuse at EXPANSION (the metadata
    pass decompresses the whole stream), before any row is written."""
    plain = _make_tar(
        tmp_path / "a.tar", {"LICENSE": _body("mit").encode() * 8}
    )
    gz = _gzip_of(plain, tmp_path / "a.tar.gz")
    data = open(gz, "rb").read()
    with open(gz, "wb") as f:
        f.write(data[: len(data) // 2])
    with pytest.raises(IngestError, match="compressed tar"):
        expand_manifest([f"{gz}::*"])


# -- readers: members, caps, positional reads --


def test_tar_reader_order_cap_and_missing(tmp_path):
    tar = _make_tar(
        tmp_path / "a.tar",
        {
            "z_first": b"zz",
            "a_second": b"aa",
            "BIG": b"x" * (64 * 1024 + 1),
        },
    )
    ex = expand_manifest([f"{tar}::*"])
    try:
        # archive order, not sorted
        assert ex.paths == ["z_first", "a_second", "BIG"]
        assert ex.read_at(0) == b"zz"
        big = ex.read_at(2)
        assert isinstance(big, SkippedBlob) and big.error == OVERSIZED
        assert ex.spans == [(f"{tar}::*", 0, 3)]
    finally:
        ex.close()
    # an explicit member that does not exist: a read_error row, not a
    # refusal — the container itself is sound
    ex = expand_manifest([f"{tar}::nope"])
    try:
        assert ex.paths == [f"{tar}::nope"]
        assert ex.read_at(0) is None
        assert ex.spans == []  # no whole-container span...
        # ...but the listed members form a SUBSET group: the sidecar
        # emits a container row over exactly what was listed
        assert ex.subsets == [(tar, [(0, "nope")])]
    finally:
        ex.close()


def test_zip_reader_and_cap(tmp_path):
    zp = _make_zip(
        tmp_path / "a.zip",
        {"LICENSE": _body("mit").encode(), "BIG": b"y" * (65 * 1024)},
    )
    ex = expand_manifest([f"{zp}::*"])
    try:
        assert ex.paths == ["LICENSE", "BIG"]
        assert ex.read_at(0) == _body("mit").encode()
        assert isinstance(ex.read_at(1), SkippedBlob)
    finally:
        ex.close()


def test_duplicate_member_names_across_containers(tmp_path):
    """Two containers holding the same member name: reads are
    positional, so each row gets its own container's bytes."""
    t1 = _make_tar(tmp_path / "one.tar", {"LICENSE": b"first"})
    t2 = _make_tar(tmp_path / "two.tar", {"LICENSE": b"second"})
    ex = expand_manifest([f"{t1}::*", f"{t2}::*"])
    try:
        assert ex.paths == ["LICENSE", "LICENSE"]
        assert ex.read_at(0) == b"first"
        assert ex.read_at(1) == b"second"
    finally:
        ex.close()


def test_mixed_manifest_spans(tmp_path):
    loose = tmp_path / "loose.txt"
    loose.write_bytes(b"loose bytes")
    tar = _make_tar(tmp_path / "a.tar", {"m1": b"1", "m2": b"2"})
    ex = expand_manifest([str(loose), f"{tar}::m1", f"{tar}::*"])
    try:
        assert ex.paths == [str(loose), f"{tar}::m1", "m1", "m2"]
        assert ex.read_at(0) == b"loose bytes"
        assert ex.read_at(1) == b"1"
        assert ex.spans == [(f"{tar}::*", 2, 2)]
        # the explicit member forms its own subset group beside the
        # whole-container span
        assert ex.subsets == [(tar, [(1, "m1")])]
    finally:
        ex.close()


def test_oversized_loose_file_skipped(tmp_path):
    from licensee_tpu.serve.featurize import read_capped

    big = tmp_path / "BIG_LICENSE"
    big.write_bytes(b"z" * (64 * 1024 + 1))
    got = read_capped(str(big))
    assert isinstance(got, SkippedBlob) and got.error == OVERSIZED
    ok = tmp_path / "ok"
    ok.write_bytes(b"z" * (64 * 1024))  # exactly at the cap: kept
    assert read_capped(str(ok)) == b"z" * (64 * 1024)


# -- torn-container refusal --


def test_failed_expansion_leaks_no_handles(tmp_path):
    """A torn container midway through a manifest must close the
    handles already opened for the containers before it."""
    good = _make_tar(tmp_path / "good.tar", {"LICENSE": b"x"})
    torn = str(tmp_path / "torn.tar")
    _make_tar(torn, {"LICENSE": _body("mit").encode() * 4})
    with open(torn, "r+b") as f:
        f.truncate(1000)
    before = len(os.listdir("/proc/self/fd"))
    with pytest.raises(IngestError):
        expand_manifest([f"{good}::*", f"{torn}::*"])
    assert len(os.listdir("/proc/self/fd")) == before


def test_oversized_prom_kind_exported(tmp_path, capsys):
    """The skipped_oversized counter reaches the --prom-file
    exposition beside every other result kind."""
    from licensee_tpu.cli.main import main

    big = tmp_path / "BIG_LICENSE"
    big.write_bytes(b"x" * (70 * 1024))
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{big}\n")
    prom = tmp_path / "run.prom"
    rc = main([
        "batch-detect", str(manifest), "--output",
        str(tmp_path / "o.jsonl"), "--mesh", "none",
        "--prom-file", str(prom),
    ])
    assert rc == 0
    text = prom.read_text()
    assert 'batch_rows{kind="skipped_oversized"} 1' in text


def test_torn_tar_refused(tmp_path):
    tar = _make_tar(
        tmp_path / "a.tar", {"LICENSE": _body("mit").encode() * 4}
    )
    with open(tar, "r+b") as f:
        f.truncate(1000)  # keep the header, tear the member data
    with pytest.raises(IngestError):
        expand_manifest([f"{tar}::*"])


def test_garbage_zip_refused(tmp_path):
    bad = tmp_path / "bad.zip"
    bad.write_bytes(b"this is not a zip central directory")
    with pytest.raises(IngestError, match="cannot read zip"):
        expand_manifest([f"{bad}::*"])


def test_truncated_git_pack_refused(git_repo):
    repo = git_repo
    # corrupt every packfile and loose object: the revision's root tree
    # becomes unreachable and expansion must refuse, not emit rows
    for root, _dirs, files in os.walk(os.path.join(repo, ".git", "objects")):
        for name in files:
            p = os.path.join(root, name)
            os.chmod(p, 0o644)
            with open(p, "r+b") as f:
                f.truncate(max(1, os.path.getsize(p) // 4))
    with pytest.raises(IngestError):
        expand_manifest([f"{repo}::HEAD"])


# -- git containers --


@pytest.fixture
def git_repo(tmp_path):
    repo = str(tmp_path / "proj.git")
    os.makedirs(repo)
    env = {
        **os.environ,
        "GIT_CONFIG_GLOBAL": "/dev/null",
        "GIT_CONFIG_SYSTEM": "/dev/null",
    }

    def git(*args):
        subprocess.run(
            ["git", "-C", repo, *args],
            check=True, capture_output=True, env=env,
        )

    git("init", "-q")
    with open(os.path.join(repo, "LICENSE"), "w", encoding="utf-8") as f:
        f.write(_body("isc"))
    with open(os.path.join(repo, "BIG"), "wb") as f:
        f.write(b"x" * (80 * 1024))
    os.makedirs(os.path.join(repo, "src"))
    with open(os.path.join(repo, "src", "x.py"), "w") as f:
        f.write("pass\n")
    git("add", ".")
    git("-c", "user.email=a@b", "-c", "user.name=n", "commit", "-qm", "x")
    # repack so the blobs live in a packfile, the forge-scan shape
    git("gc", "-q", "--aggressive")
    return repo


def test_git_container_root_tree_and_cap(git_repo):
    ex = expand_manifest([f"{git_repo}::HEAD"])
    try:
        # root-level blobs only (git_project.rb:64-76) — src/x.py is not
        # a root entry
        assert set(ex.paths) == {"LICENSE", "BIG"}
        i_lic = ex.paths.index("LICENSE")
        i_big = ex.paths.index("BIG")
        assert ex.read_at(i_lic).decode("utf-8") == _body("isc")
        assert isinstance(ex.read_at(i_big), SkippedBlob)  # the 64 KiB cap
    finally:
        ex.close()


def test_git_container_end_to_end(git_repo, tmp_path):
    from licensee_tpu.projects.batch_project import BatchProject

    out = str(tmp_path / "git.jsonl")
    project = BatchProject([f"{git_repo}::HEAD"], batch_size=8, mesh=None)
    try:
        stats = project.run(out, resume=False)
    finally:
        project.close()
    rows = {r["path"]: r for r in map(json.loads, open(out))}
    assert rows["LICENSE"]["key"] == "isc"
    assert rows["BIG"]["error"] == "oversized"
    assert stats.skipped_oversized == 1
    containers = [
        json.loads(line) for line in open(f"{out}.containers.jsonl")
    ]
    assert containers == [
        {
            "container": f"{git_repo}::HEAD",
            "files": 2,
            "license": "isc",
            "licenses": ["isc"],
            "matched_files": ["LICENSE"],
        }
    ]


# -- the golden parity gate: containers of the vendored corpus --


@pytest.mark.slow
def test_vendored_corpus_container_parity(tmp_path):
    """A tarball AND a zip of the vendored corpus must yield
    byte-identical (sha256) per-blob JSONL to the loose-file manifest
    run — the acceptance gate for the streaming sources."""
    import hashlib

    from licensee_tpu.projects.batch_project import BatchProject
    from licensee_tpu.vendor_paths import LICENSE_DIR

    paths = sorted(
        os.path.join(LICENSE_DIR, n)
        for n in os.listdir(LICENSE_DIR)
        if n.endswith(".txt")
    )
    assert len(paths) >= 40
    files = {}
    for p in paths:
        with open(p, "rb") as f:
            files[p] = f.read()  # members stored under the loose names
    tar = _make_tar(tmp_path / "corpus.tar", files)
    zp = _make_zip(tmp_path / "corpus.zip", files)

    digests = {}
    for label, manifest in (
        ("loose", paths),
        ("tar", [f"{tar}::*"]),
        ("zip", [f"{zp}::*"]),
    ):
        out = str(tmp_path / f"{label}.jsonl")
        project = BatchProject(manifest, batch_size=16, mesh=None)
        try:
            project.run(out, resume=False)
        finally:
            project.close()
        with open(out, "rb") as f:
            digests[label] = hashlib.sha256(f.read()).hexdigest()
    assert digests["tar"] == digests["loose"]
    assert digests["zip"] == digests["loose"]


# -- resume at container granularity --


@pytest.mark.slow
def test_resume_mid_container(tmp_path):
    """A run killed mid-container (simulated as the torn output a
    SIGKILL leaves: a complete prefix plus half a row) must resume to
    byte-identical per-blob output AND an identical container-verdict
    sidecar."""
    from licensee_tpu.projects.batch_project import BatchProject

    files = {
        f"repo/LICENSE_{i:02d}": (
            f"Copyright (c) {2000 + i}\n\n{_body('mit')}"
        ).encode()
        for i in range(24)
    }
    tar = _make_tar(tmp_path / "r.tar", files)
    entry = f"{tar}::*"

    golden = str(tmp_path / "golden.jsonl")
    project = BatchProject([entry], batch_size=8, mesh=None, dedupe=False)
    try:
        project.run(golden, resume=False)
    finally:
        project.close()
    with open(golden, "rb") as f:
        golden_bytes = f.read()
    with open(f"{golden}.containers.jsonl", "rb") as f:
        golden_containers = f.read()

    # fabricate the crash artifact: 10 complete rows + a torn 11th,
    # beside the sidecar the dead run wrote at open
    out = str(tmp_path / "resumed.jsonl")
    lines = golden_bytes.split(b"\n")
    with open(out, "wb") as f:
        f.write(b"\n".join(lines[:10]) + b"\n" + lines[10][: len(lines[10]) // 2])
    with open(f"{golden}.meta.json", "rb") as f:
        meta = f.read()
    with open(f"{out}.meta.json", "wb") as f:
        f.write(meta)

    project = BatchProject([entry], batch_size=8, mesh=None, dedupe=False)
    try:
        project.run(out, resume=True)
    finally:
        project.close()
    with open(out, "rb") as f:
        assert f.read() == golden_bytes
    with open(f"{out}.containers.jsonl", "rb") as f:
        assert f.read() == golden_containers


def test_rewritten_container_refuses_resume(tmp_path):
    """The expansion fingerprint in the resume sidecar: an archive
    rewritten between runs (different member set) must refuse to
    resume instead of appending rows of a foreign container."""
    from licensee_tpu.projects.batch_project import (
        BatchProject,
        ResumeConfigError,
    )

    tar = str(tmp_path / "a.tar")
    _make_tar(tar, {"LICENSE": _body("mit").encode(), "A": b"a"})
    out = str(tmp_path / "out.jsonl")
    project = BatchProject([f"{tar}::*"], batch_size=8, mesh=None)
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    _make_tar(tar, {"LICENSE": _body("mit").encode(), "B": b"b"})
    project = BatchProject([f"{tar}::*"], batch_size=8, mesh=None)
    try:
        with pytest.raises(ResumeConfigError, match="ingest"):
            project.run(out, resume=True)
    finally:
        project.close()


def test_rewritten_content_same_names_refuses_resume(tmp_path):
    """Same member NAMES, different bytes: the fingerprint folds
    content evidence (tar layout/mtimes, zip CRCs, git oids), so a
    repacked archive still refuses instead of appending rows scored
    from different content."""
    from licensee_tpu.projects.batch_project import (
        BatchProject,
        ResumeConfigError,
    )

    zp = str(tmp_path / "a.zip")
    _make_zip(zp, {"LICENSE": _body("mit").encode(), "A": b"old bytes"})
    out = str(tmp_path / "out.jsonl")
    project = BatchProject([f"{zp}::*"], batch_size=8, mesh=None)
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    _make_zip(zp, {"LICENSE": _body("mit").encode(), "A": b"NEW BYTES"})
    project = BatchProject([f"{zp}::*"], batch_size=8, mesh=None)
    try:
        with pytest.raises(ResumeConfigError, match="ingest"):
            project.run(out, resume=True)
    finally:
        project.close()


# -- expanded-count striping (the PR 15 tentpole) --


def _span_files(n: int, body_key: str = "mit") -> dict[str, bytes]:
    return {
        f"repo/LICENSE_{i:02d}": (
            f"Copyright (c) {2000 + i}\n\n{_body(body_key)}"
        ).encode()
        for i in range(n)
    }


def test_expansion_restrict_is_one_stripes_view(tmp_path):
    """restrict(lo, hi): span-local rows, clipped container groups,
    closed handles for containers outside the span, and a
    span-INDEPENDENT total + fingerprint."""
    t1 = _make_tar(tmp_path / "one.tar", {"a": b"1", "b": b"2"})
    t2 = _make_tar(tmp_path / "two.tar", {"c": b"3", "d": b"4"})
    full = expand_manifest([f"{t1}::*", f"{t2}::*"])
    try:
        total, fp = full.total, full.fingerprint()
        assert total == 4
    finally:
        full.close()
    ex = expand_manifest([f"{t1}::*", f"{t2}::*"], span=(2, 4))
    try:
        # the second container's members only; the first tar's handle
        # is closed (one live container)
        assert ex.paths == ["c", "d"]
        assert ex.read_at(0) == b"3" and ex.read_at(1) == b"4"
        assert ex.spans == [(f"{t2}::*", 0, 2)]
        assert len(ex._containers) == 1
        # full-expansion values survive the restrict: every stripe's
        # resume sidecar (and the merged output's) agree
        assert ex.total == total
        assert ex.fingerprint() == fp
        assert ex.span == (2, 4)
    finally:
        ex.close()
    # a mid-container span clips the group
    ex = expand_manifest([f"{t1}::*", f"{t2}::*"], span=(1, 3))
    try:
        assert ex.paths == ["b", "c"]
        assert ex.spans == [
            (f"{t1}::*", 0, 1), (f"{t2}::*", 1, 1),
        ]
        assert len(ex._containers) == 2
    finally:
        ex.close()


def test_striped_container_ranks_concat_to_one_process_run(tmp_path):
    """Two ranks over a container manifest (the constructor's
    process_index/count path — multi-host and stripe workers both ride
    it) stripe by EXPANDED blob count; their shards concatenate
    byte-identical to the 1-process run."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(tmp_path / "a.tar", _span_files(11))
    entry = f"{tar}::*"
    golden = str(tmp_path / "golden.jsonl")
    project = BatchProject([entry], batch_size=4, mesh=None)
    try:
        project.run(golden, resume=False)
    finally:
        project.close()
    out = str(tmp_path / "out.jsonl")
    shard_bytes = []
    for rank in (0, 1):
        project = BatchProject(
            [entry], batch_size=4, mesh=None,
            process_index=rank, process_count=2,
        )
        try:
            assert len(project.paths) in (5, 6)  # expanded span, not 1
            project.run(out, resume=False)
        finally:
            project.close()
        shard = f"{out}.shard-{rank:05d}-of-00002"
        with open(shard, "rb") as f:
            shard_bytes.append(f.read())
        # striped ranks write per-blob rows only: the container may
        # span shards, so the sidecar is the MERGE's job
        assert not os.path.exists(f"{shard}.containers.jsonl")
    with open(golden, "rb") as f:
        assert b"".join(shard_bytes) == f.read()


def test_stripe_runner_expanded_denominator_and_merged_sidecar(tmp_path):
    """StripeRunner over a container manifest: the span denominator is
    the EXPANDED blob count, and the merged output carries exactly one
    container-verdict row even though the container's blobs spanned
    both stripes (the blob-level join, parity with the 1-process
    sidecar)."""
    from licensee_tpu.parallel.stripes import StripeRunner

    tar = _make_tar(tmp_path / "a.tar", _span_files(9))
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{tar}::*\n")
    runner = StripeRunner(
        str(manifest), str(tmp_path / "o.jsonl"), 2,
        argv_for=lambda i, n, resume=True: ["true"],
    )
    assert runner.n_entries == 9  # expanded blobs, not 1 raw entry
    layout = runner.container_layout
    assert layout["total"] == 9
    assert layout["spans"] == [(f"{tar}::*", 0, 9)]
    assert layout["fingerprint"]
    # more stripes than expanded blobs still refuses
    with pytest.raises(ValueError, match="more stripes"):
        StripeRunner(
            str(manifest), str(tmp_path / "o2.jsonl"), 10,
            argv_for=lambda i, n, resume=True: ["true"],
        )


def test_resume_mid_container_under_two_stripes(tmp_path):
    """The 2-stripe torn-tail drill: a stripe worker killed mid-
    container (complete prefix + half a row in its shard) resumes to a
    byte-identical shard, and the shards still concatenate to the
    1-process output."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(tmp_path / "r.tar", _span_files(16))
    entry = f"{tar}::*"
    golden = str(tmp_path / "golden.jsonl")
    project = BatchProject([entry], batch_size=4, mesh=None, dedupe=False)
    try:
        project.run(golden, resume=False)
    finally:
        project.close()
    with open(golden, "rb") as f:
        golden_bytes = f.read()

    out = str(tmp_path / "out.jsonl")

    def rank1() -> "BatchProject":
        return BatchProject(
            [entry], batch_size=4, mesh=None, dedupe=False,
            process_index=1, process_count=2,
        )

    project = rank1()
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    shard = f"{out}.shard-00001-of-00002"
    with open(shard, "rb") as f:
        shard_golden = f.read()
    # fabricate the crash artifact: 3 complete rows + a torn 4th,
    # beside the sidecar the dead incarnation wrote at open
    lines = shard_golden.split(b"\n")
    with open(shard, "wb") as f:
        f.write(
            b"\n".join(lines[:3]) + b"\n" + lines[3][: len(lines[3]) // 2]
        )
    project = rank1()
    try:
        project.run(out, resume=True)
    finally:
        project.close()
    with open(shard, "rb") as f:
        assert f.read() == shard_golden
    # rank 0's shard + the resumed rank 1 shard == the 1-process run
    project = BatchProject(
        [entry], batch_size=4, mesh=None, dedupe=False,
        process_index=0, process_count=2,
    )
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    with open(f"{out}.shard-00000-of-00002", "rb") as f:
        assert f.read() + shard_golden == golden_bytes


@pytest.mark.slow
def test_cli_stripes_multi_container_end_to_end(tmp_path):
    """The acceptance drill: `batch-detect --stripes 2` over a
    MULTI-container manifest (a container's blobs spanning both
    stripes by construction) — merged JSONL byte-identical to the
    1-process run, container sidecar with exactly one row per
    container."""
    import subprocess
    import sys

    t1 = _make_tar(tmp_path / "one.tar", _span_files(7))
    zp = _make_zip(
        tmp_path / "two.zip",
        {"LICENSE": _body("isc").encode(), "README": b"hi"},
    )
    loose = tmp_path / "LICENSE_LOOSE"
    loose.write_bytes(_body("mit").encode())
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{t1}::*\n{loose}\n{zp}::*\n")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    outs = {}
    for label, extra in (("one", []), ("two", ["--stripes", "2"])):
        out = str(tmp_path / f"{label}.jsonl")
        subprocess.run(
            [
                sys.executable, "-m", "licensee_tpu.cli.main",
                "batch-detect", str(manifest), "--output", out,
                "--mesh", "none", "--batch-size", "4", *extra,
            ],
            check=True, env=env, capture_output=True,
        )
        with open(out, "rb") as f:
            outs[label] = f.read()
        with open(f"{out}.containers.jsonl", "rb") as f:
            outs[f"{label}_containers"] = f.read()
    assert outs["two"] == outs["one"]
    assert outs["two_containers"] == outs["one_containers"]
    rows = [
        json.loads(line)
        for line in outs["two_containers"].decode().splitlines()
    ]
    # exactly one verdict row per container, in expansion order
    assert [r["container"] for r in rows] == [f"{t1}::*", f"{zp}::*"]
    assert [r["files"] for r in rows] == [7, 2]


def test_rewritten_container_refuses_striped_resume(tmp_path):
    """The expansion fingerprint is span-independent and rides every
    shard's sidecar: a rewritten archive refuses a striped rank's
    resume exactly like a single-process one."""
    from licensee_tpu.projects.batch_project import (
        BatchProject,
        ResumeConfigError,
    )

    tar = str(tmp_path / "a.tar")
    _make_tar(tar, _span_files(6))
    out = str(tmp_path / "out.jsonl")
    project = BatchProject(
        [f"{tar}::*"], batch_size=4, mesh=None,
        process_index=0, process_count=2,
    )
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    _make_tar(tar, _span_files(6, "isc"))
    project = BatchProject(
        [f"{tar}::*"], batch_size=4, mesh=None,
        process_index=0, process_count=2,
    )
    try:
        with pytest.raises(ResumeConfigError, match="ingest"):
            project.run(out, resume=True)
    finally:
        project.close()


def test_cli_stripes_container_resume_preflight(tmp_path, capsys):
    """The striped rerun preflight expands container manifests so the
    expansion fingerprint compares: a complete output no-ops, a
    rewritten archive refuses before any worker spawns."""
    from licensee_tpu.cli.main import main
    from licensee_tpu.projects.batch_project import BatchProject

    tar = str(tmp_path / "a.tar")
    _make_tar(tar, _span_files(4))
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{tar}::*\n")
    output = str(tmp_path / "o.jsonl")
    project = BatchProject([f"{tar}::*"], batch_size=8, mesh=None)
    try:
        project.run(output, resume=False)
    finally:
        project.close()

    # complete output + unchanged archive: the runner no-ops
    rc = main([
        "batch-detect", str(manifest), "--stripes", "1",
        "--output", output, "--mesh", "none", "--batch-size", "8",
    ])
    err = capsys.readouterr().err
    assert rc == 0
    assert "already complete" in err

    # rewritten archive: refused at preflight, before any spawn
    _make_tar(tar, _span_files(4, "isc"))
    rc = main([
        "batch-detect", str(manifest), "--stripes", "1",
        "--output", output, "--mesh", "none", "--batch-size", "8",
    ])
    err = capsys.readouterr().err
    assert rc == 1
    assert "ingest" in err and "configuration differs" in err


# -- --featurize-procs over containers (per-process re-open) --


def test_featurize_procs_descriptor_reopens_no_inherited_fds(tmp_path):
    """The worker-process recipe is a PICKLABLE descriptor (entries +
    span + fingerprint), never the parent's live handles: _mp_init
    re-expands in the worker, opening its OWN container fds, and a
    changed archive fails the fingerprint check instead of silently
    reading different bytes."""
    import pickle

    from licensee_tpu.ingest.sources import ManifestExpansion
    from licensee_tpu.projects import batch_project as bp

    tar = _make_tar(tmp_path / "a.tar", {"LICENSE": _body("mit").encode()})
    parent = expand_manifest([f"{tar}::*"])
    try:
        desc = parent.descriptor()
        pickle.dumps(desc)  # the spawn crossing carries ONLY this
        with pytest.raises(TypeError):
            pickle.dumps(parent)  # live handles never cross
        worker = ManifestExpansion.from_descriptor(desc)
        try:
            # a fresh fd in the "worker", not the parent's
            assert worker._containers[0]._fd != parent._containers[0]._fd
            assert worker.paths == parent.paths
            assert worker.read_at(0) == parent.read_at(0)
        finally:
            worker.close()
        # the worker-side fingerprint gate: archive rewritten between
        # the parent's expansion and the worker's boot -> refuse
        _make_tar(tar, {"LICENSE": _body("isc").encode()})
        with pytest.raises(IngestError, match="changed"):
            ManifestExpansion.from_descriptor(desc)
    finally:
        parent.close()
        bp._MP_STATE.clear()


@pytest.mark.slow
def test_featurize_procs_containers_bit_identical(tmp_path):
    """--featurize-procs over a container manifest: byte-identical to
    the thread path, with positional dedup preserved (duplicate member
    names across containers keep their own bytes)."""
    from licensee_tpu.projects.batch_project import BatchProject

    t1 = _make_tar(
        tmp_path / "one.tar", {"LICENSE": _body("mit").encode()}
    )
    t2 = _make_tar(
        tmp_path / "two.tar", {"LICENSE": _body("isc").encode()}
    )
    manifest = [f"{t1}::*", f"{t2}::*"]
    outs = {}
    for label, procs in (("threads", 0), ("procs", 2)):
        out = str(tmp_path / f"{label}.jsonl")
        project = BatchProject(
            manifest, batch_size=4, mesh=None, featurize_procs=procs
        )
        try:
            project.run(out, resume=False)
        finally:
            project.close()
        with open(out, "rb") as f:
            outs[label] = f.read()
    assert outs["procs"] == outs["threads"]
    rows = [
        json.loads(line)
        for line in outs["procs"].decode().splitlines()
    ]
    # positional reads: same member NAME, each container's own verdict
    assert [r["path"] for r in rows] == ["LICENSE", "LICENSE"]
    assert rows[0]["key"] == "mit"
    assert rows[1]["key"] == "isc"


def test_cli_stdout_mode_prints_container_rows(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    tar = _make_tar(
        tmp_path / "a.tar",
        {
            "r/LICENSE-MIT": _body("mit").encode(),
            "r/LICENSE-APACHE": _body("apache-2.0").encode(),
            "r/BIG": b"x" * (70 * 1024),
        },
    )
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{tar}::*\n")
    rc = main(["batch-detect", str(manifest), "--mesh", "none"])
    assert rc == 0
    rows = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
    ]
    blob_rows = {r["path"]: r for r in rows if "path" in r}
    assert blob_rows["r/LICENSE-MIT"]["key"] == "mit"
    assert blob_rows["r/BIG"]["error"] == "oversized"
    container_rows = [r for r in rows if "container" in r]
    assert len(container_rows) == 1
    assert container_rows[0]["license"] == "other"
    assert container_rows[0]["spdx_expression"] == "MIT OR Apache-2.0"


# -- explicitly-listed member subsets (the PR 15 satellite) --


def test_subset_members_emit_container_row(tmp_path):
    """`a.tar::LICENSE-MIT` + `a.tar::LICENSE-APACHE` in one manifest:
    one container row over exactly the listed members (by MEMBER name,
    not display string), instead of silently skipping the sidecar."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(
        tmp_path / "a.tar",
        {
            "LICENSE-MIT": _body("mit").encode(),
            "LICENSE-APACHE": _body("apache-2.0").encode(),
            "UNLISTED": _body("isc").encode(),
        },
    )
    out = str(tmp_path / "out.jsonl")
    project = BatchProject(
        [f"{tar}::LICENSE-MIT", f"{tar}::LICENSE-APACHE"],
        batch_size=8, mesh=None,
    )
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    with open(f"{out}.containers.jsonl", encoding="utf-8") as f:
        containers = [json.loads(line) for line in f]
    assert len(containers) == 1
    row = containers[0]
    assert row["container"] == tar
    assert row["files"] == 2  # exactly the listed members, not 3
    assert row["license"] == "other"
    assert row["spdx_expression"] == "MIT OR Apache-2.0"
    assert sorted(row["matched_files"]) == [
        "LICENSE-APACHE", "LICENSE-MIT",
    ]


def test_subset_members_interleaved_with_other_entries(tmp_path):
    """Subset members of one container may interleave other manifest
    entries; the group still joins into one row, and an interleaved
    loose file stays out of it."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(
        tmp_path / "a.tar",
        {
            "COPYING": _body("gpl-3.0").encode(),
            "COPYING.lesser": _body("lgpl-3.0").encode(),
        },
    )
    loose = tmp_path / "LICENSE"
    loose.write_bytes(_body("mit").encode())
    out = str(tmp_path / "out.jsonl")
    project = BatchProject(
        [f"{tar}::COPYING.lesser", str(loose), f"{tar}::COPYING"],
        batch_size=8, mesh=None,
    )
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    with open(f"{out}.containers.jsonl", encoding="utf-8") as f:
        containers = [json.loads(line) for line in f]
    assert len(containers) == 1
    # the reference's LGPL dual-file exception over exactly the
    # listed pair — the loose MIT row never joins the container
    assert containers[0]["license"] == "lgpl-3.0"
    assert containers[0]["files"] == 2


def test_cli_stdout_mode_prints_subset_rows(tmp_path, capsys):
    from licensee_tpu.cli.main import main

    tar = _make_tar(
        tmp_path / "a.tar",
        {
            "LICENSE": _body("mit").encode(),
            "OTHER": b"not a license",
        },
    )
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{tar}::LICENSE\n")
    rc = main(["batch-detect", str(manifest), "--mesh", "none"])
    assert rc == 0
    rows = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
    ]
    container_rows = [r for r in rows if "container" in r]
    assert len(container_rows) == 1
    assert container_rows[0]["container"] == tar
    assert container_rows[0]["files"] == 1
    assert container_rows[0]["license"] == "mit"


# -- the container verdict algebra (parity with projects/project.py) --


def _fs_verdict(tmp_path, files: dict[str, bytes]):
    from licensee_tpu.projects.fs_project import FSProject

    d = tmp_path / "fsproj"
    os.makedirs(d, exist_ok=True)
    for name, data in files.items():
        with open(d / name, "wb") as f:
            f.write(data)
    project = FSProject(str(d))
    return (
        project.license.key if project.license else None,
        sorted(lic.key for lic in project.licenses),
    )


def _rows_for(files: dict[str, bytes], tmp_path, tag: str):
    """Finished per-blob rows for a file set, via the real batch path."""
    from licensee_tpu.projects.batch_project import BatchProject

    tar = _make_tar(tmp_path / f"{tag}.tar", files)
    out = str(tmp_path / f"{tag}.jsonl")
    project = BatchProject([f"{tar}::*"], batch_size=8, mesh=None)
    try:
        project.run(out, resume=False)
    finally:
        project.close()
    with open(f"{out}.containers.jsonl", encoding="utf-8") as f:
        return json.load(f)


VERDICT_SHAPES = {
    "single": {"LICENSE": "mit"},
    "dual": {"LICENSE-APACHE": "apache-2.0", "LICENSE-MIT": "mit"},
    "lgpl_pair": {"COPYING.lesser": "lgpl-3.0", "COPYING": "gpl-3.0"},
    "none": {},
}


@pytest.mark.parametrize("shape", sorted(VERDICT_SHAPES))
def test_container_verdict_matches_project(shape, tmp_path):
    """The acceptance gate: container licenses[] rows must match the
    projects/project.py verdict on the same file set."""
    files = {
        name: _body(key).encode()
        for name, key in VERDICT_SHAPES[shape].items()
    }
    files["README.md"] = b"# a readme\n"
    row = _rows_for(files, tmp_path, shape)
    fs_license, fs_keys = _fs_verdict(tmp_path, files)
    assert row["license"] == fs_license
    assert sorted(row["licenses"]) == fs_keys


def test_verdict_dual_license_spdx_expression(tmp_path):
    row = _rows_for(
        {
            "LICENSE-APACHE": _body("apache-2.0").encode(),
            "LICENSE-MIT": _body("mit").encode(),
        },
        tmp_path,
        "dual_spdx",
    )
    # reference verdict preserved (multi-license -> other), expression
    # composed on top — archive order decides the operand order
    assert row["license"] == "other"
    assert row["spdx_expression"] == "Apache-2.0 OR MIT"


def test_verdict_unmatched_license_file_is_other():
    # license_file.rb:92-98: a scored license file failing every
    # matcher still counts as 'other'
    row = container_verdict(
        "c", [("LICENSE", {"key": None, "matcher": None, "confidence": 0.0})]
    )
    assert row["license"] == "other"
    assert row["licenses"] == ["other"]
    assert "spdx_expression" not in row


def test_verdict_copyright_only_excluded():
    # project.rb:153-155: COPYRIGHT-only files never decide the verdict
    row = container_verdict(
        "c",
        [
            ("COPYRIGHT", {
                "key": "no-license", "matcher": "copyright",
                "confidence": 100.0,
            }),
            ("LICENSE", {
                "key": "mit", "matcher": "exact", "confidence": 100.0,
            }),
        ],
    )
    assert row["license"] == "mit"
    # score order: LICENSE (1.0) before COPYRIGHT (0.35), project.rb:111
    assert row["licenses"] == ["mit", "no-license"]


def test_verdict_shared_prefix_root_only():
    # nested members never count as root candidates; the shared
    # top-level wrapper (forge tarball shape) is stripped first
    row = container_verdict(
        "c",
        [
            ("repo-1.0/LICENSE", {
                "key": "mit", "matcher": "exact", "confidence": 100.0,
            }),
            ("repo-1.0/vendor/LICENSE", {
                "key": "apache-2.0", "matcher": "exact",
                "confidence": 100.0,
            }),
        ],
    )
    assert row["license"] == "mit"
    assert row["matched_files"] == ["LICENSE"]


def test_verdict_errored_rows_never_candidates():
    row = container_verdict(
        "c",
        [
            ("LICENSE", {
                "key": None, "matcher": None, "confidence": 0.0,
                "error": "oversized",
            }),
            ("COPYING", {
                "key": "gpl-3.0", "matcher": "exact", "confidence": 100.0,
            }),
        ],
    )
    assert row["license"] == "gpl-3.0"
    assert row["matched_files"] == ["COPYING"]
