"""Corpus facts pinned by the reference's license_spec
(spec/licensee/license_spec.rb:4-6 and friends)."""

from licensee_tpu.corpus.license import License, global_title_regex


def test_key_counts():
    all_licenses = License.all(hidden=True)
    assert len(all_licenses) == 49
    assert sum(1 for lic in all_licenses if lic.hidden_q) == 36
    assert sum(1 for lic in all_licenses if lic.featured_q) == 3
    assert sum(1 for lic in all_licenses if lic.pseudo_license) == 2


def test_default_options_exclude_hidden():
    default = License.all()
    assert all(not lic.hidden_q for lic in default)


def test_find():
    assert License.find("mit").key == "mit"
    assert License.find("MIT").key == "mit"
    assert License.find("does-not-exist") is None


def test_find_by_title():
    assert License.find_by_title("MIT License").key == "mit"
    assert (
        License.find_by_title("GNU Affero General Public License v3.0").key
        == "agpl-3.0"
    )


def test_pseudo_spdx_ids():
    assert License.find("other").spdx_id == "NOASSERTION"
    assert License.find("no-license").spdx_id == "NONE"


def test_meta_and_rules():
    mit = License.find("mit")
    assert mit.meta.spdx_id == "MIT"
    assert mit.featured_q
    assert not mit.hidden_q
    assert mit.rules["permissions"]
    assert {f.name for f in mit.fields} == {"year", "fullname"}


def test_name_without_version():
    assert License.find("gpl-3.0").name_without_version == "GNU General Public License"
    assert License.find("mit").name_without_version == "MIT License"


def test_title_regex_matches_own_title():
    for lic in License.all(hidden=True, pseudo=False):
        # '*' in a title is folded to 'u' (license.rb:147), so match against
        # the folded title like the reference does
        title = lic.title.replace("*", "u")
        assert lic.title_regex.search(title), lic.key


def test_global_title_regex_strips_titles():
    regex = global_title_regex()
    assert regex.search("MIT License\n\nPermission is hereby granted")
    assert regex.search("The MIT License (MIT)\nbody")
    assert not regex.search("Permission is hereby granted")


def test_spdx_alt_segments():
    # sanity: values are non-negative ints for every non-pseudo license
    for lic in License.all(hidden=True, pseudo=False):
        assert lic.spdx_alt_segments >= 0
