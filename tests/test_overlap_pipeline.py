"""The overlap pipeline (r8): the non-blocking device seam
(``dispatch_chunks_async`` -> DeviceFuture), the bounded software
pipeline in ``BatchProject.run`` (bit-identical at every depth,
resume-safe under SIGKILL mid-pipeline), in-stripe multi-chip
round-robin on the virtual CPU mesh, and the per-lane occupancy
clocks of ``obs/pipeline.py``.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from licensee_tpu.kernels.batch import BatchClassifier, DeviceFuture
from licensee_tpu.projects.batch_project import BatchProject

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _license_bodies():
    from licensee_tpu.corpus.license import License

    return [
        re.sub(r"\[(\w+)\]", "example", License.find(k).content or "")
        for k in ("mit", "isc", "bsd-3-clause")
    ]


def write_corpus(tmp_path, n: int) -> list[str]:
    """``n`` files cycling real license bodies: copyright-only rows
    (host prefilter), verbatim bodies (exact prefilter), and unique
    noise-suffixed bodies (must cross the device) — every lane of the
    pipeline sees work."""
    bodies = _license_bodies()
    paths = []
    for i in range(n):
        p = tmp_path / f"LICENSE_{i:04d}"
        body = bodies[i % len(bodies)]
        if i % 7 == 0:
            text = f"Copyright (c) 2{i:03d} Example Author {i}\n"
        elif i % 5 == 0:
            text = body
        else:
            text = f"{body}\nzqnoise{i} zqword{i}\n"
        p.write_text(text, encoding="utf-8")
        paths.append(str(p))
    return paths


# -- the async device seam ----------------------------------------------


def test_device_future_contract():
    clf = BatchClassifier(pad_batch_to=4, mesh=None)
    bodies = _license_bodies()
    blobs = [f"{bodies[0]}\nzqf{i} zqg{i}\n".encode() for i in range(6)]
    prepared = clf.prepare_batch(blobs)
    assert len(prepared.todo) == 6
    fut = clf.dispatch_chunks_async(prepared)
    assert isinstance(fut, DeviceFuture)
    assert len(fut) == 2  # 6 todo rows at pad 4 -> 2 chunks
    outs = fut.result()
    assert fut.result() is outs  # idempotent await
    assert fut.ready()
    for _chunk, out in outs:
        for a in out:
            assert isinstance(a, np.ndarray)
    # finish_chunks accepts the future itself (awaiting IS the sync)
    clf.finish_chunks(prepared, fut, 90.0)
    for r in prepared.results:
        assert (r.key, r.matcher) == ("mit", "dice")


def test_staging_ring_recycles_partial_chunk_slots():
    clf = BatchClassifier(pad_batch_to=4, mesh=None, staging_depth=2)
    bodies = _license_bodies()
    # 5 device rows -> one full chunk + one partial (borrows a slot)
    blobs = [f"{bodies[1]}\nzqs{i} zqt{i}\n".encode() for i in range(5)]
    prepared = clf.prepare_batch(blobs)
    fut = clf.dispatch_chunks_async(prepared)
    fut.result()
    # the slot came back to the ring when the future resolved
    assert len(clf._staging._free.get(4, [])) == 1
    # and is reused, not reallocated, by the next partial dispatch
    slot = clf._staging._free[4][0]
    fut2 = clf.dispatch_chunks_async(clf.prepare_batch(blobs[:1]))
    fut2.result()
    assert clf._staging._free[4][0] is slot


def test_lanes_config_validation():
    with pytest.raises(ValueError, match="mesh"):
        BatchClassifier(mesh=(2, 1), lanes=2)
    with pytest.raises(ValueError, match="visible"):
        BatchClassifier(mesh=None, lanes=999)
    with pytest.raises(ValueError, match=">= 1"):
        BatchClassifier(mesh=None, lanes=0)  # 0 must refuse, not no-op


def test_two_lane_round_robin_agreement():
    """In-stripe multi-chip: whole chunks round-robin across 2 of the
    virtual CPU devices, and the verdicts (exact integer score pairs
    included) match the single-device classifier row for row."""
    import jax

    if len(jax.local_devices()) < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    bodies = _license_bodies()
    blobs = [
        f"{bodies[i % 3]}\nzqrr{i} zqss{i}\n".encode() for i in range(24)
    ]
    base = BatchClassifier(pad_batch_to=4, mesh=None)
    rr = BatchClassifier(pad_batch_to=4, mesh=None, lanes=2)
    assert rr.devices is not None and len(rr.devices) == 2

    def row(r):
        return (r.key, r.matcher, r.confidence, r.score_num, r.score_den)

    r_base = base.classify_blobs(blobs)
    r_rr = rr.classify_blobs(blobs)
    assert [row(r) for r in r_rr] == [row(r) for r in r_base]
    # 24 device rows at pad 4 = 6 chunks, alternating chips: the pad-4
    # shape compiled once PER DEVICE, the rest were steady dispatches
    stats = rr.dispatch_stats()
    assert stats["compiles"] == 2
    assert stats["dispatches"] == 4


# -- the software pipeline (batch run loop) -----------------------------


def test_pipeline_depth_sweep_bit_identical(tmp_path):
    paths = write_corpus(tmp_path, 48)
    outs = {}
    for depth in (1, 2, 3, 5):
        out = tmp_path / f"out_d{depth}.jsonl"
        project = BatchProject(
            paths, batch_size=8, mesh=None, pipeline_depth=depth
        )
        stats = project.run(str(out), resume=False)
        assert stats.total == len(paths)
        outs[depth] = out.read_bytes()
        # the occupancy snapshot rides the stats at every depth, and
        # the in-flight gauge always drains to zero by run end
        occ = stats.pipeline["occupancy"]
        assert set(occ) == {"featurize", "device", "writer"}
        assert stats.pipeline["inflight_chunks"] == 0
    assert len(set(outs.values())) == 1, "output must not depend on depth"


def test_pipeline_depth_validation():
    with pytest.raises(ValueError, match="pipeline_depth"):
        BatchProject(["x"], pipeline_depth=0)


def test_device_failure_mid_pipeline_propagates_cleanly(tmp_path):
    """A device failure with chunks in flight must surface as the
    run()'s exception — after the writer drained what it legally could
    — and a follow-up resume with a healthy classifier completes the
    manifest with zero duplicate/missing rows."""
    paths = write_corpus(tmp_path, 64)
    out = tmp_path / "out.jsonl"
    project = BatchProject(paths, batch_size=8, mesh=None, pipeline_depth=3)
    orig = project.classifier.dispatch_chunks_async
    calls = []

    def failing(prepared, pad_to=None):
        calls.append(len(prepared.todo))
        if len(calls) >= 3:  # chunks 1-2 in flight, then the device dies
            raise RuntimeError("injected device failure")
        return orig(prepared, pad_to=pad_to)

    project.classifier.dispatch_chunks_async = failing
    with pytest.raises(RuntimeError, match="injected device failure"):
        project.run(str(out), resume=False)
    project.classifier.dispatch_chunks_async = orig

    resumed = BatchProject(paths, batch_size=8, mesh=None, pipeline_depth=3)
    resumed.run(str(out), resume=True)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths

    ref = tmp_path / "ref.jsonl"
    BatchProject(paths, batch_size=8, mesh=None, pipeline_depth=1).run(
        str(ref), resume=False
    )
    assert ref.read_bytes() == out.read_bytes()


def test_sigkill_mid_pipeline_resume(tmp_path):
    """SIGKILL a real batch-detect worker mid-pipeline (depth 3, chunks
    in flight), resume, and require the final JSONL to carry every
    manifest row exactly once, in order, byte-identical to a clean
    synchronous run."""
    paths = write_corpus(tmp_path, 240)
    manifest = tmp_path / "manifest.txt"
    manifest.write_text("\n".join(paths) + "\n", encoding="utf-8")
    out = tmp_path / "out.jsonl"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "licensee_tpu.cli.main", "batch-detect",
            str(manifest), "--output", str(out), "--batch-size", "8",
            "--mesh", "none", "--pipeline-depth", "3", "--workers", "2",
        ],
        env=env,
        cwd=REPO_ROOT,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and proc.poll() is None:
            if out.exists() and out.read_bytes().count(b"\n") >= 24:
                break  # mid-run: rows written, chunks still in flight
            time.sleep(0.05)
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=60)

    resumed = BatchProject(paths, batch_size=8, mesh=None, pipeline_depth=3)
    resumed.run(str(out), resume=True)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths, (
        "resume must yield every manifest row exactly once, in order"
    )

    ref = tmp_path / "ref.jsonl"
    BatchProject(paths, batch_size=8, mesh=None, pipeline_depth=1).run(
        str(ref), resume=False
    )
    assert ref.read_bytes() == out.read_bytes()


# -- the lane clocks ----------------------------------------------------


def test_pipeline_lanes_occupancy_and_gauges():
    from licensee_tpu.obs import PipelineLanes, render_prometheus
    from licensee_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    lanes = PipelineLanes().register(reg)
    with lanes.lane("featurize"):
        time.sleep(0.02)
    # re-entrant across workers: the lane is busy while >= 1 is inside
    lanes.enter("device")
    lanes.enter("device")
    lanes.exit_("device")
    lanes.chunk_inflight(2)
    snap = lanes.occupancy()
    assert snap["busy_seconds"]["featurize"] >= 0.02
    assert 0.0 < snap["occupancy"]["featurize"] <= 1.0
    assert snap["inflight_chunks"] == 2
    lanes.exit_("device")
    lanes.chunk_inflight(-2)
    assert lanes.inflight() == 0
    text = render_prometheus(reg)
    for name in (
        "pipeline_featurize_busy",
        "pipeline_device_busy",
        "pipeline_writer_busy",
        "pipeline_inflight_chunks",
    ):
        assert name in text
    with pytest.raises(RuntimeError, match="exited more than entered"):
        lanes.exit_("writer")
