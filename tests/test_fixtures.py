"""Golden-file corpus test over every fixture project directory, asserting
detected key, matcher name, and content hash against fixtures.yml
(parity with spec/fixture_spec.rb)."""

import os

import pytest
import yaml

import licensee_tpu
from licensee_tpu.corpus.license import License
from licensee_tpu.projects import FSProject
from tests.conftest import FIXTURES_DIR, fixture_path

with open(fixture_path("fixtures.yml"), encoding="utf-8") as f:
    FIXTURE_LICENSES = yaml.safe_load(f)

# data-only fixture dirs (not project trees mirrored from spec/fixtures)
_NON_PROJECT = {"spdx-adversarial"}

FIXTURES = sorted(
    name
    for name in os.listdir(FIXTURES_DIR)
    if os.path.isdir(os.path.join(FIXTURES_DIR, name))
    and name not in _NON_PROJECT
)


def project_for(fixture):
    return FSProject(
        fixture_path(fixture), detect_packages=True, detect_readme=True
    )


def test_every_fixture_has_an_expectation():
    for fixture in FIXTURES:
        assert fixture in FIXTURE_LICENSES, fixture


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_license(fixture):
    expectations = FIXTURE_LICENSES.get(fixture) or {}
    project = project_for(fixture)
    expected = (
        License.find(expectations["key"]) if expectations.get("key") else None
    )
    assert project.license == expected


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_hash(fixture):
    expectations = FIXTURE_LICENSES.get(fixture) or {}
    project = project_for(fixture)
    license_file = project.license_file
    hash_ = license_file.content_hash if license_file else None
    assert hash_ == expectations.get("hash")


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_matcher(fixture):
    expectations = FIXTURE_LICENSES.get(fixture) or {}
    project = project_for(fixture)
    license_file = project.license_file
    matcher = license_file.matcher if license_file else None
    name = matcher.name if matcher else None
    assert name == expectations.get("matcher")
