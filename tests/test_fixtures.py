"""Golden-file corpus test over every fixture project directory, asserting
detected key, matcher name, and content hash against fixtures.yml
(parity with spec/fixture_spec.rb)."""

import pytest
import yaml

import licensee_tpu
from licensee_tpu.corpus.license import License
from licensee_tpu.projects import FSProject
from tests.conftest import fixture_path

with open(fixture_path("fixtures.yml"), encoding="utf-8") as f:
    FIXTURE_LICENSES = yaml.safe_load(f)

# the single fixture-enumeration rule (sorted project dirs, data-only
# dirs excluded) lives next to the regeneration tooling, so these tests
# and the fixtures.yml generator can never enumerate different sets
from licensee_tpu.corpus.vendoring import fixture_names

FIXTURES = fixture_names()


def project_for(fixture):
    return FSProject(
        fixture_path(fixture), detect_packages=True, detect_readme=True
    )


def test_every_fixture_has_an_expectation():
    for fixture in FIXTURES:
        assert fixture in FIXTURE_LICENSES, fixture


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_license(fixture):
    expectations = FIXTURE_LICENSES.get(fixture) or {}
    project = project_for(fixture)
    expected = (
        License.find(expectations["key"]) if expectations.get("key") else None
    )
    assert project.license == expected


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_hash(fixture):
    expectations = FIXTURE_LICENSES.get(fixture) or {}
    project = project_for(fixture)
    license_file = project.license_file
    hash_ = license_file.content_hash if license_file else None
    assert hash_ == expectations.get("hash")


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_matcher(fixture):
    expectations = FIXTURE_LICENSES.get(fixture) or {}
    project = project_for(fixture)
    license_file = project.license_file
    matcher = license_file.matcher if license_file else None
    name = matcher.name if matcher else None
    assert name == expectations.get("matcher")
