"""DiceXLA-as-a-registry-matcher parity: on every fixture license file the
batched kernel matcher must produce the same match and the same confidence
as the scalar reference-semantics Dice matcher (the north-star integration
point, `Matchers::DiceXLA`)."""

import os

import pytest

from licensee_tpu.matchers import Dice, DiceXLA
from licensee_tpu.projects import FSProject
from tests.conftest import FIXTURES_DIR, fixture_path

FIXTURES = sorted(
    name
    for name in os.listdir(FIXTURES_DIR)
    if os.path.isdir(os.path.join(FIXTURES_DIR, name))
)


def license_file_for(fixture):
    project = FSProject(
        fixture_path(fixture), detect_packages=False, detect_readme=False
    )
    return project.license_file


LICENSE_FILES = [
    (fixture, license_file_for(fixture))
    for fixture in FIXTURES
    if license_file_for(fixture) is not None
]


@pytest.mark.parametrize(
    "fixture,license_file", LICENSE_FILES, ids=[f for f, _ in LICENSE_FILES]
)
def test_dice_xla_matches_dice(fixture, license_file):
    dice = Dice(license_file)
    xla = DiceXLA(license_file)
    want = dice.match.key if dice.match else None
    got = xla.match.key if xla.match else None
    assert got == want
    # confidence is computed in float64 from the exact same integer
    # (overlap, denominator) pair the scalar path derives — bit-identical
    assert xla.confidence == dice.confidence


def test_dice_xla_copyright_only_file_is_not_short_circuited():
    """As a chain matcher, DiceXLA must behave like Dice on a pure
    copyright notice (no match) — the Copyright matcher ahead of it in the
    chain owns that answer."""
    from licensee_tpu.project_files.license_file import LicenseFile

    file = LicenseFile("Copyright (c) 2024 Ben Balter", "LICENSE")
    assert Dice(file).match is None
    assert DiceXLA(file).match is None
    assert DiceXLA(file).confidence == 0


def test_dice_xla_name():
    from licensee_tpu.project_files.license_file import LicenseFile

    file = LicenseFile("MIT License", "LICENSE")
    assert DiceXLA(file).name == "dicexla"
