"""The striped scale-out runner (parallel/stripes.py + the batch-detect
--stripes CLI surface).

The supervision/merge machinery is exercised over STUB workers (the
fleet test suite's pattern): a protocol-faithful script that honors the
stripe rank args, the per-shard resume invariant, and the stats sidecar
— so SIGKILL/restart/merge semantics run in milliseconds, no JAX boot
per child.  The real-children end-to-end path is covered by
`batch-detect --selftest` (script/cibuild) and bench_stripes.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from licensee_tpu.fleet.supervisor import BackoffPolicy
from licensee_tpu.parallel.stripes import (
    StripeError,
    StripeRunner,
    auto_stripe_count,
    count_manifest_entries,
    merge_stats,
    parse_stripes_arg,
    stripe_argv,
)

# every test in this module runs under the lock-order sanitizer
# (tests/lock_sanitizer.py): the runner's supervision loop shares the
# BackoffPolicy/terminate machinery with the fleet supervisor, and any
# lock its callbacks take must keep a consistent global order
pytestmark = pytest.mark.usefixtures("lock_order_sanitizer")

# ---------------------------------------------------------------------------
# the stub stripe worker: same rank math, same shard naming, same
# resume-point semantics as a real batch-detect child — plus scripted
# faults (SIGKILL itself mid-stripe, leave a torn tail, write a short
# shard) driven by marker files in the output directory.

STUB = textwrap.dedent(
    """
    import json, os, signal, sys, time

    manifest, output, index, count = (
        sys.argv[1], sys.argv[2], int(sys.argv[3]), int(sys.argv[4])
    )
    slow_s = float(os.environ.get("STUB_SLOW_S", "0"))
    paths = [l.strip() for l in open(manifest) if l.strip()]
    base, rem = divmod(len(paths), count)
    lo = index * base + min(index, rem)
    hi = lo + base + (1 if index < rem else 0)
    mine = paths[lo:hi]
    shard = (
        output if count <= 1
        else f"{output}.shard-{index:05d}-of-{count:05d}"
    )
    # resume: newline-terminated rows count, torn tail truncated (the
    # BatchProject._resume_point contract)
    done, good = 0, 0
    if os.path.exists(shard):
        with open(shard, "rb") as f:
            for line in f:
                if not line.endswith(b"\\n"):
                    break
                done += 1
                good += len(line)
        with open(shard, "r+b") as f:
            f.truncate(good)
    crash_marker = f"{shard}.crash-once"
    short_marker = f"{shard}.write-short"
    stop = len(mine) - (1 if os.path.exists(short_marker) else 0)
    with open(shard, "a", encoding="utf-8") as f:
        for i in range(done, stop):
            f.write(json.dumps({"path": mine[i], "row": lo + i}) + "\\n")
            f.flush()
            if slow_s:
                time.sleep(slow_s)
            if os.path.exists(crash_marker) and i >= len(mine) // 2:
                os.remove(crash_marker)
                f.write('{"path": "torn-by-SIGKILL')  # no newline
                f.flush()
                os.kill(os.getpid(), signal.SIGKILL)
    with open(f"{shard}.stats.json.tmp", "w", encoding="utf-8") as f:
        json.dump(
            {"total": stop - done, "stage_seconds": {"write": 0.001}}, f
        )
    os.replace(f"{shard}.stats.json.tmp", f"{shard}.stats.json")
    """
)


@pytest.fixture()
def stub_world(tmp_path):
    """A manifest + a stub-worker argv_for, ready for StripeRunner."""
    stub = tmp_path / "stub_worker.py"
    stub.write_text(STUB)
    paths = [f"/nope/LICENSE_{i}" for i in range(23)]
    manifest = tmp_path / "manifest.txt"
    manifest.write_text("\n".join(paths) + "\n")
    output = str(tmp_path / "out.jsonl")

    def argv_for(index, count, resume=True):
        return [
            sys.executable, str(stub), str(manifest), output,
            str(index), str(count),
        ]

    def make_runner(stripes, **kwargs):
        kwargs.setdefault("argv_for", argv_for)
        kwargs.setdefault("env_for", lambda i, chips: dict(os.environ))
        kwargs.setdefault(
            "backoff", BackoffPolicy(base_s=0.05, max_s=0.2)
        )
        kwargs.setdefault("poll_interval_s", 0.03)
        kwargs.setdefault("stall_timeout_s", 0)  # probes off for stubs
        return StripeRunner(str(manifest), output, stripes, **kwargs)

    return {
        "paths": paths,
        "manifest": str(manifest),
        "output": output,
        "make_runner": make_runner,
    }


# -- arg validation + auto sizing --


def test_parse_stripes_arg_validation():
    assert parse_stripes_arg("3") == 3
    for bad in ("0", "-2", "two", "1.5"):
        with pytest.raises(ValueError):
            parse_stripes_arg(bad)
    assert parse_stripes_arg("auto") >= 1


def test_auto_stripe_count_sizing():
    # every stripe needs >= 2 cores (produce workers + serial loop)
    assert auto_stripe_count(cores=1) == 1
    assert auto_stripe_count(cores=2) == 1
    assert auto_stripe_count(cores=8) == 4
    assert auto_stripe_count(cores=64) == 16  # the cap
    # the bench model's north-star floor applies when cores allow
    model = {"striped_processes_needed_10M_60s": 3}
    assert auto_stripe_count(cores=8, scaling_model=model) == 4
    assert auto_stripe_count(cores=4, scaling_model=model) == 2
    # a future model demanding more than the cap raises it (cores allow)
    big = {"striped_processes_needed_10M_60s": 24}
    assert auto_stripe_count(cores=64, scaling_model=big) == 24


def test_runner_rejects_bad_stripe_counts(stub_world):
    with pytest.raises(ValueError):
        stub_world["make_runner"](0)
    with pytest.raises(ValueError):
        stub_world["make_runner"](-1)
    # more stripes than manifest entries: an empty shard can never
    # satisfy the merge row-count check — refuse up front
    with pytest.raises(ValueError, match="more stripes"):
        stub_world["make_runner"](len(stub_world["paths"]) + 1)


def test_auto_clamp_shrinks_to_manifest_size(stub_world):
    """`--stripes auto` sizes from the HOST; a small manifest clamps
    the count instead of erroring about a number the user never chose
    (explicit --stripes N still refuses, tested above)."""
    runner = stub_world["make_runner"](
        len(stub_world["paths"]) + 10, auto_clamp=True
    )
    assert runner.stripes == len(stub_world["paths"])
    summary = runner.run()
    assert summary["rows_written"] == len(stub_world["paths"])


def test_runner_rejects_bad_knobs(stub_world):
    with pytest.raises(ValueError):
        stub_world["make_runner"](2, chips_per_stripe=0)
    with pytest.raises(ValueError):
        stub_world["make_runner"](2, max_restarts=-1)


def test_count_manifest_entries_skips_blanks(tmp_path):
    m = tmp_path / "m.txt"
    m.write_text("/a\n\n/b\n   \n/c\n")
    assert count_manifest_entries(str(m)) == 3


# -- the dict-env chip partition (PR-2's regression contract: a dry run
# over a caller dict must never consult or mutate os.environ) --


def test_chip_partition_dict_env_never_touches_os_environ(tmp_path):
    manifest = tmp_path / "m.txt"
    manifest.write_text("\n".join(f"/nope/{i}" for i in range(8)) + "\n")
    before = dict(os.environ)
    runner = StripeRunner(
        str(manifest), str(tmp_path / "o.jsonl"), 3,
        chips_per_stripe=2,
        argv_for=lambda i, n, resume=True: ["true"],
        base_env={"PATH": "/usr/bin"},
    )
    assert dict(os.environ) == before  # nothing leaked into THIS process
    specs = [
        h.env["LICENSEE_TPU_VISIBLE_CHIPS"] for h in runner.handles
    ]
    assert specs == ["0,1", "2,3", "4,5"]  # disjoint contiguous ranges
    for handle, spec in zip(runner.handles, specs):
        # the runtime visibility vars derive through apply_visible_chips
        # over the CHILD's dict
        assert handle.env["TPU_VISIBLE_DEVICES"] == spec
        assert (
            f"--xla_force_host_platform_device_count=2"
            in handle.env["XLA_FLAGS"]
        )


def test_stripe_argv_resume_contract(tmp_path):
    argv = stripe_argv("m.txt", "o.jsonl", 1, 4, ("--mode", "auto"),
                       resume=False)
    assert "--no-resume" in argv
    assert ["--stripe-index", "1", "--stripe-count", "4"] == argv[
        argv.index("--stripe-index"): argv.index("--stripe-count") + 2
    ]
    assert argv[-2:] == ["--mode", "auto"]
    # a RESTART must always resume from the shard's completed prefix,
    # even when the run started --no-resume
    assert "--no-resume" not in stripe_argv(
        "m.txt", "o.jsonl", 1, 4, resume=True
    )


# -- supervision: SIGKILL mid-run, resume, merge invariants --


def test_sigkill_mid_stripe_resumes_and_merges_exactly(stub_world):
    """The satellite contract: a worker SIGKILLed mid-chunk (torn tail
    included) restarts from its OWN shard's resume point; the merged
    output has every manifest row exactly once, in manifest order."""
    output = stub_world["output"]
    # arm stripe 0's one-shot crash: it kills itself (SIGKILL, torn
    # tail) halfway through its stripe on the first incarnation
    shard0 = f"{output}.shard-00000-of-00002"
    open(f"{shard0}.crash-once", "w").close()
    runner = stub_world["make_runner"](2)
    summary = runner.run()
    assert summary["rows_written"] == len(stub_world["paths"])
    assert runner.handles[0].restarts == 1
    assert runner.handles[0].exit_codes[0] == -signal.SIGKILL
    assert runner.handles[1].restarts == 0
    rows = [
        json.loads(line)
        for line in open(output, encoding="utf-8")
    ]
    # zero duplicates, zero gaps, manifest order — the resumed stripe
    # re-scored only its own unfinished suffix
    assert [r["path"] for r in rows] == stub_world["paths"]
    assert [r["row"] for r in rows] == list(range(len(rows)))
    # per-stripe intermediates are gone after the merge
    assert not os.path.exists(shard0)
    assert not os.path.exists(f"{shard0}.stats.json")
    # merged stats count only rows CLASSIFIED by the final incarnations
    # (a resume's stats cover new rows only, like BatchProject's): the
    # crash at row 6 leaves 7 rows complete, so stripe 0's resume
    # re-scores 5 and stripe 1 scored its 11 — never the other
    # stripe's rows
    assert summary["stats"]["total"] == len(stub_world["paths"]) - 7


def test_sustained_progress_earns_restart_budget_back(stub_world):
    """Fleet-supervisor parity: a stripe that keeps growing its shard
    past stable_after_s resets its BACKOFF counter, so isolated
    transient crashes over a long run never exhaust a lifetime budget;
    the lifetime count still reports via total_restarts."""
    os.environ["STUB_SLOW_S"] = "0.02"
    try:
        output = stub_world["output"]
        open(f"{output}.shard-00000-of-00002.crash-once", "w").close()
        runner = stub_world["make_runner"](
            2,
            backoff=BackoffPolicy(
                base_s=0.02, max_s=0.1, stable_after_s=0.05
            ),
        )
        summary = runner.run()
        assert summary["rows_written"] == len(stub_world["paths"])
        handle = runner.handles[0]
        assert handle.total_restarts == 1
        assert handle.restarts == 0  # earned back by shard growth
        assert summary["per_stripe"][0]["restarts"] == 1
    finally:
        os.environ.pop("STUB_SLOW_S", None)


def test_spawn_failure_drains_other_stripes(stub_world):
    """A Popen failure must not orphan already-spawned siblings."""
    os.environ["STUB_SLOW_S"] = "0.05"
    try:
        good = stub_world["make_runner"](2).handles[0].argv_first

        def argv_for(index, count, resume=True):
            # stripe 0 spawns fine (the real stub argv); stripe 1's
            # spawn raises FileNotFoundError
            if index == 0:
                return good
            return ["/nonexistent-interpreter-for-stripe-test"]

        runner = stub_world["make_runner"](2, argv_for=argv_for)
        with pytest.raises(StripeError, match="spawn failed"):
            runner.run()
        # stripe 0 was spawned first and must be reaped by the drain
        proc = runner.handles[0].proc
        assert proc is None or proc.poll() is not None
    finally:
        os.environ.pop("STUB_SLOW_S", None)


def test_crash_loop_exhausts_restart_budget(stub_world):
    def always_dies(index, count, resume=True):
        return [sys.executable, "-c", "import sys; sys.exit(3)"]

    runner = stub_world["make_runner"](
        2, argv_for=always_dies, max_restarts=2
    )
    with pytest.raises(StripeError, match="giving up"):
        runner.run()
    # every child is reaped; nothing keeps running after the abort
    assert all(h.proc is None or h.proc.poll() is not None
               for h in runner.handles)
    # a failure with ZERO shard growth is deterministic: the fast-fail
    # fires after two attempts instead of burning the whole backoff
    # budget (max_restarts=2 would have allowed a third)
    assert len(runner.handles[0].exit_codes) <= 2


def test_short_shard_refuses_merge(stub_world):
    output = stub_world["output"]
    open(f"{output}.shard-00001-of-00002.write-short", "w").close()
    runner = stub_world["make_runner"](2)
    with pytest.raises(StripeError, match="complete rows"):
        runner.run()


def test_request_stop_drains_resume_safe(stub_world):
    os.environ["STUB_SLOW_S"] = "0.05"
    try:
        runner = stub_world["make_runner"](2)
        errs: list = []

        def run():
            try:
                runner.run()
            except StripeError as exc:
                errs.append(exc)

        t = threading.Thread(target=run)
        t.start()
        # let the stubs write a few rows, then drain
        deadline = time.perf_counter() + 5.0
        shard0 = f"{stub_world['output']}.shard-00000-of-00002"
        while time.perf_counter() < deadline:
            if os.path.exists(shard0) and os.path.getsize(shard0) > 0:
                break
            time.sleep(0.01)
        runner.request_stop()
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert errs and "resume-safe" in str(errs[0])
        # shards survive a drain (they are the resume state)
        assert os.path.exists(shard0)
    finally:
        os.environ.pop("STUB_SLOW_S", None)


def test_already_complete_skips_spawning(stub_world):
    runner = stub_world["make_runner"](2)
    summary = runner.run()
    assert summary["already_complete"] is False
    # second run over the SAME complete output: nothing respawns (the
    # argv here would fail loudly if it ran)
    runner2 = stub_world["make_runner"](
        2,
        argv_for=lambda i, n, resume=True: [
            sys.executable, "-c", "import sys; sys.exit(9)"
        ],
    )
    summary2 = runner2.run()
    assert summary2["already_complete"] is True
    assert summary2["rows_written"] == len(stub_world["paths"])
    # the merge persisted stats beside the output, so a no-op rerun
    # still surfaces them (the --stats-file contract on reruns)
    assert summary2["stats"] is not None
    assert summary2["stats"]["total"] == len(stub_world["paths"])


def test_cleanup_sweeps_stale_shards_from_other_stripe_counts(stub_world):
    """An aborted earlier run at a different stripe count leaves shards
    this run's handles don't name; a successful merge must sweep them
    so a future run at that count can never resume months-stale rows."""
    output = stub_world["output"]
    stale = f"{output}.shard-00000-of-00004"
    open(stale, "w").write('{"path": "/stale", "row": 0}\n')
    open(f"{stale}.meta.json", "w").write("{}")
    summary = stub_world["make_runner"](2).run()
    assert summary["rows_written"] == len(stub_world["paths"])
    assert not os.path.exists(stale)
    assert not os.path.exists(f"{stale}.meta.json")


def test_merge_stats_sums_counters_routes_and_stages():
    merged = merge_stats([
        {"total": 5, "dice_matched": 2, "routed": {"license": 5},
         "stage_seconds": {"read": 0.5, "elapsed": 2.0}},
        {"total": 7, "dice_matched": 1, "routed": {"license": 6,
                                                   "none": 1},
         "stage_seconds": {"read": 0.25, "elapsed": 1.0}},
    ])
    assert merged["total"] == 12
    assert merged["dice_matched"] == 3
    assert merged["routed"] == {"license": 11, "none": 1}
    assert merged["stage_seconds"]["read"] == 0.75
    assert merged["stage_seconds"]["elapsed"] == 3.0


# -- the CLI surface (error paths run without any backend import) --


def _main(argv, capsys):
    from licensee_tpu.cli.main import main

    rc = main(argv)
    return rc, capsys.readouterr()


def test_cli_stripes_needs_output(tmp_path, capsys):
    m = tmp_path / "m.txt"
    m.write_text("/nope\n")
    rc, out = _main(
        ["batch-detect", str(m), "--stripes", "2"], capsys
    )
    assert rc == 1
    assert "--stripes needs --output" in out.err


def test_cli_stripes_validation(tmp_path, capsys):
    m = tmp_path / "m.txt"
    m.write_text("/nope\n")
    for bad in ("0", "-1", "x"):
        rc, out = _main(
            ["batch-detect", str(m), "--stripes", bad,
             "--output", str(tmp_path / "o.jsonl")],
            capsys,
        )
        assert rc == 1, bad
        assert "--stripes" in out.err
    # more stripes than manifest entries surfaces as the runner's error
    rc, out = _main(
        ["batch-detect", str(m), "--stripes", "5",
         "--output", str(tmp_path / "o.jsonl")],
        capsys,
    )
    assert rc == 1
    assert "more stripes" in out.err


def test_cli_stripes_refuses_multihost_env(tmp_path, capsys, monkeypatch):
    m = tmp_path / "m.txt"
    m.write_text("/nope\n")
    monkeypatch.setenv("LICENSEE_TPU_COORDINATOR", "localhost:9999")
    rc, out = _main(
        ["batch-detect", str(m), "--stripes", "1",
         "--output", str(tmp_path / "o.jsonl")],
        capsys,
    )
    assert rc == 1
    assert "multi-host" in out.err


def test_cli_stripe_worker_flags_validated(tmp_path, capsys):
    m = tmp_path / "m.txt"
    m.write_text("/nope\n")
    rc, out = _main(
        ["batch-detect", str(m), "--stripe-index", "0"], capsys
    )
    assert rc == 1
    assert "--stripe-count" in out.err
    rc, out = _main(
        ["batch-detect", str(m), "--stripe-index", "2",
         "--stripe-count", "2",
         "--output", str(tmp_path / "o.jsonl")],
        capsys,
    )
    assert rc == 1
    assert "out of range" in out.err


def test_cli_stripes_refuses_config_mismatch_resume(tmp_path, capsys):
    """A striped rerun over an existing output whose sidecar records a
    different row-shaping config must refuse (the single-process
    ResumeConfigError contract) — even when the output is complete and
    no worker would otherwise run.  The preflight runs the REAL
    _check_resume_config, so the corpus fingerprint is covered too."""
    from licensee_tpu.projects.batch_project import BatchProject

    m = tmp_path / "m.txt"
    m.write_text("/nope\n")
    output = tmp_path / "o.jsonl"
    output.write_text('{"path": "/nope", "key": null}\n')
    meta = tmp_path / "o.jsonl.meta.json"
    config = BatchProject([], mesh=None)._run_config()
    meta.write_text(json.dumps(config))

    # changed --mode refuses
    rc, out = _main(
        ["batch-detect", str(m), "--stripes", "1",
         "--output", str(output), "--mode", "readme"],
        capsys,
    )
    assert rc == 1
    assert "configuration differs" in out.err
    assert "mode" in out.err

    # a changed CORPUS (same keys/vocab, different template content —
    # only the fingerprint knows) refuses too
    bad = dict(config)
    bad["corpus"] = dict(config["corpus"], content_sha1="0" * 40)
    meta.write_text(json.dumps(bad))
    rc, out = _main(
        ["batch-detect", str(m), "--stripes", "1",
         "--output", str(output)],
        capsys,
    )
    assert rc == 1
    assert "corpus" in out.err

    # matching config passes preflight (and no-ops: output complete)
    meta.write_text(json.dumps(config))
    rc, out = _main(
        ["batch-detect", str(m), "--stripes", "1",
         "--output", str(output)],
        capsys,
    )
    assert rc == 0
    assert "already complete" in out.err


def test_cli_batch_detect_requires_manifest_or_selftest(capsys):
    rc, out = _main(["batch-detect"], capsys)
    assert rc == 1
    assert "--selftest" in out.err


def test_cli_stripes_runs_stub_end_to_end(tmp_path, capsys, monkeypatch):
    """The full CLI path (`batch-detect --stripes 2 --output ...`) over
    stub children: monkeypatch stripe_argv so the spawned argv is the
    stub, keeping the runner/merge/summary plumbing real."""
    stub = tmp_path / "stub_worker.py"
    stub.write_text(STUB)
    paths = [f"/nope/L_{i}" for i in range(9)]
    manifest = tmp_path / "m.txt"
    manifest.write_text("\n".join(paths) + "\n")
    output = tmp_path / "out.jsonl"

    import licensee_tpu.parallel.stripes as stripes_mod

    def stub_argv(man, out, index, count, forward=(), resume=True):
        return [
            sys.executable, str(stub), man, out, str(index), str(count),
        ]

    monkeypatch.setattr(stripes_mod, "stripe_argv", stub_argv)
    stats_file = tmp_path / "merged.stats.json"
    rc, out = _main(
        ["batch-detect", str(manifest), "--stripes", "2",
         "--output", str(output), "--stats",
         "--stats-file", str(stats_file)],
        capsys,
    )
    assert rc == 0
    rows = [json.loads(l) for l in open(output, encoding="utf-8")]
    assert [r["path"] for r in rows] == paths
    assert "stripes: done: 9 rows" in out.err
    # an operator-passed --stats-file gets the MERGED stats (the
    # per-shard dumps are the runner's internal inputs)
    merged = json.loads(stats_file.read_text())
    assert merged["total"] == len(paths)


def test_chips_per_stripe_lanes_forward_respects_explicit_mesh(
    tmp_path, capsys, monkeypatch
):
    """`--chips-per-stripe K` auto-forwards `--device-lanes auto` to
    each worker — but lanes are mutually exclusive with an explicit
    numeric `--mesh` (BatchClassifier raises), so an operator who
    pinned per-dispatch sharding must NOT get lanes forwarded on top
    of it (every stripe would die at startup)."""
    stub = tmp_path / "stub_worker.py"
    stub.write_text(STUB)
    manifest = tmp_path / "m.txt"
    manifest.write_text("\n".join(f"/nope/L_{i}" for i in range(6)) + "\n")

    import licensee_tpu.parallel.stripes as stripes_mod

    captured: list[list[str]] = []

    def stub_argv(man, out, index, count, forward=(), resume=True):
        captured.append(list(forward))
        return [
            sys.executable, str(stub), man, out, str(index), str(count),
        ]

    monkeypatch.setattr(stripes_mod, "stripe_argv", stub_argv)

    for case, (extra, want_lanes) in enumerate((
        ([], True),                      # default: lanes auto-forward
        (["--mesh", "auto"], True),      # "auto" is overridden by lanes
        (["--mesh", "2,1"], False),      # explicit shard: no lanes
    )):
        captured.clear()
        rc, _out = _main(
            ["batch-detect", str(manifest), "--stripes", "2",
             "--chips-per-stripe", "2",
             "--output", str(tmp_path / f"out-{case}.jsonl"),
             "--no-resume", *extra],
            capsys,
        )
        assert rc == 0
        assert captured, "stripe_argv never called"
        for fwd in captured:
            has_lanes = "--device-lanes" in fwd
            assert has_lanes == want_lanes, (extra, fwd)
            if want_lanes:
                assert fwd[fwd.index("--device-lanes") + 1] == "auto"
