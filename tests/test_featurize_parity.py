"""Golden featurizer-parity suite: the fused single-pass native
featurizer must be BIT-IDENTICAL to the pure-Python pipeline — no
semantic drift is allowed in exchange for speed.

Covers the full vendored corpus plus adversarial blobs (HTML, CRLF,
unicode dashes/quotes, non-ASCII titles, empty/huge lines) across every
surface a classification can depend on: normalized text, content hash,
wordset bits, |wordset|, normalized length, prefilter flags.
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

from licensee_tpu.native import selftest


@pytest.fixture(scope="module")
def clf():
    from licensee_tpu.kernels.batch import BatchClassifier

    c = BatchClassifier(mesh=None, device=False)
    if c._nat is None:
        pytest.skip("native pipeline unavailable")
    return c


def test_full_corpus_and_adversarial_parity(clf):
    stats = selftest.run_parity(clf)
    assert stats["blobs"] >= 60  # 47 vendored templates + adversarial set
    assert stats["text_checked"] == stats["blobs"]


def test_adversarial_blob_list_covers_required_shapes():
    blobs = selftest.adversarial_blobs()
    joined = b"|".join(blobs)
    assert b"" in blobs  # empty
    assert b"\r\n" in joined  # CRLF
    assert "–".encode() in joined  # unicode dash
    assert "“".encode() in joined  # unicode quote
    assert "MITライセンス".encode() in joined  # non-ASCII title
    assert b"<html>" in joined  # HTML-shaped content
    assert any(len(b) > 65536 for b in blobs)  # huge line
    assert b"\xef\xbb\xbf" in joined  # BOM


def test_normalized_text_and_hash_bit_identical(clf):
    """Spot parity on the exact surfaces the golden corpus pins: the
    native stage1 -> lower -> stage2 text equals content_normalized,
    and sha1 of it equals content_hash."""
    from licensee_tpu.kernels.batch import NormalizedBlob
    from licensee_tpu.rubytext import ruby_strip

    for raw in (
        b"MIT License\n\ncopyright (c) 2000 X\n\npermission granted & "
        b"http://x.test \xe2\x80\x94 'quoted' sub-license per cent",
        "the licence – “MIT”:\n\n- a\n\n- b\n".encode(),
    ):
        blob = NormalizedBlob(raw)
        stripped = ruby_strip(blob.content)
        s1, _ = clf._nat.stage1(stripped)
        s2 = clf._nat.stage2(s1.lower())
        assert s2 == blob.content_normalized()
        assert (
            hashlib.sha1(s2.encode()).hexdigest() == blob.content_hash
        )


def test_batch_rows_mapping_zero_copy(clf):
    """featurize_batch with a sparse row map writes each blob's bits into
    the caller-owned row of the FULL matrix — identical to the dense
    call, with untouched rows left alone."""
    contents = [
        b"permission granted to deal in the software " * 20,
        b"redistribution and use in source and binary forms " * 20,
    ]
    W = clf.corpus.n_lanes
    dense_bits = np.zeros((2, W), dtype=np.uint32)
    meta = np.zeros((2, 3), dtype=np.int32)
    hashes = np.zeros((2, 16), dtype=np.uint8)
    st = clf._nat.featurize_batch(
        clf._nat_vocab, contents, dense_bits, meta, hashes
    )
    assert (st == 0).all()

    big = np.full((5, W), 7, dtype=np.uint32)
    meta2 = np.zeros((2, 3), dtype=np.int32)
    hashes2 = np.zeros((2, 16), dtype=np.uint8)
    st2 = clf._nat.featurize_batch(
        clf._nat_vocab,
        contents,
        big,
        meta2,
        hashes2,
        rows=np.array([3, 1], dtype=np.int64),
    )
    assert (st2 == 0).all()
    assert np.array_equal(big[3], dense_bits[0])
    assert np.array_equal(big[1], dense_bits[1])
    assert (big[0] == 7).all() and (big[2] == 7).all() and (big[4] == 7).all()
    assert np.array_equal(meta2, meta)
    assert np.array_equal(hashes2, hashes)
    # out-of-range rows are rejected, not written
    with pytest.raises(ValueError):
        clf._nat.featurize_batch(
            clf._nat_vocab, contents, big, meta2, hashes2,
            rows=np.array([3, 5], dtype=np.int64),
        )


def test_prepare_batch_sparse_subset_matches_dense(clf):
    """prepare_batch with preset rows (the dedupe shape) routes the
    native-eligible remainder through the row map; features must equal
    the no-preset run row for row."""
    from licensee_tpu.kernels.batch import BlobResult

    contents = [
        b"alpha beta gamma delta " * 40,
        b"the quick brown fox " * 40,
        b"permission is hereby granted " * 40,
        b"redistribution and use " * 40,
    ]
    dense = clf.prepare_batch(list(contents))
    preset = [None, BlobResult("mit", "exact", 100.0), None, None]
    sparse = clf.prepare_batch(list(contents), preset=preset)
    for i in (0, 2, 3):
        assert np.array_equal(sparse.bits[i], dense.bits[i])
        assert sparse.n_words[i] == dense.n_words[i]
        assert sparse.lengths[i] == dense.lengths[i]
    assert sparse.results[1] is preset[1]
    assert sparse.todo == [0, 2, 3]


# -- the round-2 title-strip prefix gate (native/pipeline.py) --
#
# The native pipeline fronts the corpus-wide PCRE2 title union with a
# derived table of literal lowercase prefixes: a head matching none of
# them provably cannot match the union, so PCRE2 is skipped.  The gate
# is only sound if (a) every real title still reaches the regex and
# (b) gated heads normalize bit-identically to the pure-Python path.


def _title_parity(clf, raw: bytes):
    from licensee_tpu.kernels.batch import NormalizedBlob
    from licensee_tpu.rubytext import ruby_strip

    blob = NormalizedBlob(raw)
    stripped = ruby_strip(blob.content)
    s1, _ = clf._nat.stage1(stripped)
    s2 = clf._nat.stage2(s1.lower())
    assert s2 == blob.content_normalized(), raw[:80]
    assert (
        hashlib.sha1(s2.encode()).hexdigest() == blob.content_hash
    ), raw[:80]


def test_title_prefix_gate_covers_every_real_title():
    """Derivation soundness, checked against the corpus itself: every
    vendored license title and unversioned name (the strings the union
    is BUILT from) must start with one of the derived prefixes — a
    miss here means the gate would skip a genuine title head."""
    from licensee_tpu.corpus.license import License
    from licensee_tpu.native.pipeline import _derive_title_prefixes

    prefixes = _derive_title_prefixes()
    assert prefixes, "title-prefix derivation went None (gate disabled)"
    assert all(p == p.lower() for p in prefixes)
    for lic in License.all(hidden=True, pseudo=False):
        for head in (lic.title, lic.name_without_version):
            low = head.lower()
            assert any(low.startswith(p) for p in prefixes), (
                lic.key, head, sorted(prefixes),
            )


def test_adversarial_title_strip_goldens(clf):
    """Bit-identical parity on heads engineered against the prefix
    gate: exact titles, gate-hit-but-regex-miss near-titles,
    one-char-off near-prefixes (gate miss), 'the '/paren/indent
    wrappers, and titles buried mid-document (the \\A anchor)."""
    from licensee_tpu.native.pipeline import _derive_title_prefixes

    body = b"\n\npermission is hereby granted to deal in the software.\n"
    heads = [
        b"MIT License",
        b"The MIT License (MIT)",
        b"(The MIT License)",
        b"   Apache License\nVersion 2.0, January 2004",
        b"GNU GENERAL PUBLIC LICENSE\nVersion 3, 29 June 2007",
        b"BSD 3-Clause License",
        b"the mit license",  # lowercase 'the' wrapper
        b"MIT LICENSE",  # all-caps through the caseless union
        b"MITNOTQUITE a license",  # gate hit, regex miss
        b"Apache Licensing Department",  # gate hit, regex miss
        b"MI License",  # one char short of every mit prefix
        b"XYZ Public License",  # gate miss entirely
        b"preamble first\nMIT License",  # title not at \A: no strip
        b"Copyright (c) 2026\nMIT License",
    ]
    for head in heads:
        _title_parity(clf, head + body)
    # and the derived table itself, adversarially: each prefix as a
    # bare head (gate hit, usually regex miss), plus one-char bumps
    # and truncations walking the gate's miss edge
    prefixes = _derive_title_prefixes() or []
    assert prefixes
    for p in sorted(prefixes):
        enc = p.encode("utf-8", "ignore") or b"x"
        _title_parity(clf, enc + b" license" + body)
        _title_parity(clf, enc[:-1] + b"~ license" + body)  # last bumped
        _title_parity(clf, enc[:-1] + body)  # truncated: gate-edge miss
