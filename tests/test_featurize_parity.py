"""Golden featurizer-parity suite: the fused single-pass native
featurizer must be BIT-IDENTICAL to the pure-Python pipeline — no
semantic drift is allowed in exchange for speed.

Covers the full vendored corpus plus adversarial blobs (HTML, CRLF,
unicode dashes/quotes, non-ASCII titles, empty/huge lines) across every
surface a classification can depend on: normalized text, content hash,
wordset bits, |wordset|, normalized length, prefilter flags.
"""

from __future__ import annotations

import hashlib

import numpy as np
import pytest

from licensee_tpu.native import selftest


@pytest.fixture(scope="module")
def clf():
    from licensee_tpu.kernels.batch import BatchClassifier

    c = BatchClassifier(mesh=None, device=False)
    if c._nat is None:
        pytest.skip("native pipeline unavailable")
    return c


def test_full_corpus_and_adversarial_parity(clf):
    stats = selftest.run_parity(clf)
    assert stats["blobs"] >= 60  # 47 vendored templates + adversarial set
    assert stats["text_checked"] == stats["blobs"]


def test_adversarial_blob_list_covers_required_shapes():
    blobs = selftest.adversarial_blobs()
    joined = b"|".join(blobs)
    assert b"" in blobs  # empty
    assert b"\r\n" in joined  # CRLF
    assert "–".encode() in joined  # unicode dash
    assert "“".encode() in joined  # unicode quote
    assert "MITライセンス".encode() in joined  # non-ASCII title
    assert b"<html>" in joined  # HTML-shaped content
    assert any(len(b) > 65536 for b in blobs)  # huge line
    assert b"\xef\xbb\xbf" in joined  # BOM


def test_normalized_text_and_hash_bit_identical(clf):
    """Spot parity on the exact surfaces the golden corpus pins: the
    native stage1 -> lower -> stage2 text equals content_normalized,
    and sha1 of it equals content_hash."""
    from licensee_tpu.kernels.batch import NormalizedBlob
    from licensee_tpu.rubytext import ruby_strip

    for raw in (
        b"MIT License\n\ncopyright (c) 2000 X\n\npermission granted & "
        b"http://x.test \xe2\x80\x94 'quoted' sub-license per cent",
        "the licence – “MIT”:\n\n- a\n\n- b\n".encode(),
    ):
        blob = NormalizedBlob(raw)
        stripped = ruby_strip(blob.content)
        s1, _ = clf._nat.stage1(stripped)
        s2 = clf._nat.stage2(s1.lower())
        assert s2 == blob.content_normalized()
        assert (
            hashlib.sha1(s2.encode()).hexdigest() == blob.content_hash
        )


def test_batch_rows_mapping_zero_copy(clf):
    """featurize_batch with a sparse row map writes each blob's bits into
    the caller-owned row of the FULL matrix — identical to the dense
    call, with untouched rows left alone."""
    contents = [
        b"permission granted to deal in the software " * 20,
        b"redistribution and use in source and binary forms " * 20,
    ]
    W = clf.corpus.n_lanes
    dense_bits = np.zeros((2, W), dtype=np.uint32)
    meta = np.zeros((2, 3), dtype=np.int32)
    hashes = np.zeros((2, 16), dtype=np.uint8)
    st = clf._nat.featurize_batch(
        clf._nat_vocab, contents, dense_bits, meta, hashes
    )
    assert (st == 0).all()

    big = np.full((5, W), 7, dtype=np.uint32)
    meta2 = np.zeros((2, 3), dtype=np.int32)
    hashes2 = np.zeros((2, 16), dtype=np.uint8)
    st2 = clf._nat.featurize_batch(
        clf._nat_vocab,
        contents,
        big,
        meta2,
        hashes2,
        rows=np.array([3, 1], dtype=np.int64),
    )
    assert (st2 == 0).all()
    assert np.array_equal(big[3], dense_bits[0])
    assert np.array_equal(big[1], dense_bits[1])
    assert (big[0] == 7).all() and (big[2] == 7).all() and (big[4] == 7).all()
    assert np.array_equal(meta2, meta)
    assert np.array_equal(hashes2, hashes)
    # out-of-range rows are rejected, not written
    with pytest.raises(ValueError):
        clf._nat.featurize_batch(
            clf._nat_vocab, contents, big, meta2, hashes2,
            rows=np.array([3, 5], dtype=np.int64),
        )


def test_prepare_batch_sparse_subset_matches_dense(clf):
    """prepare_batch with preset rows (the dedupe shape) routes the
    native-eligible remainder through the row map; features must equal
    the no-preset run row for row."""
    from licensee_tpu.kernels.batch import BlobResult

    contents = [
        b"alpha beta gamma delta " * 40,
        b"the quick brown fox " * 40,
        b"permission is hereby granted " * 40,
        b"redistribution and use " * 40,
    ]
    dense = clf.prepare_batch(list(contents))
    preset = [None, BlobResult("mit", "exact", 100.0), None, None]
    sparse = clf.prepare_batch(list(contents), preset=preset)
    for i in (0, 2, 3):
        assert np.array_equal(sparse.bits[i], dense.bits[i])
        assert sparse.n_words[i] == dense.n_words[i]
        assert sparse.lengths[i] == dense.lengths[i]
    assert sparse.results[1] is preset[1]
    assert sparse.todo == [0, 2, 3]
