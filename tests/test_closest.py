"""batch-detect --closest K: per-row top-K candidate lists (the batch
analog of the CLI's closest-licenses view, commands/detect.rb:44-63)."""

from __future__ import annotations

import json
import re

import numpy as np
import pytest

from licensee_tpu.corpus.license import License
from licensee_tpu.kernels.batch import BatchClassifier
from licensee_tpu.matchers.dice import Dice
from licensee_tpu.project_files.license_file import LicenseFile


def rendered(key: str) -> str:
    lic = next(l for l in License.all(hidden=True, pseudo=False) if l.key == key)
    return re.sub(r"\[(\w+)\]", "example", lic.content or "")


@pytest.fixture(scope="module")
def clf():
    return BatchClassifier(pad_batch_to=16, closest=3)


def test_closest_rows_match_scalar_ranking(clf):
    """The top-3 list must contain the same candidates, same float64
    confidences, as the scalar Dice matcher's full ranking.  (A verbatim
    rendering would stop at the Exact prefilter — closest candidates
    come from the Dice stage, mirroring the reference chain.)"""
    # PREpended: GPL-3.0's "END OF TERMS" truncation would eat appended
    # text and the blob would be exact again.  The noise drops GPL to
    # ~97.6 (below the 98 threshold), so the row is unmatched and the
    # closest list IS the answer — exactly the CLI's no-match view.
    content = "nudged off the exact prefilter\n\n" + rendered("gpl-3.0")
    results = clf.classify_blobs([content])
    r = results[0]
    assert r.key is None
    assert r.closest is not None and len(r.closest) == 3
    assert r.closest[0][0] == "gpl-3.0"
    # scalar ranking over all licenses (dice.rb licenses_by_similarity)
    file = LicenseFile(content, "LICENSE")
    matcher = Dice(file)
    ranked = [
        (lic.key, score)
        for lic, score in matcher.licenses_by_similarity
        if lic.key != r.key
    ][:3]
    assert [k for k, _ in r.closest] == [k for k, _ in ranked]
    for (_, got), (_, want) in zip(r.closest, ranked):
        assert got == want  # float64-exact


def test_closest_on_unmatched_blob(clf):
    """An unmatched blob still reports its nearest candidates."""
    # heavily noised AGPL body: below threshold but AGPL-adjacent
    body = rendered("agpl-3.0")
    words = body.split()
    noised = " ".join(
        w if i % 7 else f"zz{i}" for i, w in enumerate(words)
    )
    results = clf.classify_blobs([noised])
    r = results[0]
    assert r.key is None
    assert r.closest and r.closest[0][0] in ("agpl-3.0", "gpl-3.0")
    assert all(c >= 0 for _, c in r.closest)
    # sorted descending
    confs = [c for _, c in r.closest]
    assert confs == sorted(confs, reverse=True)


def test_closest_excludes_matched_key(clf):
    results = clf.classify_blobs([rendered("mit") + "\noneextraword"])
    r = results[0]
    assert r.key == "mit"
    assert all(k != "mit" for k, _ in r.closest)


def test_closest_absent_without_option():
    plain = BatchClassifier(pad_batch_to=16, mesh=None)
    r = plain.classify_blobs([rendered("mit")])[0]
    assert r.closest is None
    assert "closest" not in r.as_dict()


def test_closest_row_serialization(tmp_path):
    from licensee_tpu.projects.batch_project import BatchProject

    p = tmp_path / "LICENSE"
    p.write_text(rendered("isc") + "\nextra trailing words")
    out = tmp_path / "out.jsonl"
    project = BatchProject([str(p)], batch_size=4, closest=2)
    project.run(str(out), resume=False)
    row = json.loads(out.read_text().splitlines()[0])
    assert len(row["closest"]) == 2
    for key, conf in row["closest"]:
        assert isinstance(key, str) and isinstance(conf, float)


def test_topk_exact_at_f32_colliding_boundary():
    """Adversarial rank-k boundary: candidate scores that COLLIDE in
    float32 must still come back in exact fraction order (int64
    cross-multiplication, ties to the lower index) — the k-th slot
    admits exactly the right candidate."""
    from fractions import Fraction

    import jax.numpy as jnp

    from licensee_tpu.kernels.dice_xla import topk_candidates

    # (d-1)//2 / d = 1/2 - 1/(2d): adjacent pairs differ by ~1e-10,
    # far below f32's ~6e-8 spacing at 0.5.  Shuffled so index order
    # and score order disagree; one exact tie pair (indexes 3 and 6)
    # checks the lower-index break.
    dens = [99991, 99961, 99989, 100000, 99979, 99971, 50000, 99959]
    nums = [(d - 1) // 2 for d in dens]
    nums[3], dens[3] = 50000, 100000  # == 25000/50000 at index 6
    nums[6], dens[6] = 25000, 50000
    f32 = np.asarray(nums, np.float32) / np.asarray(dens, np.float32)
    assert len(set(f32.tolist())) < len(dens)  # the premise: f32 collides

    order = sorted(
        range(len(dens)),
        key=lambda i: (-Fraction(nums[i], dens[i]), i),
    )
    for k in (1, 4, len(dens)):
        k_idx, k_num, k_den = topk_candidates(
            jnp.asarray([nums], jnp.int32), jnp.asarray([dens], jnp.int32), k
        )
        assert list(np.asarray(k_idx)[0]) == order[:k], k
        assert list(np.asarray(k_num)[0]) == [nums[i] for i in order[:k]]
        assert list(np.asarray(k_den)[0]) == [dens[i] for i in order[:k]]


def test_closest_rejects_pallas():
    with pytest.raises(ValueError):
        BatchClassifier(pad_batch_to=16, method="pallas", closest=2)


def test_closest_on_device_mesh():
    """closest rides the sharded scorer: DP and DPxTP meshes produce the
    same rows (top-1 AND candidate lists) as the single-device path."""
    single = BatchClassifier(pad_batch_to=16, mesh=None, closest=3)
    contents = [
        "nudged off the exact prefilter\n\n" + rendered("gpl-3.0"),
        rendered("mit") + "\noneextraword",
        "totally unrelated prose about nothing in particular",
    ]
    want = single.classify_blobs(contents)
    for mesh in ((4, 1), (4, 2)):
        clf = BatchClassifier(pad_batch_to=16, mesh=mesh, closest=3)
        got = clf.classify_blobs(contents)
        for g, w in zip(got, want):
            assert (g.key, g.matcher, g.confidence) == (
                w.key,
                w.matcher,
                w.confidence,
            )
            assert g.closest == w.closest, mesh
