"""The fleet telemetry plane (PR 12): cross-process trace assembly
(obs/collect.py), the worker flight recorder (obs/flight.py), the SLO
burn-rate engine (obs/slo.py), the supervisor's black-box harvest, and
the traces/slo CLI surfaces.

The assembly tests pin the edge cases the collector must survive
deterministically: orphan worker spans (router restarted mid-request),
duplicate span arrival from a hedged twin, and tail truncation (a ring
that wrapped between pulls) — and in every case the critical-path
self-times must account the recorded end-to-end latency without double
counting."""

from __future__ import annotations

import json
import os
import random
import sys
import threading
import time

import pytest

from licensee_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    SLOEngine,
    TraceCollector,
    Tracer,
    assemble_rows,
    assemble_trace,
    flight_path_for_socket,
    load_flight_dump,
    render_tree,
    serve_objectives,
)
from licensee_tpu.obs.slo import (
    AvailabilityObjective,
    LatencyObjective,
)

TID = "ab" * 8


def _router_row(trace=TID, dur_ms=40.0, spans=None, status="ok"):
    return {
        "trace": trace, "id": 1, "kind": "trace", "proc": "router",
        "status": status, "dur_ms": dur_ms,
        "spans": spans if spans is not None else [
            {"name": "route", "t_ms": 0.0, "dur_ms": 0.0, "note": "to=w0"},
            {"name": "failover", "t_ms": 9.8, "dur_ms": 0.0,
             "note": "w0: connection lost"},
            {"name": "route", "t_ms": 10.0, "dur_ms": 0.0, "note": "to=w1"},
        ],
    }


def _worker_row(proc="w1", trace=TID, dur_ms=12.0, spans=None,
                status="ok"):
    return {
        "trace": trace, "id": 1, "kind": "trace", "proc": proc,
        "status": status, "dur_ms": dur_ms,
        "spans": spans if spans is not None else [
            {"name": "queue_wait", "t_ms": 0.0, "dur_ms": 2.0},
            {"name": "featurize", "t_ms": 2.0, "dur_ms": 1.0},
            {"name": "device", "t_ms": 3.0, "dur_ms": 8.0},
        ],
    }


def _critical_ok(tree, tol=0.05):
    e2e = tree["e2e_ms"]
    return e2e > 0 and abs(tree["critical_ms"] - e2e) <= tol * e2e


# -- trace assembly ------------------------------------------------------


def test_failover_tree_joins_router_and_surviving_worker():
    tree = assemble_trace([_router_row(), _worker_row()])
    assert tree["procs"] == ["router", "w1"]
    assert not tree["orphan"]
    assert tree["e2e_ms"] == 40.0
    root_span_names = [c["name"] for c in tree["root"]["children"]]
    assert "failover" in root_span_names
    assert _critical_ok(tree)
    # the worker's stages carry their own self-time, the router the rest
    path = {(c["proc"], c["name"]): c["self_ms"]
            for c in tree["critical_path"]}
    assert path[("w1", "queue_wait")] == 2.0
    assert path[("w1", "device")] == 8.0
    assert path[("router", "request")] == pytest.approx(28.0)


def test_orphan_worker_rows_root_their_own_tree():
    """Router restarted mid-request: the worker row must still
    assemble — flagged orphan, critical path over its own stages."""
    tree = assemble_trace([_worker_row(proc="w0")])
    assert tree["orphan"] is True
    assert tree["procs"] == ["w0"]
    assert tree["e2e_ms"] == 12.0
    assert _critical_ok(tree)
    names = {c["name"] for c in tree["critical_path"]}
    assert {"queue_wait", "featurize", "device"} <= names


def test_hedged_twin_duplicate_never_double_counts():
    """A hedge sends the same request to two workers; the loser's row
    arrives too (and the winner's row arrives TWICE across pulls).
    Exactly one attempt may contribute critical-path time."""
    winner = _worker_row(proc="w1", dur_ms=12.0)
    loser = _worker_row(proc="w2", dur_ms=11.0, status="late")
    rows = [_router_row(), winner, loser, dict(winner)]
    tree = assemble_trace(rows)
    assert tree["attempts"] == 2
    assert tree["duplicates_dropped"] == 1
    assert _critical_ok(tree)
    procs_on_path = {c["proc"] for c in tree["critical_path"]}
    assert procs_on_path == {"router", "w1"}, (
        "the losing twin leaked onto the critical path"
    )


def test_tail_truncation_keeps_assembly_deterministic():
    """Ring wrapped between pulls: the worker tail lost its early
    spans.  Assembly must stay deterministic under any arrival order
    and still account e2e time without double counting."""
    truncated = _worker_row(spans=[
        {"name": "device", "t_ms": 3.0, "dur_ms": 8.0},
    ])
    rows = [_router_row(), truncated]
    base = assemble_trace(rows)
    assert _critical_ok(base)
    for seed in range(8):
        shuffled = list(rows) + [dict(truncated)]
        random.Random(seed).shuffle(shuffled)
        again = assemble_trace(shuffled)
        assert json.dumps(again, sort_keys=True) == json.dumps(
            base if again["duplicates_dropped"] == 0 else {
                **base, "duplicates_dropped": 1,
            },
            sort_keys=True,
        )


def test_attempt_claiming_more_than_e2e_is_clamped():
    """Clock skew / truncation can make a worker claim more time than
    the router recorded end to end — the path must clamp, not mint."""
    tree = assemble_trace([
        _router_row(dur_ms=10.0),
        _worker_row(dur_ms=25.0, spans=[
            {"name": "queue_wait", "t_ms": 0.0, "dur_ms": 20.0},
            {"name": "device", "t_ms": 20.0, "dur_ms": 5.0},
        ]),
    ])
    assert tree["e2e_ms"] == 10.0
    assert tree["critical_ms"] == pytest.approx(10.0)


def test_slow_exemplar_rows_join_without_spans():
    """A mint-only router retains span-less `kind: "slow"` exemplars;
    a full worker row under the same ID still assembles, and the full
    row wins the root when the slow row is all the router has."""
    slow = {"trace": TID, "id": 1, "kind": "slow", "proc": "router",
            "status": "ok", "dur_ms": 300.0, "spans": []}
    tree = assemble_trace([slow, _worker_row()])
    assert tree["e2e_ms"] == 300.0
    assert not tree["orphan"]
    assert _critical_ok(tree)


def test_assemble_rows_sorts_slowest_first():
    rows = [
        _router_row(trace="11" * 8, dur_ms=5.0),
        _router_row(trace="22" * 8, dur_ms=50.0),
        _router_row(trace="33" * 8, dur_ms=20.0),
    ]
    trees = assemble_rows(rows)
    assert [t["trace"] for t in trees] == ["22" * 8, "33" * 8, "11" * 8]


def test_render_tree_carries_self_times_and_critical_path():
    text = render_tree(assemble_trace([_router_row(), _worker_row()]))
    assert "critical path" in text
    assert "[w1] device" in text
    assert "failover" in text


# -- the collector -------------------------------------------------------


def test_collector_tags_untagged_rows_with_source_and_dedupes():
    stub_tail = [{"trace": TID, "id": 1, "status": "ok",
                  "spans": [{"name": "stub_serve", "t_ms": 0.0,
                             "dur_ms": 4.0}]}]
    col = TraceCollector({
        "router": lambda: [_router_row()],
        "w1": lambda: list(stub_tail),
    })
    assert col.pull() == 2
    assert col.pull() == 0  # idempotent re-pull
    (tree,) = col.assembled(10)
    assert tree["procs"] == ["router", "w1"]
    assert _critical_ok(tree)


def test_collector_survives_a_dead_source_and_evicts_lru():
    def dead():
        raise OSError("worker gone")

    col = TraceCollector({"router": dead}, capacity=2)
    for i in range(4):
        tid = f"{i:02d}" * 8
        col.add_source(f"s{i}", lambda t=tid: [_router_row(trace=t)])
    col.pull()
    assert col.stats()["traces"] == 2  # bounded, oldest evicted
    assert len(col.assembled(10)) == 2


def test_collector_union_survives_ring_wrap_between_pulls():
    """First pull sees the worker row, the ring then wraps and the
    second pull sees only the router row — the stored union still
    assembles the joined tree."""
    tails = [[_worker_row()], [_router_row()]]
    col = TraceCollector({"fleet": lambda: tails.pop(0)})
    col.pull()
    col.pull()
    (tree,) = col.assembled(10)
    assert tree["procs"] == ["router", "w1"]
    assert not tree["orphan"]


# -- tracer tail tagging -------------------------------------------------


def test_tracer_tail_rows_carry_kind_and_proc():
    tracer = Tracer(sample_rate=1.0, slow_ms=1000.0, proc="w7")
    trace = tracer.start(request_id=1)
    trace.add_span("featurize", 0.001)
    tracer.finish(trace, "ok")
    tracer.note_slow("ff" * 8, 2, time.perf_counter(), 5.0)
    rows = tracer.tail(10)
    assert [r["kind"] for r in rows] == ["trace", "slow"]
    assert all(r["proc"] == "w7" for r in rows)
    # the pre-existing key set is intact
    assert {"trace", "id", "status", "dur_ms", "spans"} <= set(rows[0])


# -- the SLO engine ------------------------------------------------------


def _engine():
    reg = MetricsRegistry()
    events = reg.counter("serve_requests_total", labels=("event",))
    hist = reg.histogram("serve_stage_seconds", labels=("stage",))
    eng = SLOEngine(reg, serve_objectives()).attach()
    return reg, events, hist, eng


def test_slo_burn_zero_on_clean_traffic_and_gauges_exported():
    reg, events, hist, eng = _engine()
    events.labels(event="completed").inc(1000)
    hist.labels(stage="total").observe(0.01)
    out = eng.snapshot()
    avail = out["objectives"]["availability"]
    assert avail["max_burn"] == 0.0 and avail["ok"]
    assert out["ok"] is True
    snap = reg.snapshot()["slo_burn_rate"]["samples"]
    assert {s["labels"]["window"] for s in snap} == {
        "5m", "30m", "1h", "6h"
    }


def test_slo_burn_reflects_windowed_error_deltas():
    reg, events, hist, eng = _engine()
    t0 = 1000.0
    events.labels(event="completed").inc(1000)
    eng.evaluate(now=t0)
    # the next "minute": 1000 more good, 10 bad.  The 5m window still
    # reaches past the whole recorded history, so the delta runs from
    # the CONSTRUCTION baseline (0, 0): (10/2010)/0.001 ≈ 4.98
    events.labels(event="completed").inc(1000)
    events.labels(event="rejected").inc(10)
    out = eng.evaluate(now=t0 + 60.0)
    avail = out["objectives"]["availability"]
    assert avail["windows"]["5m"] == pytest.approx(4.98, abs=0.1)
    assert avail["max_burn"] >= avail["windows"]["6h"]
    # burn >= 1: the budget is being spent too fast, but one window
    # alone never pages — both fast windows must agree
    assert isinstance(avail["fast_burn_alert"], bool)


def test_slo_fast_pair_pages_only_when_both_windows_burn():
    reg, events, hist, eng = _engine()
    t0 = 0.0
    eng.evaluate(now=t0)
    events.labels(event="completed").inc(10)
    events.labels(event="rejected").inc(90)  # 90% errors
    out = eng.evaluate(now=t0 + 10.0)
    avail = out["objectives"]["availability"]
    # all history is inside every window here -> both pairs agree
    assert avail["fast_burn_alert"] is True
    assert out["ok"] is False


def test_latency_objective_reads_histogram_buckets():
    reg = MetricsRegistry()
    hist = reg.histogram("serve_stage_seconds", labels=("stage",))
    obj = LatencyObjective(
        "latency_p99", family="serve_stage_seconds",
        labels={"stage": "total"}, threshold_s=0.25, target=0.5,
    )
    for _ in range(8):
        hist.labels(stage="total").observe(0.01)  # good
    for _ in range(2):
        hist.labels(stage="total").observe(2.0)  # bad
    good, bad = obj.totals(reg)
    assert (good, bad) == (8.0, 2.0)


def test_availability_objective_ignores_bookkeeping_events():
    reg = MetricsRegistry()
    events = reg.counter("serve_requests_total", labels=("event",))
    obj = AvailabilityObjective(
        "availability", family="serve_requests_total",
        good_events=("completed",), bad_events=("rejected",),
        target=0.999,
    )
    events.labels(event="completed").inc(5)
    events.labels(event="cache_hits").inc(50)  # neither good nor bad
    events.labels(event="rejected").inc(1)
    assert obj.totals(reg) == (5.0, 1.0)


def test_slo_first_scrape_sees_errors_since_boot():
    """Errors accumulated BEFORE the first-ever scrape must burn: the
    window differences against the construction baseline, never
    vacuously against the first sample itself."""
    reg, events, hist, eng = _engine()
    events.labels(event="completed").inc(500)
    events.labels(event="rejected").inc(500)  # 50% errors, never scraped
    out = eng.evaluate()  # the FIRST evaluation ever
    avail = out["objectives"]["availability"]
    assert avail["max_burn"] > 14.4
    assert out["ok"] is False


def test_slo_window_excludes_history_older_than_the_window():
    """Once the ring holds a sample older than the cutoff, the window
    differences against it — old errors age out of the fast windows."""
    reg, events, hist, eng = _engine()
    t0 = 1000.0
    events.labels(event="rejected").inc(100)  # ancient errors
    eng.evaluate(now=t0)
    events.labels(event="completed").inc(1000)
    out = eng.evaluate(now=t0 + 400.0)  # 5m cutoff lands AFTER t0
    avail = out["objectives"]["availability"]
    assert avail["windows"]["5m"] == 0.0  # the old errors aged out
    assert avail["windows"]["6h"] > 0.0  # but still burn the slow window


def test_slo_ancient_errors_age_out_of_the_longest_window():
    """Errors from hour 1 of a day-plus process must eventually leave
    even the 6h window: pruning keeps one sample at or before the
    horizon as the 6h base, so the delta stops reaching the ancient
    burst (the gauge decays to 0 instead of paging forever)."""
    reg, events, hist, eng = _engine()
    events.labels(event="rejected").inc(100)
    eng.evaluate(now=0.0)
    events.labels(event="completed").inc(10_000)
    eng.evaluate(now=3600.0)
    out = eng.evaluate(now=30_000.0)  # ~8.3h: the burst is > 6h old
    avail = out["objectives"]["availability"]
    assert avail["windows"]["6h"] == 0.0, avail["windows"]


def test_hedge_winner_is_the_fastest_ok_attempt():
    """Both hedge twins record status ok (the loser never learns it
    lost); the critical path must follow the FASTEST ok attempt — the
    answer the client actually got — not the slow loser."""
    fast = _worker_row(proc="w1", dur_ms=12.0)
    slow_loser = _worker_row(proc="w2", dur_ms=30.0, spans=[
        {"name": "queue_wait", "t_ms": 0.0, "dur_ms": 28.0},
        {"name": "device", "t_ms": 28.0, "dur_ms": 2.0},
    ])
    tree = assemble_trace([
        _router_row(dur_ms=13.0), fast, slow_loser,
    ])
    procs_on_path = {c["proc"] for c in tree["critical_path"]}
    assert procs_on_path == {"router", "w1"}, tree["critical_path"]
    assert _critical_ok(tree)


def test_slo_sample_cap_decimates_instead_of_shrinking_horizon(
    monkeypatch,
):
    """A fast scrape cadence overflowing the sample cap must coarsen
    resolution, never shrink the covered horizon: the 6h base sample
    survives, so ancient errors still age out of the longest window."""
    import licensee_tpu.obs.slo as slo_mod

    monkeypatch.setattr(slo_mod, "_MAX_SAMPLES", 8)
    reg, events, hist, eng = _engine()
    events.labels(event="rejected").inc(100)
    eng.evaluate(now=0.0)
    events.labels(event="completed").inc(10_000)
    for i in range(1, 60):  # every 10 min for ~10h: cap overflows
        eng.evaluate(now=i * 600.0)
    out = eng.evaluate(now=36_000.0)  # the burst is > 6h old
    avail = out["objectives"]["availability"]
    assert avail["windows"]["6h"] == 0.0, avail["windows"]
    assert len(eng._samples) <= 9  # decimated, not unbounded


def test_slo_no_traffic_burns_nothing():
    _reg, _events, _hist, eng = _engine()
    out = eng.evaluate()
    assert out["ok"] is True
    assert out["objectives"]["availability"]["max_burn"] == 0.0


# -- the flight recorder -------------------------------------------------


def test_flight_ring_wraps_and_snapshot_orders_by_seq():
    fr = FlightRecorder(capacity=4, proc="w0")
    for i in range(11):
        fr.record("admission", id=i)
    events = fr.snapshot()
    assert [e["id"] for e in events] == [7, 8, 9, 10]
    assert [e["seq"] for e in events] == [7, 8, 9, 10]
    assert fr.stats()["dropped"] == 7


def test_flight_dump_roundtrip_and_stop_writes_final_box(tmp_path):
    path = str(tmp_path / "w0.sock.flight")
    fr = FlightRecorder(path, capacity=8, proc="w0",
                        flush_interval_s=0.02)
    fr.start()
    fr.record("boot")
    fr.record("admission", id=1, trace="aa" * 8)
    deadline = time.perf_counter() + 5.0
    while time.perf_counter() < deadline:
        if load_flight_dump(path):
            break
        time.sleep(0.01)
    box = load_flight_dump(path)
    assert box and box["proc"] == "w0"
    fr.record("shutdown")
    fr.stop()
    box = load_flight_dump(path)
    assert [e["kind"] for e in box["events"]] == [
        "boot", "admission", "shutdown",
    ]
    assert box["events"][1]["trace"] == "aa" * 8


def test_flight_record_is_safe_under_concurrent_appenders():
    fr = FlightRecorder(capacity=128, proc="w0")

    def hammer(k):
        for i in range(500):
            fr.record("admission", worker=k, i=i)

    threads = [threading.Thread(target=hammer, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = fr.snapshot()
    assert 0 < len(events) <= 128
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)


def test_flight_path_convention_matches_supervisor():
    assert flight_path_for_socket("/run/w0.sock") == "/run/w0.sock.flight"


def test_flight_missing_dump_reads_none(tmp_path):
    assert load_flight_dump(str(tmp_path / "absent.flight")) is None
    torn = tmp_path / "torn.flight"
    torn.write_text("{not json", encoding="utf-8")
    assert load_flight_dump(str(torn)) is None


# -- supervisor harvest (real stub process, real SIGKILL) ---------------


def test_supervisor_harvests_flight_dump_on_sigkill(tmp_path):
    from licensee_tpu.fleet import faults
    from licensee_tpu.fleet.supervisor import Supervisor, worker_env
    from licensee_tpu.fleet.wire import oneshot

    sock = str(tmp_path / "w0.sock")

    def argv(name, path):
        return [sys.executable, "-m", "licensee_tpu.fleet.faults",
                "--socket", path, "--name", name]

    supervisor = Supervisor(
        {"w0": sock}, argv_for=argv,
        env_for=lambda n, c: worker_env(None, None),
        probe_interval_s=0.1, backoff_base_s=0.1, backoff_max_s=1.0,
        startup_grace_s=30.0,
    )
    try:
        supervisor.start()
        assert supervisor.wait_healthy(30.0)
        for i in range(5):
            oneshot(sock, {"id": i, "content": f"blob {i}",
                           "trace": f"{i:016x}"}, 5.0)
        # give the stub's 50 ms flusher a beat to spill the events
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            box = load_flight_dump(flight_path_for_socket(sock))
            if box and any(
                e["kind"] == "admission" for e in box["events"]
            ):
                break
            time.sleep(0.02)
        handle = supervisor.workers["w0"]
        faults.kill(handle.pid)
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            if handle.restart_log:
                break
            time.sleep(0.05)
        assert handle.restart_log, "supervisor never logged the crash"
        entry = handle.restart_log[0]
        assert entry["reason"] == "crash"
        assert entry["signal"] == 9 and entry["exit_code"] is None
        assert entry["backoff_s"] >= 0.1
        assert entry["flight_dump"] == flight_path_for_socket(sock)
        assert entry["flight_harvested"] is True
        kinds = {e["kind"] for e in entry["flight_events"]}
        assert "admission" in kinds
        assert entry["flight_proc"] == "w0"
        # the status surface carries the harvest for operators
        assert supervisor.status()["w0"]["restart_log"][0][
            "flight_harvested"
        ] is True
        # the dump was CONSUMED: a crash-looping respawn that dies
        # before writing its own box must not replay this one (the
        # fresh idle incarnation writes nothing until its first event)
        assert not os.path.exists(flight_path_for_socket(sock))
    finally:
        supervisor.stop()


# -- the traces / slo CLI -----------------------------------------------


def test_traces_cli_renders_assembled_trees(monkeypatch, capsys):
    import importlib

    cli = importlib.import_module("licensee_tpu.cli.main")

    def fake_scrape(_sock, payload, _timeout):
        assert payload["op"] == "traces"
        return {"id": None, "traces": [
            assemble_trace([_router_row(), _worker_row()]),
            assemble_trace([_router_row(trace="cd" * 8, dur_ms=5.0)]),
        ]}

    monkeypatch.setattr(cli, "_scrape_row", fake_scrape)
    rc = cli.main(["traces", "--socket", "front.sock", "--slowest", "1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("critical path") == 1  # --slowest 1: one tree
    assert "failover" in out and "[w1] device" in out

    rc = cli.main(["traces", "--socket", "front.sock", "--json"])
    out = capsys.readouterr().out
    trees = [json.loads(line) for line in out.splitlines()]
    assert rc == 0 and len(trees) == 2
    assert trees[0]["e2e_ms"] >= trees[1]["e2e_ms"]


def test_traces_cli_reports_worker_socket_mistake(monkeypatch, capsys):
    import importlib

    cli = importlib.import_module("licensee_tpu.cli.main")

    monkeypatch.setattr(
        cli, "_scrape_row",
        lambda *_a: {"id": None,
                     "error": "bad_request: unknown op 'traces'"},
    )
    rc = cli.main(["traces", "--socket", "w0.sock"])
    assert rc == 1
    assert "front socket" in capsys.readouterr().err


def test_slo_cli_verdict_and_exit_code(monkeypatch, capsys):
    import importlib

    cli = importlib.import_module("licensee_tpu.cli.main")

    reg = MetricsRegistry()
    events = reg.counter("serve_requests_total", labels=("event",))
    reg.histogram("serve_stage_seconds", labels=("stage",))
    eng = SLOEngine(reg, serve_objectives()).attach()
    events.labels(event="completed").inc(100)
    block = eng.snapshot()
    monkeypatch.setattr(
        cli, "_scrape_row",
        lambda *_a: {"id": None, "stats": {"slo": block}},
    )
    rc = cli.main(["slo", "--socket", "w0.sock"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "availability" in out and "slo: ok" in out

    events.labels(event="rejected").inc(1000)
    burning = eng.evaluate()
    monkeypatch.setattr(
        cli, "_scrape_row",
        lambda *_a: {"id": None, "stats": {"slo": burning}},
    )
    rc = cli.main(["slo", "--socket", "w0.sock", "--json"])
    out = capsys.readouterr().out
    assert rc == 1
    assert json.loads(out)["ok"] is False


def test_slo_cli_without_slo_block_errors(monkeypatch, capsys):
    import importlib

    cli = importlib.import_module("licensee_tpu.cli.main")

    monkeypatch.setattr(
        cli, "_scrape_row", lambda *_a: {"id": None, "stats": {}},
    )
    rc = cli.main(["slo", "--socket", "w0.sock"])
    assert rc == 1
    assert "no slo block" in capsys.readouterr().err
