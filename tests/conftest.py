"""Test harness configuration.

JAX-dependent tests run on a virtual 8-device CPU mesh: multi-chip sharding
is validated without TPU hardware (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os
import re
import subprocess

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def _force_cpu_jax():
    """Pin JAX to the virtual CPU mesh for tests.

    The environment's TPU plugin may override jax_platforms via config at
    interpreter startup (sitecustomize), which beats the JAX_PLATFORMS env
    var — so set the config explicitly before any backend initializes."""
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except ImportError:
        pass


_force_cpu_jax()

@pytest.fixture()
def lock_order_sanitizer(monkeypatch):
    """Wrap threading.Lock/RLock for the duration of one test and fail
    it on any lock-order inversion observed across its threads (see
    tests/lock_sanitizer.py).  Opt-in per module:

        pytestmark = pytest.mark.usefixtures("lock_order_sanitizer")
    """
    import threading as _threading

    from lock_sanitizer import LockOrderSanitizer

    sanitizer = LockOrderSanitizer()
    monkeypatch.setattr(_threading, "Lock", sanitizer.make_lock)
    monkeypatch.setattr(_threading, "RLock", sanitizer.make_rlock)
    yield sanitizer
    inversions = sanitizer.check()
    if inversions:
        pytest.fail(
            "lock-order sanitizer: "
            + "\n---\n".join(inversions),
            pytrace=False,
        )


FIXTURES_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")


def fixture_path(name: str) -> str:
    return os.path.join(FIXTURES_DIR, name)


def fixture_contents(name: str) -> str:
    with open(fixture_path(name), encoding="utf-8") as f:
        return f.read()


# Field values used by the reference's vendored-license round-trip spec
# (spec/spec_helper.rb:65-79)
FIELD_VALUES = {
    "fullname": "Ben Balter",
    "year": "2018",
    "email": "ben@github.invalid",
    "projecturl": "http://github.invalid/benbalter/licensee",
    "login": "benbalter",
    "project": "Licensee",
    "description": "Detects licenses",
}


def sub_copyright_info(license) -> str:
    """Render a license template with concrete field values (the mustache
    rendering in spec_helper.rb:77-79)."""
    return re.sub(
        r"\{\{\{(\w+)\}\}\}",
        lambda m: FIELD_VALUES[m.group(1)],
        license.content_for_mustache,
    )


@pytest.fixture()
def git_fixture(tmp_path):
    """Copy a fixture dir into a temp git repo (spec_helper.rb:96-103)."""

    def _build(fixture: str) -> str:
        import shutil

        dest = tmp_path / fixture
        shutil.copytree(fixture_path(fixture), dest)
        subprocess.run(["git", "init", "-q"], cwd=dest, check=True)
        subprocess.run(
            ["git", "config", "--local", "commit.gpgsign", "false"],
            cwd=dest,
            check=True,
        )
        subprocess.run(
            ["git", "config", "--local", "user.email", "test@example.invalid"],
            cwd=dest,
            check=True,
        )
        subprocess.run(
            ["git", "config", "--local", "user.name", "Test"], cwd=dest, check=True
        )
        subprocess.run(["git", "add", "."], cwd=dest, check=True)
        subprocess.run(
            ["git", "commit", "-q", "-m", "initial commit"], cwd=dest, check=True
        )
        return str(dest)

    return _build
