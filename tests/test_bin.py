"""The installed CLI entry point run as a real subprocess
(parity: spec/bin_spec.rb + the subprocess contexts of
spec/licensee/commands/detect_spec.rb) — exercises the shebang, the
sys.path shim, argv handling and process exit codes, none of which the
in-process tests in test_cli.py touch."""

import json
import os
import subprocess
import sys

import yaml

from tests.conftest import fixture_path

BIN = os.path.join(os.path.dirname(__file__), "..", "bin", "licensee-tpu")


def run_bin(*args, cwd=None):
    return subprocess.run(
        [sys.executable, BIN, *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=120,
    )


def test_help_returns_zero_and_lists_commands():
    proc = run_bin("help")
    assert proc.returncode == 0
    assert "Licensee commands:" in proc.stdout
    for command in ("detect", "diff", "license-path", "version"):
        assert command in proc.stdout


def test_detect_path_argument():
    proc = run_bin("detect", fixture_path("mit"))
    assert proc.returncode == 0
    parsed = yaml.safe_load(proc.stdout)
    assert parsed["License"] == "MIT"
    assert parsed["LICENSE.txt"]["Matcher"].endswith(".Exact")


def test_detect_no_arguments_uses_cwd():
    proc = run_bin("detect", cwd=fixture_path("mit"))
    assert proc.returncode == 0
    assert yaml.safe_load(proc.stdout)["License"] == "MIT"


def test_default_command_is_detect():
    proc = run_bin(fixture_path("mit"))
    assert proc.returncode == 0
    assert yaml.safe_load(proc.stdout)["License"] == "MIT"


def test_detect_json():
    proc = run_bin("detect", "--json", fixture_path("mit"))
    assert proc.returncode == 0
    parsed = json.loads(proc.stdout)
    assert parsed["licenses"][0]["key"] == "mit"
    assert parsed["matched_files"][0]["matcher"]["name"] == "exact"


def test_detect_exit_code_one_when_no_license(tmp_path):
    (tmp_path / "README.md").write_text("no license here")
    proc = run_bin("detect", str(tmp_path))
    assert proc.returncode == 1


def test_diff():
    proc = run_bin("diff", fixture_path("mit"), "--license", "mit")
    assert proc.returncode == 0
    assert "Similarity:" in proc.stdout


def test_diff_stdin():
    """diff reads license text from STDIN when no path is given
    (commands/diff.rb:16-17)."""
    with open(
        os.path.join(fixture_path("mit"), "LICENSE.txt"), encoding="utf-8"
    ) as f:
        content = f.read()
    proc = subprocess.run(
        [sys.executable, BIN, "diff", "--license", "mit"],
        input=content,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0
    assert "100.00%" in proc.stdout


def test_license_path():
    proc = run_bin("license-path", fixture_path("mit"))
    assert proc.returncode == 0
    assert proc.stdout.strip().endswith("LICENSE.txt")


def test_version():
    import licensee_tpu

    proc = run_bin("version")
    assert proc.returncode == 0
    assert proc.stdout.strip() == licensee_tpu.__version__
