"""The installed CLI entry point run as a real subprocess
(parity: spec/bin_spec.rb + the subprocess contexts of
spec/licensee/commands/detect_spec.rb) — exercises the shebang, the
sys.path shim, argv handling and process exit codes, none of which the
in-process tests in test_cli.py touch."""

import json
import os
import subprocess
import sys

import yaml

from tests.conftest import fixture_path

BIN = os.path.join(os.path.dirname(__file__), "..", "bin", "licensee-tpu")


def run_bin(*args, cwd=None):
    return subprocess.run(
        [sys.executable, BIN, *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        timeout=120,
    )


def test_help_returns_zero_and_lists_commands():
    proc = run_bin("help")
    assert proc.returncode == 0
    assert "Licensee commands:" in proc.stdout
    for command in ("detect", "diff", "license-path", "version"):
        assert command in proc.stdout


def test_detect_path_argument():
    proc = run_bin("detect", fixture_path("mit"))
    assert proc.returncode == 0
    parsed = yaml.safe_load(proc.stdout)
    assert parsed["License"] == "MIT"
    assert parsed["LICENSE.txt"]["Matcher"].endswith(".Exact")


def test_detect_no_arguments_uses_cwd():
    proc = run_bin("detect", cwd=fixture_path("mit"))
    assert proc.returncode == 0
    assert yaml.safe_load(proc.stdout)["License"] == "MIT"


def test_default_command_is_detect():
    proc = run_bin(fixture_path("mit"))
    assert proc.returncode == 0
    assert yaml.safe_load(proc.stdout)["License"] == "MIT"


def test_detect_json():
    proc = run_bin("detect", "--json", fixture_path("mit"))
    assert proc.returncode == 0
    parsed = json.loads(proc.stdout)
    assert parsed["licenses"][0]["key"] == "mit"
    assert parsed["matched_files"][0]["matcher"]["name"] == "exact"


def test_detect_exit_code_one_when_no_license(tmp_path):
    (tmp_path / "README.md").write_text("no license here")
    proc = run_bin("detect", str(tmp_path))
    assert proc.returncode == 1


def test_diff():
    proc = run_bin("diff", fixture_path("mit"), "--license", "mit")
    assert proc.returncode == 0
    assert "Similarity:" in proc.stdout


def test_diff_stdin():
    """diff reads license text from STDIN when no path is given
    (commands/diff.rb:16-17)."""
    with open(
        os.path.join(fixture_path("mit"), "LICENSE.txt"), encoding="utf-8"
    ) as f:
        content = f.read()
    proc = subprocess.run(
        [sys.executable, BIN, "diff", "--license", "mit"],
        input=content,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0
    assert "100.00%" in proc.stdout


def test_license_path():
    proc = run_bin("license-path", fixture_path("mit"))
    assert proc.returncode == 0
    assert proc.stdout.strip().endswith("LICENSE.txt")


def test_version():
    import licensee_tpu

    proc = run_bin("version")
    assert proc.returncode == 0
    assert proc.stdout.strip() == licensee_tpu.__version__


def test_batch_detect_auto_flags_through_real_bin(tmp_path):
    """The round-4 flag surface (--mode auto, --attribution, --closest,
    --progress) through the REAL executable: argparse wiring, JSONL on
    stdout, heartbeats+stats on stderr."""
    with open(
        os.path.join(fixture_path("mit"), "LICENSE.txt"), "rb"
    ) as f:
        mit = f.read()
    (tmp_path / "LICENSE").write_bytes(mit)
    (tmp_path / "main.c").write_text("int main(void){return 0;}\n")
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{tmp_path / 'LICENSE'}\n{tmp_path / 'main.c'}\n")
    out = tmp_path / "out.jsonl"
    proc = run_bin(
        "batch-detect", str(manifest), "--mode", "auto", "--attribution",
        "--closest", "2", "--progress", "100", "--output", str(out),
        "--stats",
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert rows[0]["key"] == "mit"
    assert rows[0]["attribution"] == "Copyright (c) 2016 Ben Balter"
    assert rows[1]["key"] is None
    stats = json.loads(proc.stderr.strip().splitlines()[-1])
    assert stats["routed"] == {"license": 1, "none": 1}

    # bad values are rejected in argparse BEFORE the manifest loads
    # (exit 2, usage + clean error line, never a traceback)
    for bad in (["--progress", "-1"], ["--featurize-procs", "-2"]):
        proc = run_bin(
            "batch-detect", str(manifest), *bad, "--output", str(out)
        )
        assert proc.returncode == 2
        assert any(
            "error:" in l and "must be >= 0" in l
            for l in proc.stderr.splitlines()
        ), proc.stderr[:400]
        assert "Traceback" not in proc.stderr
