"""The online serving subsystem (licensee_tpu/serve/): micro-batch
scheduling, content-hash result cache, backpressure, deadlines, device
fallback, and the JSONL transports.  All CPU-only and fast — the
scheduler's clocks are monotonic and every wait has a generous bound.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time

import pytest

from licensee_tpu.kernels.batch import BatchClassifier
from licensee_tpu.serve.cache import ResultCache
from licensee_tpu.serve.scheduler import MicroBatcher, QueueFullError
from licensee_tpu.serve.server import (
    UnixServer,
    _Session,
    serve_session,
)
from licensee_tpu.serve.stats import LatencyStats
from tests.conftest import fixture_contents

# lock-order sanitizer across every serve test (PR 6 infrastructure,
# previously fleet/stripes only): the corpus-reload path added a new
# lock interaction with the scheduler (_reload_lock -> scheduler lock
# -> cache lock), and an inversion anywhere in serve/ must fail here
# before it deadlocks a live worker
pytestmark = pytest.mark.usefixtures("lock_order_sanitizer")


@pytest.fixture(scope="module")
def clf():
    return BatchClassifier(pad_batch_to=16, mesh=None)


@pytest.fixture(scope="module")
def mit_body():
    from licensee_tpu.corpus.license import License

    return re.sub(r"\[(\w+)\]", "example", License.find("mit").content)


def dice_blob(mit_body: str, tag: str) -> str:
    """A unique Dice-bound blob: the MIT body plus a couple of noise
    words — defeats the Exact wordset prefilter but stays above the
    confidence threshold, so the row must cross the device."""
    return f"{mit_body}\nzqx{tag} zqy{tag}\n"


# -- scheduler core --


def test_prefilter_answers_without_device(clf, mit_body):
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        result = b.classify(mit_body, "LICENSE")
        assert (result.key, result.matcher) == ("mit", "exact")
        stats = b.stats()["scheduler"]
        assert stats["prefiltered"] == 1
        assert stats["device_batches"] == 0


def test_deadline_flush_fires_with_partial_batch(clf, mit_body):
    """3 requests against max_batch=64: the flush can only come from the
    max_delay deadline, and it must carry all three rows in ONE device
    batch."""
    with MicroBatcher(
        classifier=clf, max_batch=64, max_delay_ms=40.0, buckets=(4, 64),
        start=False,
    ) as b:
        reqs = [
            b.submit(dice_blob(mit_body, f"d{i}"), "LICENSE")
            for i in range(3)
        ]
        b.start()  # all 3 queued: exactly one deadline flush can fire
        results = [r.wait(60.0) for r in reqs]
        assert all(r.key == "mit" and r.matcher == "dice" for r in results)
        stats = b.stats()["scheduler"]
        assert stats["device_batches"] == 1
        assert stats["device_rows"] == 3
        assert stats["flush"]["deadline"] == 1
        assert stats["flush"]["full"] == 0


def test_full_batch_flushes_without_waiting(clf, mit_body):
    with MicroBatcher(
        classifier=clf, max_batch=2, max_delay_ms=10_000.0, start=False,
        buckets=(2,),
    ) as b:
        reqs = [
            b.submit(dice_blob(mit_body, f"f{i}"), "LICENSE")
            for i in range(2)
        ]
        b.start()
        t0 = time.perf_counter()
        for r in reqs:
            r.wait(60.0)
        # flushed on "full", not after the 10-second delay bound
        assert time.perf_counter() - t0 < 9.0
        assert b.stats()["scheduler"]["flush"]["full"] == 1


def test_bucket_padding_picks_smallest_fitting_shape(clf, mit_body):
    with MicroBatcher(
        classifier=clf, max_batch=16, max_delay_ms=10_000.0,
        buckets=(4, 16), start=False,
    ) as b:
        reqs = [
            b.submit(dice_blob(mit_body, f"b{i}"), "LICENSE")
            for i in range(3)
        ]
        b.start()
        for r in reqs:
            r.wait(60.0)
        stats = b.stats()["scheduler"]
    assert stats["buckets"] == {"4": 1}
    assert stats["padded_rows"] == 1  # 3 rows padded to the 4-bucket


def test_bucket_ladder_defaults_and_mesh_rounding(clf):
    b = MicroBatcher(classifier=clf, max_batch=256, start=False)
    try:
        assert b.buckets == (8, 32, 128, 256)
        assert b.bucket_for(1) == 8
        assert b.bucket_for(9) == 32
        assert b.bucket_for(256) == 256
    finally:
        b.close()


def test_cache_hit_skips_device_dispatch(clf, mit_body):
    blob = dice_blob(mit_body, "cache")
    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        first = b.submit(blob, "LICENSE")
        r1 = first.wait(60.0)
        assert (r1.key, r1.matcher) == ("mit", "dice")
        batches_before = b.stats()["scheduler"]["device_batches"]
        second = b.submit(blob, "LICENSE")
        r2 = second.wait(60.0)
        assert second.cached and not first.cached
        assert (r2.key, r2.matcher, r2.confidence) == (
            r1.key, r1.matcher, r1.confidence
        )
        stats = b.stats()
        assert stats["scheduler"]["device_batches"] == batches_before
        assert stats["scheduler"]["cache_hits"] == 1
        assert stats["cache"]["hits"] == 1


def test_concurrent_duplicates_coalesce_to_one_device_row(clf, mit_body):
    blob = dice_blob(mit_body, "dup")
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), start=False
    ) as b:
        a = b.submit(blob, "LICENSE")
        c = b.submit(blob, "LICENSE")
        b.start()
        ra, rc = a.wait(60.0), c.wait(60.0)
        assert (ra.key, rc.key) == ("mit", "mit")
        assert rc.confidence == ra.confidence
        assert c.cached  # answered without its own device slot
        stats = b.stats()["scheduler"]
        assert stats["device_rows"] == 1
        assert stats["coalesced"] == 1


def test_bucket_rounding_covers_max_batch_on_a_mesh():
    """Every bucket — including the implicitly appended max_batch —
    must divide across the mesh data axis, or full flushes would raise
    in dispatch_chunks and degrade to the scalar fallback forever."""

    class _FakeMesh:
        shape = {"data": 8}

    class _FakeClf:
        mesh = _FakeMesh()
        mode = "license"

    b = MicroBatcher(
        classifier=_FakeClf(), max_batch=100, buckets=(16, 30),
        start=False,
    )
    try:
        assert b.buckets == (16, 32, 104)
        assert all(bucket % 8 == 0 for bucket in b.buckets)
        assert b.buckets[-1] >= b.max_batch
    finally:
        b.close()


def test_follower_outlives_expired_primary(clf, mit_body):
    """A coalesced duplicate with no deadline must get the verdict even
    when its primary's own deadline lapsed in the queue."""
    blob = dice_blob(mit_body, "heir")
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), start=False
    ) as b:
        doomed = b.submit(blob, "LICENSE", deadline_ms=5.0)
        heir = b.submit(blob, "LICENSE")  # coalesces onto doomed's row
        time.sleep(0.05)  # doomed's deadline lapses; heir has none
        b.start()
        assert doomed.wait(60.0).error == "deadline_exceeded"
        verdict = heir.wait(60.0)
        assert (verdict.key, verdict.matcher) == ("mit", "dice")
        assert heir.cached
        stats = b.stats()["scheduler"]
        assert stats["expired"] == 1
        assert stats["device_rows"] == 1


def test_submit_after_close_raises(clf, mit_body):
    from licensee_tpu.serve.scheduler import BatcherClosedError

    b = MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,))
    b.close()
    with pytest.raises(BatcherClosedError):
        b.submit(dice_blob(mit_body, "dead"), "LICENSE")


def test_full_queue_rejects_with_retry_after(clf, mit_body):
    with MicroBatcher(
        classifier=clf, queue_depth=2, max_delay_ms=5.0, buckets=(4,),
        start=False,
    ) as b:
        reqs = [
            b.submit(dice_blob(mit_body, f"q{i}"), "LICENSE")
            for i in range(2)
        ]
        with pytest.raises(QueueFullError) as exc_info:
            b.submit(dice_blob(mit_body, "q-overflow"), "LICENSE")
        assert exc_info.value.retry_after > 0
        assert b.stats()["scheduler"]["rejected"] == 1
        # the queued requests still answer once the scheduler drains
        b.start()
        assert all(r.wait(60.0).key == "mit" for r in reqs)


def test_per_request_deadline_expires_in_queue(clf, mit_body):
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), start=False
    ) as b:
        doomed = b.submit(
            dice_blob(mit_body, "late"), "LICENSE", deadline_ms=5.0
        )
        time.sleep(0.05)  # let the deadline lapse while queued
        b.start()
        result = doomed.wait(60.0)
        assert result.error == "deadline_exceeded"
        assert result.key is None
        assert b.stats()["scheduler"]["expired"] == 1


def test_device_failure_falls_back_to_scalar_dice(clf, mit_body):
    blob = dice_blob(mit_body, "fb")
    # the device-path verdict, for comparison (fresh content so neither
    # call can hit the other's cache)
    expected = clf.classify_blobs([blob])[0]
    assert (expected.key, expected.matcher) == ("mit", "dice")

    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        # the flush path's device seam is the ASYNC submit now
        original = b.classifier.dispatch_chunks_async

        def broken(*args, **kwargs):
            raise RuntimeError("injected device failure")

        b.classifier.dispatch_chunks_async = broken
        try:
            result = b.classify(blob, "LICENSE")
        finally:
            b.classifier.dispatch_chunks_async = original
        assert (result.key, result.matcher) == ("mit", "dice")
        assert result.confidence == expected.confidence
        assert b.stats()["scheduler"]["fallbacks"] == 1
        # the fallback verdict is clean, so it was cached like any other
        again = b.classify(blob, "LICENSE")
        assert again.confidence == expected.confidence
        assert b.stats()["scheduler"]["cache_hits"] == 1


def test_device_failure_at_await_with_chunks_in_flight(clf, mit_body):
    """The async split means the device can ALSO fail at await time,
    on the completion thread, with several submitted flushes in
    flight — every rider of every broken group must still answer via
    the host fallback, and the batcher must keep serving afterwards."""
    expected = clf.classify_blobs([dice_blob(mit_body, "aw0")])[0]
    assert (expected.key, expected.matcher) == ("mit", "dice")

    class _FailingFuture:
        def __len__(self):
            return 1

        def result(self):
            raise RuntimeError("injected await failure")

    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), pipeline_depth=2
    ) as b:
        original = b.classifier.dispatch_chunks_async

        def submit_ok_await_fails(prepared, pad_to=None):
            return _FailingFuture()  # the SUBMIT half stays healthy

        b.classifier.dispatch_chunks_async = submit_ok_await_fails
        try:
            reqs = [
                b.submit(dice_blob(mit_body, f"aw{i}"), "LICENSE")
                for i in range(6)
            ]
            results = [r.wait(60.0) for r in reqs]
        finally:
            b.classifier.dispatch_chunks_async = original
        for res in results:
            assert (res.key, res.matcher) == ("mit", "dice")
            assert res.confidence == expected.confidence
        assert b.stats()["scheduler"]["fallbacks"] >= 6
        # the pipeline recovered: the next flush rides the real device
        post = b.classify(dice_blob(mit_body, "aw-post"), "LICENSE")
        assert (post.key, post.matcher) == ("mit", "dice")


def test_completion_thread_survives_a_completion_failure(clf, mit_body):
    """An exception escaping the completion half (here: the fallback
    itself dying after a device failure) must not end the completion
    thread — the bounded handoff queue would fill and wedge the
    scheduler.  The group's waiters get an error row, the counter
    ticks, and the NEXT flush rides the pipeline normally."""
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), pipeline_depth=2
    ) as b:
        original = b.classifier.dispatch_chunks_async

        def broken(*args, **kwargs):
            raise RuntimeError("injected device failure")

        orig_fb = b._scalar_fallback

        def bad_fallback(req):
            raise RuntimeError("injected fallback failure")

        b.classifier.dispatch_chunks_async = broken
        b._scalar_fallback = bad_fallback
        try:
            res = b.submit(dice_blob(mit_body, "ce0"), "LICENSE").wait(60.0)
        finally:
            b.classifier.dispatch_chunks_async = original
            b._scalar_fallback = orig_fb
        assert res.error is not None and "completion_error" in res.error
        assert b.stats()["scheduler"]["completion_errors"] == 1
        post = b.classify(dice_blob(mit_body, "ce1"), "LICENSE")
        assert (post.key, post.matcher) == ("mit", "dice")


def test_warm_start_precompiles_bucket_shapes():
    """The cold-start fix: warm_start=True compiles every bucket pad
    shape in the constructor, so no live request pays a jit compile —
    and the per-shape attribution names what each bucket's warmup
    cost."""
    fresh = BatchClassifier(pad_batch_to=16, mesh=None)
    with MicroBatcher(
        classifier=fresh, max_delay_ms=5.0, buckets=(4, 16),
        warm_start=True,
    ) as b:
        stats = fresh.dispatch_stats()
        # every bucket in the ladder (max_batch rides at the top)
        assert set(stats["per_shape"]) == set(b.buckets)
        assert stats["compiles"] == len(b.buckets)  # one per shape
        compiles_before = stats["compiles"]
        body = fixture_contents("mit/LICENSE.txt")
        res = b.classify(body + "\nzqwarm zqcold\n", "LICENSE")
        assert (res.key, res.matcher) == ("mit", "dice")
        after = fresh.dispatch_stats()
        # the live request's flush was a steady-state enqueue: the
        # bucket shape had already been compiled by the warmup probe
        assert after["compiles"] == compiles_before
        assert after["dispatches"] >= 1
        assert b.stats()["config"]["warm_start"] is True


def test_scheduler_stats_surface_pipeline_occupancy(clf, mit_body):
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), pipeline_depth=3
    ) as b:
        res = b.classify(dice_blob(mit_body, "occ"), "LICENSE")
        assert (res.key, res.matcher) == ("mit", "dice")
        stats = b.stats()
        pipe = stats["pipeline"]
        assert set(pipe["occupancy"]) == {"featurize", "device", "writer"}
        assert pipe["inflight_chunks"] == 0  # drained between flushes
        assert stats["config"]["pipeline_depth"] == 3


def test_pipeline_depth_bounds_submitted_unfinished_groups(clf, mit_body):
    """The in-flight bound is submit-to-ANSWERED, not queue residency:
    with pipeline_depth=1 a second flush must not touch the device
    until the completion thread has fully finished the first — the
    documented 'depth 1 = synchronous flush' contract."""
    release = threading.Event()
    lock = threading.Lock()
    inflight = [0]
    max_inflight = [0]
    original = clf.dispatch_chunks_async

    class _GatedFuture:
        def __init__(self, inner):
            self._inner = inner

        def __len__(self):
            return len(self._inner)

        def result(self):
            assert release.wait(60.0), "test never released the gate"
            outs = self._inner.result()
            with lock:
                inflight[0] -= 1
            return outs

    def gated_submit(prepared, pad_to=None):
        with lock:
            inflight[0] += 1
            max_inflight[0] = max(max_inflight[0], inflight[0])
        return _GatedFuture(original(prepared, pad_to=pad_to))

    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), pipeline_depth=1
    ) as b:
        b.classifier.dispatch_chunks_async = gated_submit
        try:
            r0 = b.submit(dice_blob(mit_body, "pd0"), "LICENSE")
            # let flush 0 submit and park on the gated await, then
            # offer a second flush: the scheduler must block on the
            # in-flight permit, never reaching the device
            deadline = time.monotonic() + 10.0
            while inflight[0] == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert inflight[0] == 1
            r1 = b.submit(dice_blob(mit_body, "pd1"), "LICENSE")
            time.sleep(0.3)  # time for a (buggy) second submit to land
            assert max_inflight[0] == 1
            release.set()
            res = [r.wait(60.0) for r in (r0, r1)]
        finally:
            b.classifier.dispatch_chunks_async = original
            release.set()
        for r in res:
            assert (r.key, r.matcher) == ("mit", "dice")
    assert max_inflight[0] == 1


def test_auto_mode_routes_and_skips_unscored_filenames(mit_body):
    auto = BatchClassifier(pad_batch_to=16, mesh=None, mode="auto")
    with MicroBatcher(classifier=auto, max_delay_ms=5.0) as b:
        licensed = b.classify(mit_body, "LICENSE")
        assert (licensed.key, licensed.matcher) == ("mit", "exact")
        unrouted = b.classify(mit_body, "main.c")
        assert (unrouted.key, unrouted.matcher) == (None, None)
        stats = b.stats()["scheduler"]
        assert stats["unrouted"] == 1


def test_serve_verdicts_match_offline_chain(clf):
    """Acceptance: serving answers == the batch/detect chain's answers
    for real fixture licenses (same code path by construction, but this
    pins it end-to-end)."""
    fixtures = [
        ("mit/LICENSE.txt", "LICENSE.txt"),
        ("bsd-2-author/LICENSE", "LICENSE"),
        ("cc-by-nd/LICENSE", "LICENSE"),
    ]
    contents = [fixture_contents(path) for path, _ in fixtures]
    offline = clf.classify_blobs(
        contents, filenames=[name for _, name in fixtures]
    )
    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        for content, (_, name), expected in zip(contents, fixtures, offline):
            got = b.classify(content, name)
            assert (got.key, got.matcher, got.confidence) == (
                expected.key, expected.matcher, expected.confidence
            )


# -- cache + stats units --


def test_result_cache_lru_and_counters():
    from licensee_tpu.kernels.batch import BlobResult

    cache = ResultCache(capacity=2)
    r = BlobResult("mit", "dice", 99.0, closest=[("isc", 88.0)])
    cache.put("a", r)
    cache.put("b", r)
    assert cache.get("a").key == "mit"  # touches "a": LRU order b, a
    cache.put("c", r)  # evicts "b"
    assert cache.get("b") is None
    assert cache.get("a") is not None and cache.get("c") is not None
    stats = cache.stats()
    assert stats["entries"] == 2
    assert stats["evictions"] == 1
    assert stats["hits"] == 3 and stats["misses"] == 1
    # stored results are frozen copies: the caller's list is not aliased
    assert isinstance(cache.get("a").closest, tuple)
    assert cache.get("a") is not r


def test_result_cache_zero_capacity_disables():
    from licensee_tpu.kernels.batch import BlobResult

    cache = ResultCache(capacity=0)
    cache.put("a", BlobResult("mit", "dice", 99.0))
    assert cache.get("a") is None
    assert len(cache) == 0


def test_latency_stats_percentiles():
    ls = LatencyStats(capacity=100)
    for ms in range(1, 101):  # 1..100 ms
        ls.record(ms / 1000.0)
    snap = ls.snapshot()
    assert snap["count"] == 100
    assert snap["p50_ms"] == 50.0
    assert snap["p95_ms"] == 95.0
    assert snap["p99_ms"] == 99.0
    assert snap["max_ms"] == 100.0
    empty = LatencyStats().snapshot()
    assert empty["count"] == 0 and empty["p99_ms"] is None


def test_latency_stats_capacity_one():
    """capacity=1 is the degenerate ring: every percentile IS the last
    sample, count/mean stay lifetime."""
    ls = LatencyStats(capacity=1)
    ls.record(0.010)
    ls.record(0.030)
    snap = ls.snapshot()
    assert snap["count"] == 2
    assert snap["p50_ms"] == 30.0
    assert snap["p99_ms"] == 30.0
    assert snap["max_ms"] == 30.0
    assert snap["mean_ms"] == 20.0  # lifetime mean, not window mean
    with pytest.raises(ValueError):
        LatencyStats(capacity=0)


def test_latency_stats_percentiles_after_ring_wrap():
    """After the ring wraps, percentiles cover exactly the most recent
    `capacity` samples — the overwrite must hit the OLDEST slot, so an
    early outlier ages out."""
    ls = LatencyStats(capacity=4)
    ls.record(9.999)  # the outlier that must age out
    for ms in (1, 2, 3, 4):  # wraps: overwrites the outlier first
        ls.record(ms / 1000.0)
    snap = ls.snapshot()
    assert snap["count"] == 5
    assert snap["max_ms"] == 4.0  # the outlier left the window
    assert snap["p50_ms"] == 2.0
    assert snap["p99_ms"] == 4.0


def test_latency_stats_concurrent_records():
    """N threads hammering record(): lifetime count must equal the sum
    of per-thread records (no lost updates), and the ring stays exactly
    `capacity` wide."""
    ls = LatencyStats(capacity=64)
    per_thread, n_threads = 500, 8

    def work():
        for i in range(per_thread):
            ls.record(0.001 * (i % 10 + 1))

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = ls.snapshot()
    assert snap["count"] == per_thread * n_threads
    assert len(ls._ring) == 64
    assert snap["p50_ms"] is not None and snap["max_ms"] <= 10.0


# -- transports --


def _session_lines(rows):
    return [json.dumps(r) for r in rows]


def test_session_answers_in_request_order(clf, mit_body):
    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        out: list[str] = []
        counts = serve_session(
            b,
            _session_lines(
                [
                    {"id": "dice-1", "content": dice_blob(mit_body, "s1"),
                     "filename": "LICENSE"},
                    {"id": "exact-2", "content": mit_body,
                     "filename": "LICENSE"},
                    {"id": "stats-3", "op": "stats"},
                    {"id": "bad-4", "op": "nope"},
                ]
            ),
            out.append,
        )
    assert counts == {"requests": 4, "responses": 4}
    rows = [json.loads(line) for line in out]
    assert [r["id"] for r in rows] == ["dice-1", "exact-2", "stats-3", "bad-4"]
    assert (rows[0]["key"], rows[0]["matcher"]) == ("mit", "dice")
    assert (rows[1]["key"], rows[1]["matcher"]) == ("mit", "exact")
    # the stats verb snapshots AFTER every earlier request answered
    assert rows[2]["stats"]["scheduler"]["completed"] == 2
    assert rows[2]["stats"]["latency_ms"]["total"]["count"] == 2
    assert rows[3]["error"].startswith("bad_request")


def test_session_surfaces_backpressure(clf, mit_body):
    b = MicroBatcher(
        classifier=clf, queue_depth=1, max_delay_ms=5.0, buckets=(4,),
        start=False,
    )
    out: list[str] = []
    session = _Session(b, out.append)
    session.handle_line(json.dumps(
        {"id": 1, "content": dice_blob(mit_body, "bp1"),
         "filename": "LICENSE"}
    ))
    session.handle_line(json.dumps(
        {"id": 2, "content": dice_blob(mit_body, "bp2"),
         "filename": "LICENSE"}
    ))
    b.start()  # only now can request 1 answer
    session.finish()
    b.close()
    rows = [json.loads(line) for line in out]
    assert [r["id"] for r in rows] == [1, 2]
    assert rows[0]["key"] == "mit"
    assert rows[1]["error"] == "queue_full"
    assert rows[1]["retry_after"] > 0


def test_session_rejects_malformed_lines(clf, mit_body):
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        out: list[str] = []
        serve_session(
            b,
            [
                "not json",
                json.dumps({"id": 7}),
                json.dumps([1, 2]),
                json.dumps({"id": 8, "content": "x", "filename": 5}),
                json.dumps(
                    {"id": 9, "content": "x", "deadline_ms": "100"}
                ),
                json.dumps({"id": 10, "content": "x", "deadline_ms": -1}),
                # the session survives every bad line above and still
                # answers a good request
                json.dumps({"id": 11, "content": mit_body,
                            "filename": "LICENSE"}),
            ],
            out.append,
        )
    rows = [json.loads(line) for line in out]
    assert all("bad_request" in r["error"] for r in rows[:6])
    assert rows[1]["id"] == 7
    assert (rows[6]["id"], rows[6]["key"]) == (11, "mit")


def test_unix_socket_transport(clf, mit_body, tmp_path):
    path = str(tmp_path / "serve.sock")
    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        server = UnixServer(path, b)
        thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        thread.start()
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.connect(path)
                f = s.makefile("rwb")
                for row in (
                    {"id": 1, "content": dice_blob(mit_body, "ux"),
                     "filename": "LICENSE"},
                    {"id": 2, "content": dice_blob(mit_body, "ux"),
                     "filename": "LICENSE"},
                    {"id": 3, "op": "stats"},
                ):
                    f.write(json.dumps(row).encode() + b"\n")
                f.flush()
                rows = [json.loads(f.readline()) for _ in range(3)]
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)
    assert rows[0]["key"] == "mit"
    assert rows[1]["key"] == "mit" and rows[1]["cached"]
    sched = rows[2]["stats"]["scheduler"]
    assert sched["device_rows"] == 1  # the duplicate never hit the device


# -- the diff verb (normalized blob vs template word diff) --


def test_diff_verb_roundtrips_over_worker_socket(clf, mit_body, tmp_path):
    """The acceptance drill: {"op":"diff"} over a real worker socket —
    closest-template pick, named-license pick, and the
    unknown_license refusal, all on one session."""
    path = str(tmp_path / "diff.sock")
    blob = mit_body + "\nan extra tail clause\n"
    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        server = UnixServer(path, b)
        thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        thread.start()
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.connect(path)
                f = s.makefile("rwb")
                for row in (
                    {"id": 1, "op": "diff", "content": blob,
                     "filename": "LICENSE"},
                    {"id": 2, "op": "diff", "content": mit_body,
                     "license": "mit"},
                    {"id": 3, "op": "diff", "content": blob,
                     "license": "not-a-license"},
                    {"id": 4, "op": "diff"},
                ):
                    f.write(json.dumps(row).encode() + b"\n")
                f.flush()
                rows = [json.loads(f.readline()) for _ in range(4)]
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)
    by_id = {r["id"]: r for r in rows}
    closest = by_id[1]["diff"]
    assert closest["key"] == "mit"
    assert not closest["identical"]
    assert "{+an extra tail clause+}" in closest["diff"]
    assert 0.0 < closest["similarity"] <= 100.0
    named = by_id[2]["diff"]
    assert named["key"] == "mit" and named["identical"]
    assert named["diff"] == ""
    assert by_id[3]["error"].startswith("unknown_license")
    assert by_id[4]["error"].startswith("bad_request")


def test_word_diff_replace_matches_git_inline_form():
    from licensee_tpu.normalize.worddiff import word_diff

    # git --word-diff renders a replaced run as one adjacent pair
    assert word_diff("a b c", "a x c") == "a [-b-]{+x+} c"
    assert word_diff("a b", "a") == "a [-b-]"
    assert word_diff("a", "a b") == "a {+b+}"
    assert word_diff("same", "same") == "same"


def test_diff_payload_fenced_to_the_serving_corpus():
    """The blue/green fence: the diff verb must never rank or validate
    against a template outside the LIVE corpus — the diff and the
    verdict name the same epoch or the verb refuses."""
    from licensee_tpu.corpus.compiler import CompiledCorpus
    from licensee_tpu.corpus.license import License
    from licensee_tpu.serve.diffverb import (
        UnknownLicenseError,
        diff_payload,
    )

    isc_only = CompiledCorpus.compile([License.find("isc")])
    mit_text = re.sub(
        r"\[(\w+)\]", "example", License.find("mit").content or ""
    )
    # a key the corpus does not serve refuses, even though the vendored
    # pool knows it
    with pytest.raises(UnknownLicenseError):
        diff_payload(mit_text, "LICENSE", "mit", corpus=isc_only)
    # closest-mode never picks an out-of-pool template: MIT text ranks
    # mit first in the vendored pool, but the fence yields isc
    row = diff_payload(mit_text, "LICENSE", corpus=isc_only)
    assert row["key"] == "isc"
    # no corpus (corpusless/package-mode worker): vendored pool intact
    assert diff_payload(mit_text, "LICENSE")["key"] == "mit"


def test_diff_verb_validates_fields(clf, mit_body):
    out: list[str] = []
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        serve_session(
            b,
            [
                json.dumps({"id": 1, "op": "diff", "content": "x",
                            "license": 7}),
                json.dumps({"id": 2, "op": "diff", "content": "x",
                            "filename": 7}),
                json.dumps({"id": 3, "op": "diff",
                            "content_b64": "%%%not-base64%%%"}),
                json.dumps({"id": 4, "op": "diff",
                            "content": "x" * (64 * 1024 + 1)}),
            ],
            out.append,
        )
    rows = [json.loads(line) for line in out]
    assert all(r["error"].startswith("bad_request") for r in rows)
    # the 64 KiB MAX_LICENSE_SIZE cap bounds the word-diff's cost too
    assert "64 KiB" in rows[3]["error"]


# -- the shared featurize helper (offline/online drift guard) --


def test_featurize_request_matches_offline_keys(mit_body):
    """The serve cache and the offline dedupe cache share one key
    function; pin the shape so neither can drift silently."""
    from licensee_tpu.serve.featurize import content_key, dispatch_key

    assert dispatch_key("license", "LICENSE") == ("license", False)
    assert dispatch_key("license", "license.html") == ("license", True)
    assert dispatch_key("package", "Cargo.toml") == ("package", "Cargo.toml")
    key = content_key("license", "LICENSE", b"hello")
    assert key[0] == ("license", False)
    assert len(key[1]) == 20  # sha1 digest

    # attribution folds the copyright? filename gate into the key
    with_attr = dispatch_key("license", "COPYRIGHT", attribution=True)
    without = dispatch_key("license", "LICENSE", attribution=True)
    assert with_attr != without


def test_batch_project_reexports_shared_helpers():
    """batch_project's long-standing private names now alias the shared
    serve/featurize implementations — one definition for both paths."""
    from licensee_tpu.projects import batch_project
    from licensee_tpu.serve import featurize

    assert batch_project._produce_batch is featurize.produce_batch
    assert batch_project._read_capped is featurize.read_capped
    assert batch_project._jsonl_row is featurize.jsonl_row
    assert batch_project._IN_BATCH_DUP is featurize.IN_BATCH_DUP
    assert batch_project._UNROUTED is featurize.UNROUTED


# -- observability: trace propagation + the extended stats verb --


def test_every_response_row_carries_its_requests_trace_id(clf, mit_body):
    """A serve JSONL session: every response row echoes the trace ID
    minted for ITS request — device-scored, exact-prefiltered, and
    cache-hit rows alike, each with a distinct id."""
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), trace_sample=1.0,
    ) as b:
        out: list[str] = []
        serve_session(
            b,
            _session_lines(
                [
                    {"id": 1, "content": dice_blob(mit_body, "tp1"),
                     "filename": "LICENSE"},
                    {"id": 2, "content": mit_body, "filename": "LICENSE"},
                    {"id": 3, "content": dice_blob(mit_body, "tp1"),
                     "filename": "LICENSE"},  # cache hit (or coalesce)
                ]
            ),
            out.append,
        )
    rows = [json.loads(line) for line in out]
    traces = [r.get("trace") for r in rows]
    assert all(re.fullmatch(r"[0-9a-f]{16}", t) for t in traces)
    assert len(set(traces)) == 3  # one trace per request, even dupes
    assert rows[2]["cached"]


def test_queue_full_row_carries_trace_id(clf, mit_body):
    b = MicroBatcher(
        classifier=clf, queue_depth=1, max_delay_ms=5.0, buckets=(4,),
        start=False, trace_sample=1.0,
    )
    out: list[str] = []
    session = _Session(b, out.append)
    session.handle_line(json.dumps(
        {"id": 1, "content": dice_blob(mit_body, "tq1"),
         "filename": "LICENSE"}
    ))
    session.handle_line(json.dumps(
        {"id": 2, "content": dice_blob(mit_body, "tq2"),
         "filename": "LICENSE"}
    ))
    b.start()
    session.finish()
    b.close()
    rows = [json.loads(line) for line in out]
    assert rows[1]["error"] == "queue_full"
    assert re.fullmatch(r"[0-9a-f]{16}", rows[1]["trace"])
    assert rows[1]["trace"] != rows[0]["trace"]
    # the rejected request's trace was retained with queue_full status
    statuses = {t["status"] for t in b.trace_tail(10)}
    assert "queue_full" in statuses


def test_scalar_fallback_row_carries_trace_with_all_five_spans(
    clf, mit_body
):
    """A device failure routes through the scalar fallback: the
    response still carries the trace id, and the retained trace holds
    the full five-span story (cache_probe, featurize, queue_wait,
    device, fallback)."""
    blob = dice_blob(mit_body, "tfb")
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,),
        trace_sample=1.0, trace_slow_ms=0.0,
    ) as b:
        # the flush path's device seam is the ASYNC submit now
        original = b.classifier.dispatch_chunks_async

        def broken(*args, **kwargs):
            raise RuntimeError("injected device failure")

        b.classifier.dispatch_chunks_async = broken
        try:
            out: list[str] = []
            serve_session(
                b,
                _session_lines(
                    [{"id": 1, "content": blob, "filename": "LICENSE"}]
                ),
                out.append,
            )
        finally:
            b.classifier.dispatch_chunks_async = original
        row = json.loads(out[0])
        assert (row["key"], row["matcher"]) == ("mit", "dice")
        trace = next(
            t for t in b.trace_tail(10) if t["trace"] == row["trace"]
        )
    names = [s["name"] for s in trace["spans"]]
    assert names == [
        "cache_probe", "featurize", "queue_wait", "device", "fallback"
    ]
    device_span = trace["spans"][3]
    assert "error" in device_span.get("note", "")


def test_stats_verb_reports_gauges_and_uptime(clf, mit_body):
    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        b.classify(mit_body, "LICENSE")
        stats = b.stats()
    sched = stats["scheduler"]
    assert sched["queue_depth"] == 0
    assert sched["in_flight"] == 0
    assert isinstance(stats["uptime_s"], float) and stats["uptime_s"] >= 0
    assert stats["tracing"]["started"] == 1
    # the compile/execute split rides along (cumulative per classifier,
    # which this module shares across tests — so shape only)
    assert {"compiles", "compile_s", "dispatches", "dispatch_s",
            "shapes"} <= set(stats["device"])
    assert stats["config"]["trace_sample"] == 0.01


def test_stats_verb_prometheus_format_parses(clf, mit_body):
    from licensee_tpu.obs import check_exposition

    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        out: list[str] = []
        serve_session(
            b,
            _session_lines(
                [
                    {"id": 1, "content": dice_blob(mit_body, "prom"),
                     "filename": "LICENSE"},
                    {"id": 2, "op": "stats", "format": "prometheus"},
                    {"id": 3, "op": "trace", "n": 5},
                    {"id": 4, "op": "stats", "format": "nope"},
                ]
            ),
            out.append,
        )
    rows = [json.loads(line) for line in out]
    text = rows[1]["prometheus"]
    assert check_exposition(text) == []
    assert 'serve_requests_total{event="submitted"} 1' in text
    assert "serve_queue_depth 0" in text
    assert "serve_stage_seconds_bucket" in text
    # the classifier is module-shared so the compile COUNT is
    # cumulative; the family itself must be present and synced
    assert 'device_dispatch_total{phase="compile"}' in text
    assert "process_uptime_seconds" in text
    assert isinstance(rows[2]["traces"], list)
    assert rows[3]["error"].startswith("bad_request")


def test_tracing_disabled_omits_trace_fields(clf, mit_body):
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), tracing=False,
    ) as b:
        out: list[str] = []
        serve_session(
            b,
            _session_lines(
                [{"id": 1, "content": dice_blob(mit_body, "notrace"),
                  "filename": "LICENSE"}]
            ),
            out.append,
        )
        assert b.trace_tail(10) == []
    row = json.loads(out[0])
    assert row["key"] == "mit"
    assert "trace" not in row


def test_registry_absorbs_cache_and_flush_counters(clf, mit_body):
    """One registry scrape carries the scheduler, cache, AND stage
    reservoir families — the three former islands behind one snapshot."""
    blob = dice_blob(mit_body, "absorb")
    with MicroBatcher(classifier=clf, max_delay_ms=5.0, buckets=(4,)) as b:
        b.classify(blob, "LICENSE")
        b.classify(blob, "LICENSE")  # cache hit
        snap = b.obs.registry.snapshot()

    def value(name, **labels):
        for s in snap[name]["samples"]:
            if s["labels"] == labels:
                return s["value"]
        return None

    assert value("serve_requests_total", event="submitted") == 2
    assert value("serve_requests_total", event="cache_hits") == 1
    assert value("serve_cache_events_total", event="hits") == 1
    assert value("serve_flush_total", reason="deadline") == 1
    assert value("serve_bucket_flush_total", bucket="4") == 1
    hist = value("serve_stage_seconds", stage="total")
    assert hist["count"] == 2


# -- stale-socket reclaim (fleet satellite: rebind after SIGKILL) --


def test_unix_server_reclaims_stale_socket(clf, tmp_path):
    """A SIGKILLed worker leaves its socket file behind; a restarted
    worker must bind over the STALE file instead of dying with
    EADDRINUSE (the supervisor restart path depends on this)."""
    path = str(tmp_path / "serve.sock")
    stale = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    stale.bind(path)  # bound but never accepting: a dead owner's file
    stale.close()
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        server = UnixServer(path, b)  # must not raise
        server.server_close()


def test_unix_server_refuses_live_socket(clf, tmp_path):
    """The flip side: a LIVE server's socket must never be unlinked —
    binding over it would silently hijack a running worker."""
    from licensee_tpu.serve.server import SocketInUseError

    path = str(tmp_path / "serve.sock")
    owner = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    owner.bind(path)
    owner.listen(1)
    try:
        with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
            with pytest.raises(SocketInUseError):
                UnixServer(path, b)
        assert os.path.exists(path)  # the live socket survived
    finally:
        owner.close()


def test_unix_server_refuses_non_socket_path(clf, tmp_path):
    from licensee_tpu.serve.server import SocketInUseError

    path = tmp_path / "serve.sock"
    path.write_text("precious user data")
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        with pytest.raises(SocketInUseError):
            UnixServer(str(path), b)
    assert path.read_text() == "precious user data"


# -- trace adoption (fleet satellite: router -> worker propagation) --


def test_session_adopts_upstream_trace_id(clf, mit_body):
    """A request line carrying a 16-hex "trace" field (the fleet
    router's) must answer under THAT ID and retain it in the worker's
    own tail — the cross-process join."""
    upstream = "deadbeef00c0ffee"
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,), trace_sample=1.0,
    ) as b:
        out: list[str] = []
        serve_session(
            b,
            [json.dumps({
                "id": 1, "content": dice_blob(mit_body, "adopt"),
                "filename": "LICENSE", "trace": upstream,
            })],
            out.append,
        )
        row = json.loads(out[0])
        assert row["key"] == "mit"
        assert row["trace"] == upstream
        assert upstream in {t["trace"] for t in b.trace_tail(10)}


def test_session_rejects_malformed_trace_field(clf, mit_body):
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        out: list[str] = []
        serve_session(
            b,
            [
                json.dumps({"id": 1, "content": "x", "trace": "nope"}),
                json.dumps({"id": 2, "content": "x", "trace": 42}),
                json.dumps({"id": 3, "content": "x",
                            "trace": "DEADBEEF00C0FFEE"}),  # uppercase
            ],
            out.append,
        )
    rows = [json.loads(line) for line in out]
    assert all("bad_request" in r["error"] for r in rows)


# -- ResultCache byte bound (fleet satellite: bounded worker memory) --


def _fat_result(n_closest: int = 0):
    from licensee_tpu.kernels.batch import BlobResult

    closest = [(f"lic-{i}", 50.0 + i) for i in range(n_closest)] or None
    return BlobResult("mit", "dice", 99.0, closest=closest)


def test_result_cache_byte_accounting_tracks_entries():
    from licensee_tpu.serve.cache import ResultCache, result_bytes

    cache = ResultCache(capacity=100, max_bytes=100_000)
    r = _fat_result(3)
    cache.put("a", r)
    frozen = cache.get("a")
    assert cache.bytes == result_bytes("a", frozen)
    cache.put("b", r)
    assert cache.bytes == 2 * result_bytes("a", frozen)
    # replacing a key re-accounts instead of double-counting
    cache.put("a", _fat_result(0))
    assert cache.bytes == result_bytes("a", frozen) + result_bytes(
        "a", cache.get("a")
    )
    stats = cache.stats()
    assert stats["bytes"] == cache.bytes
    assert stats["max_bytes"] == 100_000


def test_result_cache_evicts_lru_by_bytes_not_count():
    from licensee_tpu.serve.cache import ResultCache, result_bytes

    r = _fat_result(4)
    one = result_bytes("k", r)
    # room for ~3 fat entries, far below the 1000-entry count bound
    cache = ResultCache(capacity=1000, max_bytes=3 * one + one // 2)
    for key in ("a", "b", "c"):
        cache.put(key, r)
    assert cache.evictions == 0
    cache.get("a")  # a is now most-recent: LRU order b, c, a
    cache.put("d", r)  # over budget: evicts "b", the LRU
    assert cache.get("b") is None
    assert cache.get("a") is not None
    assert cache.evictions == 1
    assert cache.bytes <= cache.max_bytes
    assert len(cache) == 3


def test_result_cache_rejects_oversized_entry_without_wiping():
    from licensee_tpu.serve.cache import ResultCache, result_bytes

    small = _fat_result(0)
    cache = ResultCache(capacity=10, max_bytes=result_bytes("k", small) * 2)
    cache.put("keep", small)
    huge = _fat_result(500)  # alone bigger than the whole budget
    cache.put("huge", huge)
    assert cache.get("huge") is None  # refused
    assert cache.get("keep") is not None  # and nothing was evicted for it
    assert len(cache) == 1


def test_result_cache_max_bytes_zero_and_validation():
    from licensee_tpu.serve.cache import ResultCache

    with pytest.raises(ValueError):
        ResultCache(capacity=10, max_bytes=-1)
    cache = ResultCache(capacity=10, max_bytes=0)
    cache.put("a", _fat_result(0))
    assert cache.get("a") is None  # a 0-byte budget stores nothing


def test_micro_batcher_wires_cache_bytes(clf):
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, cache_bytes=4096
    ) as b:
        assert b.cache.max_bytes == 4096
        assert b.stats()["config"]["cache_bytes"] == 4096
        assert b.stats()["cache"]["max_bytes"] == 4096


# -- corpus lifecycle: blue/green reload, cache fencing --


@pytest.fixture(scope="module")
def spdx_artifact(tmp_path_factory):
    """A corpus artifact with a fingerprint distinct from vendored."""
    from licensee_tpu.corpus.artifact import write_artifact
    from licensee_tpu.corpus.spdx import spdx_corpus

    path = str(tmp_path_factory.mktemp("corpus") / "spdx.corpus.npz")
    write_artifact(path, spdx_corpus(None), source="spdx")
    return path


def test_reload_swaps_corpus_and_fences_cache(clf, mit_body, spdx_artifact):
    """The satellite regression: a reload must never serve a pre-swap
    cached verdict — the cache key is fenced by corpus fingerprint."""
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, corpus_source="vendored"
    ) as b:
        fp_old = b.corpus_fingerprint
        assert fp_old
        blob = dice_blob(mit_body, "reload")
        first = b.classify(blob, "LICENSE")
        assert first.key == "mit"
        rq = b.submit(blob, "LICENSE")
        rq.wait(60.0)
        assert rq.cached  # pre-swap repeat serves from cache

        out = b.reload_corpus(spdx_artifact)
        assert out["ok"]
        fp_new = out["fingerprint"]
        assert fp_new != fp_old
        assert out["previous"] == fp_old
        assert b.corpus_fingerprint == fp_new
        assert b.classifier.corpus.n_templates == 47

        # the first post-swap repeat must RE-SCORE, not answer from the
        # pre-swap cache...
        post = b.submit(blob, "LICENSE")
        res = post.wait(60.0)
        assert not post.cached
        assert res.key == "mit"  # ...and re-validate under the new corpus
        assert post.corpus_fp == fp_new
        # ...and the new epoch caches normally from then on
        post2 = b.submit(blob, "LICENSE")
        post2.wait(60.0)
        assert post2.cached

        stats = b.stats()
        assert stats["scheduler"]["reloads"] == 1
        assert stats["corpus"]["fingerprint"] == fp_new
        assert stats["corpus"]["source"] == spdx_artifact
        # the obs surface: the fingerprint gauge labels both epochs,
        # 1 on the serving one, 0 on the retired one
        exposition = b.prometheus()
        assert (
            f'serve_corpus_info{{fingerprint="{fp_new[:12]}"}} 1'
            in exposition
        )
        assert (
            f'serve_corpus_info{{fingerprint="{fp_old[:12]}"}} 0'
            in exposition
        )


def test_scalar_fallback_scores_against_admitted_corpus(
    clf, mit_body, spdx_artifact
):
    """A device failure AFTER a reload must fall back to the admitted
    corpus epoch, not the vendored pool: the verdict must come from the
    corpus the response's fingerprint names, at device-identical
    confidence (the fallback runs the same score algebra on the host)."""
    with MicroBatcher(
        classifier=clf, max_delay_ms=5.0, buckets=(4,),
        corpus_source="vendored",
    ) as b:
        fp_new = b.reload_corpus(spdx_artifact)["fingerprint"]
        blob = dice_blob(mit_body, "fallback-epoch")
        expected = b.classifier.classify_blobs([blob])[0]
        assert (expected.key, expected.matcher) == ("mit", "dice")
        new_clf = b.classifier
        # the flush path's device seam is the ASYNC submit now
        original = new_clf.dispatch_chunks_async

        def broken(*args, **kwargs):
            raise RuntimeError("injected device failure")

        new_clf.dispatch_chunks_async = broken
        try:
            rq = b.submit(blob, "LICENSE")
            res = rq.wait(60.0)
        finally:
            new_clf.dispatch_chunks_async = original
        assert (res.key, res.matcher) == ("mit", "dice")
        assert res.confidence == expected.confidence
        assert rq.corpus_fp == fp_new
        assert b.stats()["scheduler"]["fallbacks"] == 1


def test_reload_rejects_bad_sources_and_keeps_serving(
    clf, mit_body, tmp_path
):
    from licensee_tpu.serve.reload import ReloadRejectedError

    corrupt = tmp_path / "bad.corpus.npz"
    corrupt.write_bytes(b"this is not an artifact")
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        fp = b.corpus_fingerprint
        with pytest.raises(ReloadRejectedError, match="cannot load"):
            b.reload_corpus(str(corrupt))
        with pytest.raises(ReloadRejectedError, match="cannot load"):
            b.reload_corpus(str(tmp_path / "missing.npz"))
        assert b.corpus_fingerprint == fp  # old corpus still serving
        assert b.classify(mit_body, "LICENSE").key == "mit"
        assert b.stats()["scheduler"]["reload_failed"] == 2


def test_reload_validation_gate_refuses(clf, monkeypatch):
    import licensee_tpu.serve.reload as reload_mod

    monkeypatch.setattr(
        reload_mod, "validate_classifier",
        lambda c: ["injected validation failure"],
    )
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        fp = b.corpus_fingerprint
        with pytest.raises(
            reload_mod.ReloadRejectedError, match="injected"
        ):
            b.reload_corpus("vendored")
        assert b.corpus_fingerprint == fp
        assert b.stats()["scheduler"]["reload_failed"] == 1


def test_concurrent_reload_rejected_deterministically(clf, monkeypatch):
    """The satellite: a second reload while one is compiling is
    REJECTED (never queued, never interleaved), and the first completes
    unharmed."""
    import licensee_tpu.serve.reload as reload_mod

    started, release = threading.Event(), threading.Event()
    real_build = reload_mod.build_classifier_like

    def slow_build(template, source, method=None):
        started.set()
        assert release.wait(30.0)
        return real_build(template, source, method=method)

    monkeypatch.setattr(reload_mod, "build_classifier_like", slow_build)
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        results = {}

        def first():
            try:
                results["first"] = b.reload_corpus("vendored")
            except Exception as exc:  # pragma: no cover - failure detail
                results["first"] = exc

        t = threading.Thread(target=first)
        t.start()
        assert started.wait(10.0)
        with pytest.raises(reload_mod.ReloadInProgressError):
            b.reload_corpus("vendored")
        assert b.stats()["scheduler"]["reload_rejected"] == 1
        release.set()
        t.join(30.0)
        assert isinstance(results["first"], dict)
        assert results["first"]["ok"]
        # same source, same corpus: the swap is a no-op fingerprint-wise
        assert results["first"]["unchanged"]
        assert b.stats()["scheduler"]["reloads"] == 1


def test_reload_verb_over_session(clf, mit_body, tmp_path):
    """The wire surface: bad requests cost error rows, a failed reload
    reports reload_failed, and classification rows carry the corpus
    fingerprint — all in request order."""
    lines = [
        json.dumps({"id": 1, "op": "reload"}),  # missing corpus
        json.dumps({
            "id": 2, "op": "reload",
            "corpus": str(tmp_path / "nonexistent.npz"),
        }),
        json.dumps({"id": 3, "content": mit_body, "filename": "LICENSE"}),
    ]
    out = []
    with MicroBatcher(classifier=clf, max_delay_ms=5.0) as b:
        serve_session(b, lines, lambda line: out.append(json.loads(line)))
        fp = b.corpus_fingerprint
    assert [row["id"] for row in out] == [1, 2, 3]
    assert "bad_request" in out[0]["error"]
    assert out[1]["error"].startswith("reload_failed")
    assert out[1]["problems"]
    assert out[2]["key"] == "mit"
    assert out[2]["corpus"] == fp[:12]


def test_reload_rejected_for_corpusless_mode():
    from licensee_tpu.serve.reload import ReloadRejectedError

    pkg_clf = BatchClassifier(mode="package", mesh=None)
    with MicroBatcher(classifier=pkg_clf, max_delay_ms=5.0) as b:
        assert b.corpus_fingerprint is None
        with pytest.raises(ReloadRejectedError, match="host-only"):
            b.reload_corpus("vendored")
