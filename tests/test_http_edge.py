"""The network edge's protocol corners (PR 13): HTTP/1.1 keep-alive
framing over TCP against a live stub fleet — header dribble, oversized
bodies, invalid requests mid-pipeline, auth and rate-limit refusals,
backpressure translation — plus the TCP wire lift (parse_target,
TCP_NODELAY, ECONNREFUSED-vs-EAGAIN) and the 2-domain federation
drill."""

import json
import math
import os
import socket
import sys
import tempfile
import threading
import time

import pytest

from licensee_tpu.fleet import faults
from licensee_tpu.fleet.http_edge import HttpEdgeServer, _TokenBucket
from licensee_tpu.fleet.router import Router
from licensee_tpu.fleet.supervisor import Supervisor, worker_env
from licensee_tpu.fleet.wire import (
    Connection,
    WireError,
    json_str_field,
    oneshot,
)
from licensee_tpu.serve.eventloop import parse_target

TOKEN = "test-edge-token"


def _stub_argv(extra=()):
    def argv(name, sock):
        return [
            sys.executable, "-m", "licensee_tpu.fleet.faults",
            "--socket", sock, "--name", name, "--service-ms", "1",
            *extra,
        ]

    return argv


class _Fleet:
    """One stub fleet + router + HTTP edge on loopback TCP, torn down
    in reverse order."""

    def __init__(self, n_workers=1, stub_args=(), edge_kwargs=None,
                 worker_tcp=False):
        self.tmp = tempfile.mkdtemp(prefix="licensee-edge-test-")
        if worker_tcp:
            self.sockets = {
                f"w{i}": f"127.0.0.1:{_free_port()}"
                for i in range(n_workers)
            }
        else:
            self.sockets = {
                f"w{i}": os.path.join(self.tmp, f"w{i}.sock")
                for i in range(n_workers)
            }
        self.supervisor = Supervisor(
            self.sockets, argv_for=_stub_argv(stub_args),
            env_for=lambda name, chips: worker_env(None, None),
            probe_interval_s=0.1, backoff_base_s=0.1, backoff_max_s=1.0,
        )
        self.supervisor.start()
        assert self.supervisor.wait_healthy(30.0)
        self.router = Router(
            self.sockets, supervisor=self.supervisor,
            probe_interval_s=0.1, request_timeout_s=10.0,
            dispatch_wait_s=5.0, trace_sample=0.0,
        )
        self.router.start()
        kwargs = {"tokens": {TOKEN: "tester"},
                  "rate_per_client": 10000.0,
                  "stall_timeout_s": 1.0}
        kwargs.update(edge_kwargs or {})
        self.edge = HttpEdgeServer("127.0.0.1:0", self.router, **kwargs)
        self.port = self.edge.bound_port
        self.thread = threading.Thread(
            target=self.edge.serve_forever,
            kwargs={"poll_interval": 0.05}, daemon=True,
        )
        self.thread.start()

    def close(self):
        self.edge.shutdown()
        self.edge.server_close()
        self.thread.join(timeout=5.0)
        self.router.close()
        self.supervisor.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _free_port() -> int:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]
    finally:
        sock.close()


def _connect(port, timeout=10.0):
    sock = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


def _request(body: bytes, token=TOKEN, path="/classify",
             method="POST", headers=()) -> bytes:
    lines = [f"{method} {path} HTTP/1.1", "Host: edge"]
    if token:
        lines.append(f"Authorization: Bearer {token}")
    lines.append(f"Content-Length: {len(body)}")
    lines.extend(headers)
    return ("\r\n".join(lines) + "\r\n\r\n").encode() + body


def _read_response(reader):
    """(status, headers, body) off a buffered socket reader; None at
    EOF."""
    status_line = reader.readline()
    if not status_line:
        return None
    code = int(status_line.split(b" ")[1])
    headers = {}
    while True:
        line = reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode().partition(":")
        headers[name.strip().lower()] = value.strip()
    n = int(headers.get("content-length", "0"))
    body = reader.read(n) if n else b""
    return code, headers, body


def _roundtrip(port, raw: bytes, n_responses=1, timeout=15.0):
    sock = _connect(port, timeout)
    try:
        sock.sendall(raw)
        reader = sock.makefile("rb")
        out = []
        for _ in range(n_responses):
            resp = _read_response(reader)
            if resp is None:
                break
            out.append(resp)
        reader.close()
        return out
    finally:
        sock.close()


# -- wire / transport lift ---------------------------------------------


def test_parse_target_grammar():
    assert parse_target("/tmp/x.sock") == ("unix", "/tmp/x.sock")
    assert parse_target("w0.sock") == ("unix", "w0.sock")
    assert parse_target("127.0.0.1:7001") == ("tcp", ("127.0.0.1", 7001))
    assert parse_target("host:0") == ("tcp", ("host", 0))
    # a path containing a colon stays a path
    assert parse_target("dir/w:1")[0] == "unix"
    assert parse_target(":123")[0] == "unix"


def test_wire_refused_kind_on_dead_tcp_host():
    port = _free_port()  # leased then released: provably refused
    with pytest.raises(WireError) as exc:
        Connection(f"127.0.0.1:{port}", 2.0)
    assert exc.value.kind == "refused"


def test_wire_tcp_connection_sets_nodelay_and_round_trips():
    port = _free_port()
    target = f"127.0.0.1:{port}"
    proc = None
    import subprocess

    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "licensee_tpu.fleet.faults",
             "--socket", target, "--name", "tcpstub"],
            stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        deadline = time.perf_counter() + 20.0
        row = None
        while time.perf_counter() < deadline:
            try:
                row = oneshot(target, {"op": "stats"}, 2.0)
                break
            except WireError:
                time.sleep(0.1)
        assert row is not None and row["stats"]["worker"] == "tcpstub"
        conn = Connection(target, 2.0)
        try:
            assert conn._sock.getsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY
            )
            row = conn.request(json.dumps({"op": "stats"}), 2.0)
            assert "stats" in row
        finally:
            conn.close()
    finally:
        if proc is not None:
            proc.kill()
            proc.wait(timeout=10.0)


def test_json_str_field_extraction():
    assert json_str_field('{"trace": "ab12", "x": 1}', "trace") == "ab12"
    assert json_str_field('{"trace":"ab12"}', "trace") == "ab12"
    assert json_str_field('{"other": "y"}', "trace") is None
    # client-controlled escaped text cannot forge the pattern
    assert json_str_field(
        json.dumps({"id": '{"trace":"evil"}'}), "trace"
    ) is None


def test_token_bucket_refill_horizon():
    bucket = _TokenBucket(rate=10.0, burst=2.0)
    assert bucket.take() == 0.0
    assert bucket.take() == 0.0
    wait = bucket.take()
    assert 0.0 < wait <= 0.1 + 1e-6
    assert math.ceil(wait) >= 1 or wait < 1


# -- edge protocol corners ---------------------------------------------


def test_classify_roundtrip_and_header_echo():
    with _Fleet() as fleet:
        body = json.dumps({"id": 7, "content": "hello edge"}).encode()
        [(code, headers, payload)] = _roundtrip(
            fleet.port, _request(body)
        )
        assert code == 200
        row = json.loads(payload)
        assert row["key"] == "stub-mit"
        assert headers.get("x-trace-id") == row["trace"]
        assert headers.get("x-corpus") == row["corpus"]


def test_pipelined_keepalive_answers_in_order():
    with _Fleet(n_workers=2) as fleet:
        raw = b"".join(
            _request(json.dumps({"id": i, "content": f"blob {i}"}).encode())
            for i in range(8)
        )
        responses = _roundtrip(fleet.port, raw, n_responses=8)
        assert [c for c, _h, _b in responses] == [200] * 8
        ids = [json.loads(b)["id"] for _c, _h, b in responses]
        assert ids == list(range(8))  # arrival order, always


def test_invalid_request_mid_pipeline_answers_then_burns():
    with _Fleet() as fleet:
        good = _request(json.dumps({"id": 1, "content": "x"}).encode())
        raw = good + b"NOT AN HTTP LINE\r\n" + good
        sock = _connect(fleet.port)
        try:
            sock.sendall(raw)
            reader = sock.makefile("rb")
            first = _read_response(reader)
            second = _read_response(reader)
            assert first is not None and first[0] == 200
            assert second is not None and second[0] == 400
            assert second[1].get("connection") == "close"
            # the third (valid) request after the burn is never parsed:
            # the connection closes instead
            assert _read_response(reader) is None
            reader.close()
        finally:
            sock.close()


def test_oversized_body_refused_413_and_burned():
    with _Fleet(edge_kwargs={"max_body_bytes": 128}) as fleet:
        body = b'{"content": "' + b"x" * 400 + b'"}'
        [(code, headers, payload)] = _roundtrip(
            fleet.port, _request(body)
        )
        assert code == 413
        assert headers.get("connection") == "close"
        assert b"bad_request" in payload


def test_http_header_dribble_slowloris_reaped_over_tcp():
    with _Fleet() as fleet:
        loris = faults.Slowloris(
            f"127.0.0.1:{fleet.port}", mode="dribble",
            byte_interval_s=0.1, give_up_s=20.0,
            payload=b"POST /classify HTTP/1.1\r\nHost: edge\r\nContent-Le",
        )
        box = {}
        thread = threading.Thread(
            target=lambda: box.update(loris.run()), daemon=True
        )
        thread.start()
        # honest traffic keeps answering while the dribbler stalls
        body = json.dumps({"id": 1, "content": "alive"}).encode()
        [(code, _h, _b)] = _roundtrip(fleet.port, _request(body))
        assert code == 200
        thread.join(timeout=30.0)
        assert box.get("reaped"), box


def test_body_dribble_slowloris_reaped():
    with _Fleet() as fleet:
        # complete headers, then a body that never finishes
        head = (
            "POST /classify HTTP/1.1\r\nHost: edge\r\n"
            f"Authorization: Bearer {TOKEN}\r\n"
            "Content-Length: 1000\r\n\r\n"
        ).encode()
        loris = faults.Slowloris(
            f"127.0.0.1:{fleet.port}", mode="dribble",
            byte_interval_s=0.1, give_up_s=20.0,
            payload=head + b'{"content": "never finished',
        )
        box = loris.run()
        assert box.get("reaped"), box


def test_auth_failure_401():
    with _Fleet() as fleet:
        body = json.dumps({"content": "x"}).encode()
        [(code, headers, payload)] = _roundtrip(
            fleet.port, _request(body, token="wrong")
        )
        assert code == 401
        assert headers.get("www-authenticate") == "Bearer"
        [(code, _h, _b)] = _roundtrip(
            fleet.port, _request(body, token=None)
        )
        assert code == 401
        # healthz stays unauthenticated (load-balancer probes)
        [(code, _h, payload)] = _roundtrip(
            fleet.port,
            _request(b"", token=None, path="/healthz", method="GET"),
        )
        assert code == 200 and json.loads(payload)["ok"] is True


def test_rate_limit_429_with_retry_after():
    with _Fleet(
        edge_kwargs={"rate_per_client": 2.0, "burst": 2.0}
    ) as fleet:
        body = json.dumps({"content": "x"}).encode()
        raw = b"".join(_request(body) for _ in range(5))
        responses = _roundtrip(fleet.port, raw, n_responses=5)
        codes = [c for c, _h, _b in responses]
        assert codes[:2] == [200, 200]
        assert set(codes[2:]) == {429}
        throttled = responses[2]
        assert int(throttled[1]["retry-after"]) >= 1
        assert b"queue_full" in throttled[2]


def test_queue_full_backpressure_maps_to_429():
    with _Fleet(stub_args=("--queue-full",)) as fleet:
        body = json.dumps({"content": "x"}).encode()
        [(code, headers, payload)] = _roundtrip(
            fleet.port, _request(body)
        )
        assert code == 429
        assert int(headers["retry-after"]) >= 1
        assert json.loads(payload)["error"] == "queue_full"


def test_router_shutdown_maps_to_503():
    fleet = _Fleet()
    try:
        # put the router into its closing state WITHOUT stopping the
        # shared loop (the edge rides it): exactly the in-flight
        # shutdown window the 503 translation covers
        fleet.router.loop.run_sync(fleet.router._shutdown_on_loop)
        body = json.dumps({"content": "x"}).encode()
        [(code, _h, payload)] = _roundtrip(fleet.port, _request(body))
        assert code == 503
        assert b"router_closed" in payload
        # and healthz says so too
        [(code, _h, payload)] = _roundtrip(
            fleet.port,
            _request(b"", token=None, path="/healthz", method="GET"),
        )
        assert code == 503 and json.loads(payload)["ok"] is False
    finally:
        fleet.close()


def test_unknown_route_404_and_wrong_method_405_keep_alive():
    with _Fleet() as fleet:
        ok = _request(json.dumps({"content": "x"}).encode())
        raw = (
            _request(b"", path="/nope", method="GET")
            + _request(b'{"content": "x"}', path="/classify",
                       method="GET")
            + ok
        )
        responses = _roundtrip(fleet.port, raw, n_responses=3)
        assert [c for c, _h, _b in responses] == [404, 405, 200]


def test_empty_body_is_400_keep_alive():
    with _Fleet() as fleet:
        raw = _request(b"") + _request(
            json.dumps({"content": "x"}).encode()
        )
        responses = _roundtrip(fleet.port, raw, n_responses=2)
        assert [c for c, _h, _b in responses] == [400, 200]


def test_metrics_endpoint_serves_merged_exposition():
    with _Fleet() as fleet:
        # a counted request first: a labeled counter family renders
        # only once a child exists
        body = json.dumps({"content": "count me"}).encode()
        [(code, _h, _b)] = _roundtrip(fleet.port, _request(body))
        assert code == 200
        [(code, headers, payload)] = _roundtrip(
            fleet.port, _request(b"", path="/metrics", method="GET"),
            timeout=20.0,
        )
        assert code == 200
        assert headers["content-type"] == "text/plain"
        text = payload.decode()
        assert "edge_http_requests_total" in text
        assert 'worker="w0"' in text


def test_drr_fair_queue_interleaves_clients():
    """Two clients, one hogging with fat bodies: DRR must not let the
    hog starve the small-body client."""
    with _Fleet(
        n_workers=1,
        stub_args=("--service-ms", "20"),
        edge_kwargs={
            "tokens": {"hog-token": "hog", "mouse-token": "mouse"},
            "max_inflight": 1,
            "quantum_bytes": 256,
        },
    ) as fleet:
        fat = json.dumps({"content": "y" * 2000}).encode()
        thin = json.dumps({"content": "z"}).encode()
        done: dict = {}

        def run(name, token, body, n):
            t0 = time.perf_counter()
            responses = _roundtrip(
                fleet.port,
                b"".join(_request(body, token=token) for _ in range(n)),
                n_responses=n, timeout=60.0,
            )
            done[name] = (
                time.perf_counter() - t0,
                [c for c, _h, _b in responses],
            )

        hog = threading.Thread(
            target=run, args=("hog", "hog-token", fat, 20), daemon=True
        )
        hog.start()
        time.sleep(0.1)  # the hog's queue is deep before the mouse asks
        run("mouse", "mouse-token", thin, 1)
        hog.join(timeout=60.0)
        assert done["mouse"][1] == [200]
        assert all(c == 200 for c in done["hog"][1])
        # the mouse waited ~one service slot, not the hog's whole queue
        assert done["mouse"][0] < done["hog"][0] / 2, done


# -- federation ---------------------------------------------------------


@pytest.mark.slow
def test_two_domain_tcp_federation_selftest():
    """The acceptance drill end to end: 2 supervisor domains over
    loopback TCP + HTTP edge, SIGKILL mid-stream, zero client-visible
    errors (fleet/selftest.py selftest_tcp — also cibuild stage 2c3)."""
    from licensee_tpu.fleet.selftest import selftest_tcp

    assert selftest_tcp(verbose=True, stub=True) == 0


def test_federated_router_fails_over_domain_errors():
    """A backend answering no_backend_available is a failed ATTEMPT at
    the tier above — failed over, never relayed (the cross-host
    contract), while a healthy single-host fleet is untouched."""
    with _Fleet(n_workers=2) as fleet:
        # front tier over ONE healthy domain + one dead target: every
        # request must answer via the healthy domain
        front_target = f"127.0.0.1:{_free_port()}"
        from licensee_tpu.fleet.router import FrontServer

        domain_front = FrontServer(front_target, fleet.router)
        dft = threading.Thread(
            target=domain_front.serve_forever,
            kwargs={"poll_interval": 0.05}, daemon=True,
        )
        dft.start()
        dead_target = f"127.0.0.1:{_free_port()}"
        front = Router(
            {"hostA": front_target, "hostB": dead_target},
            probe_interval_s=0.1, request_timeout_s=10.0,
            dispatch_wait_s=10.0, trace_sample=0.0,
            merge_label="host",
        )
        front.start()
        try:
            for i in range(10):
                row = front.dispatch({"id": i, "content": f"fed {i}"})
                assert not row.get("error"), row
                assert row["key"] == "stub-mit"
            stats = front.stats()
            # the domain router's stats expose worker-shaped scheduler
            # depth for the front tier's probed-depth math
            assert "scheduler" in fleet.router.stats()
            assert stats["backends"]["hostA"]["ok"] == 10
            exposition = front.prometheus()
            assert 'host="hostA"' in exposition
            assert 'host="hostA",worker="' in exposition
        finally:
            front.close()
            domain_front.shutdown()
            domain_front.server_close()
            dft.join(timeout=5.0)


def test_trace_adoption_across_tiers():
    """A line arriving with a valid 16-hex trace keeps it end to end —
    the federation tier's correlation contract."""
    with _Fleet() as fleet:
        row = fleet.router.dispatch(
            {"id": 1, "content": "adopt me",
             "trace": "00deadbeef00cafe"}
        )
        assert row.get("trace") == "00deadbeef00cafe"
        # an invalid trace value is NOT adopted: the router mints
        row = fleet.router.dispatch(
            {"id": 2, "content": "mint me", "trace": "nope"}
        )
        assert row.get("trace") != "nope"


def test_trace_adoption_is_top_level_only():
    """Adoption must match the worker's TOP-LEVEL parse: a nested
    "trace" occurrence (which a textual last-occurrence scan would
    grab) must not poison the pipelining cross-check — the review's
    live repro burned the pooled connection on every retry."""
    with _Fleet() as fleet:
        # nested trace AFTER the top-level one: both tiers must agree
        # on the top-level value, zero failovers
        body = json.dumps({
            "id": 1, "trace": "aaaaaaaaaaaaaaaa", "content": "x",
            "opts": {"trace": "bbbbbbbbbbbbbbbb"},
        }).encode()
        [(code, headers, payload)] = _roundtrip(
            fleet.port, _request(body)
        )
        assert code == 200
        assert json.loads(payload)["trace"] == "aaaaaaaaaaaaaaaa"
        # nested-only trace: the router must MINT (the worker adopts
        # nothing), and the response still correlates
        body = json.dumps({
            "id": 2, "content": "y",
            "opts": {"trace": "cccccccccccccccc"},
        }).encode()
        [(code, _h, payload)] = _roundtrip(fleet.port, _request(body))
        assert code == 200
        row = json.loads(payload)
        assert row["trace"] != "cccccccccccccccc"
        stats = fleet.router.stats()["router"]
        assert stats["failovers"] == 0 and stats["retries"] == 0, stats


def test_burned_session_still_answers_requests_queued_before_burn():
    """Answer-then-burn with the DRR queue backed up: requests parked
    BEFORE the invalid frame must still answer, then the 400 flushes
    and the connection closes — a burned session must not strand its
    earlier slots (review finding)."""
    with _Fleet(
        stub_args=("--service-ms", "30"),
        edge_kwargs={"max_inflight": 1},
    ) as fleet:
        good = _request(json.dumps({"content": "x"}).encode())
        raw = good + good + good + b"GARBAGE LINE\r\n"
        sock = _connect(fleet.port, timeout=30.0)
        try:
            sock.sendall(raw)
            reader = sock.makefile("rb")
            codes = []
            for _ in range(4):
                resp = _read_response(reader)
                if resp is None:
                    break
                codes.append(resp[0])
            assert codes == [200, 200, 200, 400], codes
            assert _read_response(reader) is None  # burned after
            reader.close()
        finally:
            sock.close()


def test_merge_expositions_nests_host_outside_worker():
    from licensee_tpu.obs import merge_expositions

    worker_labeled = (
        "# HELP x_total t.\n# TYPE x_total counter\n"
        'x_total{worker="w0"} 1\nx_total{worker="w1"} 2\n'
    )
    merged = merge_expositions(
        {"hostA": worker_labeled, "hostB": worker_labeled},
        label="host",
    )
    assert 'x_total{host="hostA",worker="w0"} 1' in merged
    assert 'x_total{host="hostB",worker="w1"} 2' in merged
    from licensee_tpu.obs import check_exposition

    assert check_exposition(merged) == []


def test_supervisor_host_health():
    with _Fleet(n_workers=2) as fleet:
        # wait_healthy probes the workers directly; the HEALTHY state
        # host_health() counts is stamped by the monitor thread's next
        # pass, so give that pass time to land under suite load
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            health = fleet.supervisor.host_health()
            if health["healthy"] == 2:
                break
            time.sleep(0.05)
        assert health["workers"] == 2
        assert health["healthy"] == 2
        assert health["serving"] is True
        assert fleet.router.stats()["host"]["serving"] is True
