"""Remote streaming ingest (licensee_tpu/ingest/remote.py): URL
grammar routing, loopback sha256 parity for ranged tar / ranged zip /
streaming compressed tar (including restricted spans, descriptor
re-opens, and ``--featurize-procs``), range coalescing, and the
failure model — torn bodies, retry budgets, mid-job republish fencing,
behind-window misses counted not taken, and submit-time probing.
"""

from __future__ import annotations

import gzip
import hashlib
import io
import os
import tarfile
import zipfile

import pytest

from licensee_tpu.ingest import SkippedBlob
from licensee_tpu.ingest.loopback import LoopbackBlobHost
from licensee_tpu.ingest.remote import (
    RemoteChangedError,
    RemoteError,
    RemoteProbeError,
    RemoteRetryBudgetError,
    _RemoteSeqTarContainer,
    probe_remote,
    remote_entry_kind,
)
from licensee_tpu.ingest.sources import (
    IngestError,
    ManifestExpansion,
    expand_manifest,
    expanded_layout,
    is_container_entry,
    split_entry,
)

BLOBS = {
    f"pkg{i:02d}/LICENSE": (
        b"Permission is hereby granted, free of charge %02d\n" % i
    ) * 8
    for i in range(24)
}


def _tar_bytes(files=None) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for name, data in (files or BLOBS).items():
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def _zip_bytes(files=None) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for name, data in (files or BLOBS).items():
            zf.writestr(name, data)
    return buf.getvalue()


@pytest.fixture()
def host():
    # 1 ms backoff so the scripted-fault retries cost nothing
    saved = os.environ.get("LICENSEE_TPU_REMOTE_BACKOFF_MS")
    os.environ["LICENSEE_TPU_REMOTE_BACKOFF_MS"] = "1"
    h = LoopbackBlobHost({
        "a.tar": _tar_bytes(),
        "a.zip": _zip_bytes(),
        "a.tar.gz": gzip.compress(_tar_bytes()),
    })
    with h:
        yield h
    if saved is None:
        os.environ.pop("LICENSEE_TPU_REMOTE_BACKOFF_MS", None)
    else:
        os.environ["LICENSEE_TPU_REMOTE_BACKOFF_MS"] = saved


# -- grammar routing --


def test_url_entry_grammar():
    assert remote_entry_kind("https://h/x/a.tar") == "rtar"
    assert remote_entry_kind("http://h:8080/a.tar.gz?tok=1") == "rctar"
    assert remote_entry_kind("https://h/a.zip#frag") == "rzip"
    assert remote_entry_kind("https://h/repo.git") == "rgit"
    assert remote_entry_kind("https://h/a.bin") is None
    assert remote_entry_kind("/local/a.tar") is None
    # the FIRST :: splits; scheme/port colons are single and safe
    assert split_entry("https://h:8080/a.tar::*") == (
        "https://h:8080/a.tar", "*",
    )
    assert is_container_entry("https://h/r.zip::LICENSE")
    # an unrecognized URL shape degrades to a loose path, row-contained
    assert not is_container_entry("https://h/a.bin::x")


def test_git_over_http_refused(host):
    host.set_content("repo.git", b"not a repo")
    with pytest.raises(IngestError, match="publish a tar/zip"):
        expand_manifest([host.url("repo.git") + "::HEAD"])


# -- parity --


@pytest.mark.parametrize("artifact", ["a.tar", "a.zip", "a.tar.gz"])
def test_remote_parity_bit_identical(host, artifact):
    ex = expand_manifest([host.url(artifact) + "::*"])
    try:
        assert ex.total == len(BLOBS)
        got = {ex.paths[i]: ex.read_at(i) for i in range(ex.total)}
    finally:
        ex.close()
    assert got == BLOBS


def test_ranged_reads_coalesce(host):
    ex = expand_manifest([host.url("a.tar") + "::*"])
    try:
        for i in range(ex.total):
            ex.read_at(i)
    finally:
        ex.close()
    # 24 small members must NOT cost 24 round trips: adjacent spans
    # coalesce into few ranged reads (plus metadata/probe requests)
    assert len(host.ranges.get("a.tar", [])) < len(BLOBS) // 2


def test_restricted_spans_and_descriptor_reopen(host):
    url = host.url("a.tar") + "::*"
    names = sorted(BLOBS)
    halves = []
    for lo, hi in ((0, 12), (12, 24)):
        ex = expand_manifest([url])
        try:
            ex.restrict(lo, hi)
            desc = ex.descriptor()
            # the worker-process path: pickle the recipe, re-open fresh
            worker = ManifestExpansion.from_descriptor(desc)
            try:
                halves.append(
                    [worker.read_at(i) for i in range(hi - lo)]
                )
            finally:
                worker.close()
        finally:
            ex.close()
    assert halves[0] + halves[1] == [BLOBS[n] for n in names]


def test_featurize_procs_parity(host, tmp_path):
    from licensee_tpu.projects.batch_project import BatchProject

    outs = {}
    for label, procs in (("solo", 0), ("procs", 2)):
        out = tmp_path / f"{label}.jsonl"
        project = BatchProject(
            [host.url("a.tar") + "::*"], batch_size=8, mesh=None,
            featurize_procs=procs,
        )
        try:
            project.run(str(out), resume=False)
        finally:
            project.close()
        outs[label] = hashlib.sha256(out.read_bytes()).hexdigest()
    assert outs["solo"] == outs["procs"]


def test_oversized_member_skips_not_truncates(host):
    big = {"small/LICENSE": b"MIT\n" * 10, "big/LICENSE": b"x" * 70_000}
    host.set_content("big.tar", _tar_bytes(big))
    ex = expand_manifest([host.url("big.tar") + "::*"])
    try:
        rows = {ex.paths[i]: ex.read_at(i) for i in range(ex.total)}
    finally:
        ex.close()
    assert rows["small/LICENSE"] == big["small/LICENSE"]
    assert isinstance(rows["big/LICENSE"], SkippedBlob)


# -- the failure model --


def test_torn_body_retried_once_then_bit_identical(host):
    host.truncate_next("a.tar", 40)
    ex = expand_manifest([host.url("a.tar") + "::*"])
    try:
        assert ex.read_at(0) == BLOBS[sorted(BLOBS)[0]]
    finally:
        ex.close()


def test_persistent_tear_fails_closed(host):
    # every body torn: the retry budget must exhaust, never a silent
    # partial scan (metadata fetches hit the tear at expansion)
    host.truncate_next("a.tar", 40, times=99)
    with pytest.raises(RemoteRetryBudgetError):
        expand_manifest([host.url("a.tar") + "::*"])


def test_retry_budget_exhaustion_on_5xx(host):
    host.fail_next("a.tar", 99, 503)
    with pytest.raises(RemoteRetryBudgetError):
        expand_manifest([host.url("a.tar") + "::*"])


def test_503_then_recover_within_budget(host):
    host.fail_next("a.zip", 2, 503)
    ex = expand_manifest([host.url("a.zip") + "::*"])
    try:
        got = {ex.paths[i]: ex.read_at(i) for i in range(ex.total)}
    finally:
        ex.close()
    assert got == BLOBS


def test_midjob_republish_refuses_ranged_reads(host):
    ex = expand_manifest([host.url("a.tar") + "::*"])
    try:
        host.set_content("a.tar", _tar_bytes() + b"\0" * 1024)
        with pytest.raises(RemoteChangedError):
            ex.read_at(0)
    finally:
        ex.close()


def test_midjob_republish_refuses_stream_reads(host):
    ex = expand_manifest([host.url("a.tar.gz") + "::*"])
    try:
        host.set_content("a.tar.gz", gzip.compress(_tar_bytes() + b"\0"))
        with pytest.raises((RemoteChangedError, RemoteRetryBudgetError)):
            ex.read_at(0)
    finally:
        ex.close()


def test_republish_changes_fingerprint_and_refuses_resume(host):
    """The validators fold into the expansion fingerprint, so the
    PR 15 resume/worker gates refuse a republished artifact even when
    the member table looks identical."""
    url = host.url("a.tar") + "::*"
    before = expanded_layout([url])["fingerprint"]
    ex = expand_manifest([url])
    try:
        desc = ex.descriptor()
    finally:
        ex.close()
    # same member names + sizes, different bytes -> new ETag
    flipped = {n: d[:-1] + b"?" for n, d in BLOBS.items()}
    host.set_content("a.tar", _tar_bytes(flipped))
    after = expanded_layout([url])["fingerprint"]
    assert before != after
    with pytest.raises(IngestError, match="changed under a running"):
        ManifestExpansion.from_descriptor(desc)


def test_behind_window_miss_counted_not_taken(host):
    """The streaming-tar path: a read behind the forward window that
    was never want()ed pays ONE counted rescan (the correctness
    fallback), it does not fail and it does not silently rescan per
    blob."""
    container = _RemoteSeqTarContainer(host.url("a.tar.gz"))
    try:
        names = container.members()
        # no wants registered: walking to ordinal 2 caches nothing
        assert container.read(names[2]) == BLOBS[names[2]]
        assert container.rescans == 0
        # ordinal 0 is now behind the window -> one counted rescan
        assert container.read(names[0]) == BLOBS[names[0]]
        assert container.rescans == 1
    finally:
        container.close()


# -- submit-time probing --


def test_probe_remote_shapes(host):
    info = probe_remote(host.url("a.tar"))
    assert info["kind"] == "rtar" and info["size"] == len(_tar_bytes())
    assert info["etag"]
    # compressed tar needs reachability only, not Range support
    host.no_range = True
    assert probe_remote(host.url("a.tar.gz"))["kind"] == "rctar"
    with pytest.raises(RemoteProbeError, match="byte ranges"):
        probe_remote(host.url("a.tar"))
    host.no_range = False
    with pytest.raises(RemoteProbeError):
        probe_remote(host.url("missing.zip"))
    with pytest.raises(RemoteError, match="503"):
        host.fail_next("a.zip", 99, 503)
        probe_remote(host.url("a.zip"))
