"""Native git ODB reader: parity with git plumbing on every repo shape the
backend must handle (loose, packed+delta, annotated tags, bare, short SHA),
plus backend equivalence inside GitProject."""

import os
import subprocess

import pytest

def _native_available() -> bool:
    try:
        from licensee_tpu.native import gitodb

        gitodb._load()
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(
    not _native_available(),
    reason="native gitodb unavailable (disabled or no toolchain)",
)

from licensee_tpu.projects.git_project import (
    GitProject,
    InvalidRepository,
    _NativeBackend,
    _SubprocessBackend,
)
from tests.conftest import fixture_path


def git(repo, *args, binary=False):
    out = subprocess.run(
        ["git", "-C", repo, *args], capture_output=True, check=True
    ).stdout
    return out if binary else out.decode().strip()


@pytest.fixture()
def packed_repo(tmp_path):
    """A repo with packed objects (incl. deltas), an annotated tag, a
    branch, and loose objects layered on top of the pack."""
    repo = str(tmp_path / "repo")
    os.makedirs(repo)
    run = lambda *a: subprocess.run(a, cwd=repo, check=True, capture_output=True)
    run("git", "init", "-q")
    run("git", "config", "user.email", "t@example.invalid")
    run("git", "config", "user.name", "t")
    run("git", "config", "commit.gpgsign", "false")
    with open(os.path.join(repo, "LICENSE"), "w") as f:
        f.write("MIT License\n" * 500)
    run("git", "add", ".")
    run("git", "commit", "-qm", "one")
    with open(os.path.join(repo, "LICENSE"), "w") as f:
        f.write("MIT License\n" * 500 + "changed\n")
    run("git", "add", ".")
    run("git", "commit", "-qm", "two")
    run("git", "tag", "-a", "v1", "-m", "tag")
    run("git", "repack", "-adq")
    with open(os.path.join(repo, "README.md"), "w") as f:
        f.write("readme\n")
    run("git", "add", ".")
    run("git", "commit", "-qm", "three")
    return repo


def test_native_matches_plumbing(packed_repo):
    native = _NativeBackend(packed_repo, None)
    sub = _SubprocessBackend(packed_repo, None)
    assert native.files() == sub.files()
    for f in native.files():
        assert native.load_file(f) == sub.load_file(f)
    native.close()


@pytest.mark.parametrize("rev", ["HEAD", "v1"])
def test_native_revisions(packed_repo, rev):
    native = _NativeBackend(packed_repo, rev)
    sub = _SubprocessBackend(packed_repo, rev)
    assert native.files() == sub.files()
    native.close()


def test_native_short_sha_revision(packed_repo):
    short = git(packed_repo, "rev-parse", "--short", "HEAD")
    native = _NativeBackend(packed_repo, short)
    assert {f["name"] for f in native.files()} == {"LICENSE", "README.md"}
    native.close()


def test_native_bare_repo(packed_repo, tmp_path):
    bare = str(tmp_path / "bare.git")
    subprocess.run(
        ["git", "clone", "-q", "--bare", packed_repo, bare],
        check=True, capture_output=True,
    )
    native = _NativeBackend(bare, None)
    assert {f["name"] for f in native.files()} == {"LICENSE", "README.md"}
    native.close()


def test_native_blob_cap(packed_repo):
    """A blob past MAX_LICENSE_SIZE is SKIPPED (None), never truncated
    and scored — a 64 KiB head can match a license the rest of the
    file contradicts (the ingest-consistency contract; the project
    layer drops skipped candidates entirely)."""
    with open(os.path.join(packed_repo, "BIG"), "wb") as f:
        f.write(b"x" * (200 * 1024))
    subprocess.run(["git", "add", "."], cwd=packed_repo, check=True,
                   capture_output=True)
    subprocess.run(["git", "commit", "-qm", "big"], cwd=packed_repo,
                   check=True, capture_output=True)
    native = _NativeBackend(packed_repo, None)
    big = [f for f in native.files() if f["name"] == "BIG"][0]
    assert native.load_file(big) is None  # MAX_LICENSE_SIZE: skip
    small = [f for f in native.files() if f["name"] == "LICENSE"][0]
    assert native.load_file(small)  # under the cap: real bytes
    native.close()


def test_native_rejects_non_repo(tmp_path):
    with pytest.raises(InvalidRepository):
        _NativeBackend(str(tmp_path), None)


def test_native_rejects_unknown_revision(packed_repo):
    with pytest.raises(InvalidRepository):
        _NativeBackend(packed_repo, "no-such-branch")


def test_git_project_uses_native_backend(git_fixture):
    repo = git_fixture("mit")
    project = GitProject(repo)
    assert isinstance(project._backend, _NativeBackend)
    assert project.license is not None and project.license.key == "mit"
    project.close()


def test_git_project_detection_parity_both_backends(git_fixture):
    repo = git_fixture("bsd-2-author")
    native = GitProject(repo)
    key_native = native.license.key if native.license else None
    native.close()

    class _Forced(GitProject):
        @staticmethod
        def _open_backend(repo, revision):
            return _SubprocessBackend(repo, revision)

    sub = _Forced(repo)
    key_sub = sub.license.key if sub.license else None
    assert key_native == key_sub == "bsd-2-clause"


def test_native_linked_worktree(packed_repo, tmp_path):
    wt = str(tmp_path / "wt")
    subprocess.run(
        ["git", "worktree", "add", "-q", wt, "HEAD"],
        cwd=packed_repo, check=True, capture_output=True,
    )
    native = _NativeBackend(wt, None)
    sub = _SubprocessBackend(wt, None)
    assert native.files() == sub.files()
    native.close()


def test_native_shared_clone_alternates(packed_repo, tmp_path):
    clone = str(tmp_path / "shared")
    subprocess.run(
        ["git", "clone", "-q", "--shared", packed_repo, clone],
        check=True, capture_output=True,
    )
    native = _NativeBackend(clone, None)
    sub = _SubprocessBackend(clone, None)
    assert native.files() == sub.files()
    for f in native.files():
        assert native.load_file(f) == sub.load_file(f)
    native.close()


def test_native_symlink_entry_counts_as_blob(packed_repo):
    os.symlink("LICENSE", os.path.join(packed_repo, "COPYING"))
    subprocess.run(["git", "add", "."], cwd=packed_repo, check=True,
                   capture_output=True)
    subprocess.run(["git", "commit", "-qm", "symlink"], cwd=packed_repo,
                   check=True, capture_output=True)
    native = _NativeBackend(packed_repo, None)
    sub = _SubprocessBackend(packed_repo, None)
    assert native.files() == sub.files()
    assert "COPYING" in {f["name"] for f in native.files()}
    native.close()


def test_native_hex_named_ref_precedence(packed_repo):
    """A branch named like hex ('beef') resolves to the ref, not to a
    colliding short-SHA prefix (git rev-parse precedence)."""
    git(packed_repo, "branch", "beef", "HEAD~1")
    expected = git(packed_repo, "rev-parse", "beef")
    native = _NativeBackend(packed_repo, "beef")
    assert native._commit == expected
    native.close()


def test_native_hex_named_tag_precedence(packed_repo):
    git(packed_repo, "tag", "cafe", "HEAD~1")
    expected = git(packed_repo, "rev-parse", "cafe^{commit}")
    native = _NativeBackend(packed_repo, "cafe")
    assert native._commit == expected
    native.close()
