"""The unified observability layer (licensee_tpu/obs/): metrics
registry math, Prometheus exposition grammar, tracer retention (head
sampling + slow exemplars + bounded JSONL log), the native profile
delta scrape (no double-count across scrapes), profile_reset parity,
the device compile-vs-execute split, and the offline BatchProject
per-chunk traces.  All CPU-only and fast."""

from __future__ import annotations

import json
import threading
import time

import pytest

from licensee_tpu.obs import (
    AnomalyWatchdog,
    FlatlineRule,
    MetricsRegistry,
    NativeProfileSource,
    Observability,
    QueryError,
    RateJumpRule,
    SaturationRule,
    Tracer,
    TsdbStore,
    check_exposition,
    merge_expositions,
    render_prometheus,
)

# -- registry --


def test_counter_gauge_histogram_math():
    reg = MetricsRegistry()
    c = reg.counter("events_total", "events", labels=("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(4)
    assert c.labels(kind="a").value == 5
    with pytest.raises(ValueError):
        c.labels(kind="a").inc(-1)  # counters are monotonic
    g = reg.gauge("depth")
    g.set(3)
    assert g.value == 3
    g.set_fn(lambda: 11)
    assert g.value == 11
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.01, 0.05, 5.0):
        h.observe(v)
    hv = h.value
    # buckets are CUMULATIVE (le semantics): 0.01 holds both <=0.01
    # observations, +Inf holds everything
    assert hv["buckets"]["0.01"] == 2
    assert hv["buckets"]["0.1"] == 3
    assert hv["buckets"]["1.0"] == 3
    assert hv["buckets"]["+Inf"] == 4
    assert hv["count"] == 4
    assert hv["sum"] == pytest.approx(5.065)


def test_registry_registration_is_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    a = reg.counter("x_total", labels=("k",))
    assert reg.counter("x_total", labels=("k",)) is a
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # kind mismatch
    with pytest.raises(ValueError):
        reg.counter("x_total")  # label mismatch
    with pytest.raises(ValueError):
        reg.counter("bad name")  # exposition-illegal name
    with pytest.raises(ValueError):
        a.labels(other="v")  # undeclared label


def test_counter_sync_never_goes_backwards():
    reg = MetricsRegistry()
    c = reg.counter("ext_total")
    c.sync(10)
    c.sync(7)  # a restarted source must not rewind the series
    assert c.value == 10


def test_snapshot_runs_collectors():
    reg = MetricsRegistry()
    c = reg.counter("pulled_total")
    state = {"n": 0}
    reg.add_collector(lambda r: c.sync(state["n"]))
    state["n"] = 5
    snap = reg.snapshot()
    assert snap["pulled_total"]["samples"][0]["value"] == 5


# -- exposition --


def test_prometheus_exposition_grammar_and_content():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests by kind", labels=("kind",))
    c.labels(kind="cache_hit").inc(3)
    reg.gauge("queue_depth", "now").set(7)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.01, 1.0))
    h.observe(0.005)
    text = render_prometheus(reg)
    assert check_exposition(text) == []
    assert "# TYPE req_total counter" in text
    assert 'req_total{kind="cache_hit"} 3' in text
    assert "queue_depth 7" in text
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_sum 0.005" in text
    assert "lat_seconds_count 1" in text


def test_exposition_escapes_label_values():
    reg = MetricsRegistry()
    c = reg.counter("weird_total", labels=("path",))
    c.labels(path='a"b\\c\nd').inc()
    text = render_prometheus(reg)
    assert check_exposition(text) == []
    assert r'path="a\"b\\c\nd"' in text


def test_check_exposition_flags_garbage():
    assert check_exposition("not a metric line !!!\n")
    assert check_exposition("name{unclosed 1\n")
    assert check_exposition("") == []


# -- tracer --


def test_head_sampling_is_deterministic_stride():
    tracer = Tracer(sample_rate=0.25, slow_ms=10_000.0, capacity=64)
    kept = sum(
        tracer.finish(tracer.start(request_id=i)) for i in range(16)
    )
    assert kept == 4  # every 4th


def test_slow_exemplar_always_captured(tmp_path):
    log = str(tmp_path / "trace.jsonl")
    tracer = Tracer(
        sample_rate=0.0, slow_ms=20.0, capacity=8, log_path=log
    )
    fast = tracer.start(request_id="fast")
    assert tracer.finish(fast) is False  # unsampled and fast: dropped
    slow = tracer.start(request_id="slow")
    slow.add_span("featurize", 0.001)
    slow.add_span("device", 0.02)
    time.sleep(0.025)
    assert tracer.finish(slow) is True  # sampling off, kept anyway
    tail = tracer.tail(10)
    assert [t["id"] for t in tail] == ["slow"]
    assert [s["name"] for s in tail[0]["spans"]] == ["featurize", "device"]
    assert tail[0]["dur_ms"] >= 20.0
    with open(log, encoding="utf-8") as f:
        logged = [json.loads(line) for line in f]
    assert len(logged) == 1 and logged[0]["slow"] is True
    assert logged[0]["trace"] == tail[0]["trace"]


def test_trace_log_is_bounded_by_rotation(tmp_path):
    import os

    log = str(tmp_path / "trace.jsonl")
    tracer = Tracer(
        sample_rate=0.0, slow_ms=0.0, capacity=4, log_path=log,
        log_max_bytes=2048,
    )
    for i in range(100):
        tracer.finish(tracer.start(request_id=f"r{i}"))
    assert os.path.getsize(log) <= 2048
    assert os.path.getsize(log + ".1") <= 2048  # single rotation, ~2x cap


def test_trace_ids_unique_and_ring_bounded():
    tracer = Tracer(sample_rate=1.0, slow_ms=10_000.0, capacity=4)
    ids = set()
    for i in range(10):
        t = tracer.start(request_id=i)
        ids.add(t.trace_id)
        tracer.finish(t)
    assert len(ids) == 10
    assert all(len(i) == 16 for i in ids)
    tail = tracer.tail(100)
    assert len(tail) == 4  # ring keeps the most recent `capacity`
    assert [t["id"] for t in tail] == [6, 7, 8, 9]


# -- native profile deltas --


def test_profile_source_does_not_double_count_across_scrapes():
    cumulative = {"stage.normalize_s": 2.0, "count.blobs": 8.0}
    reg = MetricsRegistry()
    NativeProfileSource(reg, dump_fn=lambda: dict(cumulative))
    reg.snapshot()
    reg.snapshot()  # the regression: a second scrape with no new work
    blobs = reg.counter(
        "native_featurize_events_total", labels=("kind",)
    ).labels(kind="blobs")
    secs = reg.counter(
        "native_featurize_stage_seconds_total", labels=("stage",)
    ).labels(stage="normalize")
    assert blobs.value == 8.0
    assert secs.value == 2.0
    cumulative["count.blobs"] = 11.0
    reg.snapshot()
    assert blobs.value == 11.0
    # an external profile_reset rewinds the cumulative source: the
    # delta clamps at zero instead of going negative
    cumulative["count.blobs"] = 1.0
    reg.snapshot()
    assert blobs.value == 11.0
    cumulative["count.blobs"] = 3.0
    reg.snapshot()
    assert blobs.value == 13.0  # counts resume from the new baseline


def test_profile_source_is_once_per_registry():
    """Several attachments to ONE registry (e.g. MicroBatchers sharing
    the process-wide registry) must not multiply the deltas: the
    cumulative surface is process-wide, so only one collector scrapes
    it."""
    cumulative = {"count.blobs": 5.0}
    reg = MetricsRegistry()
    NativeProfileSource(reg, dump_fn=lambda: dict(cumulative))
    NativeProfileSource(reg, dump_fn=lambda: dict(cumulative))
    reg.snapshot()
    blobs = reg.counter(
        "native_featurize_events_total", labels=("kind",)
    ).labels(kind="blobs")
    assert blobs.value == 5.0  # not 10: one collector, one baseline


def test_histogram_bucket_mismatch_is_rejected():
    """Re-registering a histogram with different bounds must be a hard
    error — silently reusing the first family would drop the second
    caller's observations into the wrong bins."""
    reg = MetricsRegistry()
    reg.histogram("h_seconds", buckets=(1.0, 10.0))
    with pytest.raises(ValueError):
        reg.histogram("h_seconds", buckets=(0.001, 0.01))
    assert reg.histogram("h_seconds", buckets=(1.0, 10.0)) is not None


def test_module_profile_dump_reset_fallback_parity():
    """The module-level pair works without the native library: the
    pure-Python accumulator reports under the same keys and resets."""
    from licensee_tpu.native import pipeline

    pipeline.profile_reset()
    before = pipeline.profile_dump()
    pipeline.py_profile_add(**{
        "count.blobs": 2, "stage.normalize_s": 0.25,
    })
    after = pipeline.profile_dump()
    assert after.get("count.blobs", 0) - before.get("count.blobs", 0) == 2
    assert (
        after.get("stage.normalize_s", 0.0)
        - before.get("stage.normalize_s", 0.0)
    ) == pytest.approx(0.25)
    assert pipeline.profile_reset() is True
    cleared = pipeline.profile_dump()
    assert cleared.get("count.blobs", 0.0) == 0.0


def test_native_profile_reset_zeroes_stage_counters():
    from licensee_tpu.native import pipeline

    nat = pipeline.load()
    if nat is None:
        pytest.skip("native pipeline unavailable")
    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(pad_batch_to=8, mesh=None, device=False)
    clf.prepare_batch([b"mit license words alpha beta"])
    assert nat.profile_dump().get("count.blobs", 0) >= 1
    assert nat.profile_reset() is True
    assert nat.profile_dump().get("count.blobs") == 0.0


def test_two_scrapes_after_work_count_each_blob_once():
    """End-to-end double-count regression over the REAL profile
    surface: scrape, do one blob of work, scrape twice — the counter
    moves by exactly that one blob."""
    from licensee_tpu.kernels.batch import BatchClassifier
    from licensee_tpu.native import pipeline

    reg = MetricsRegistry()
    NativeProfileSource(reg, dump_fn=pipeline.profile_dump)
    reg.snapshot()  # baseline absorbs all prior work in this process
    blobs = reg.counter(
        "native_featurize_events_total", labels=("kind",)
    ).labels(kind="blobs")
    base = blobs.value
    clf = BatchClassifier(pad_batch_to=8, mesh=None, device=False)
    clf.prepare_batch([b"one more blob of words to featurize"])
    reg.snapshot()
    reg.snapshot()
    assert blobs.value == base + 1


# -- Observability bundle --


def test_bundle_snapshot_shape_and_uptime():
    obs = Observability(tracing=True, trace_sample=1.0)
    t = obs.tracer.start(request_id="x")
    obs.tracer.finish(t)
    snap = obs.snapshot()
    assert snap["uptime_s"] >= 0
    assert "process_uptime_seconds" in snap["metrics"]
    assert snap["tracing"]["started"] == 1
    assert check_exposition(obs.prometheus()) == []


def test_bundle_tracing_disabled_is_null_tracer():
    obs = Observability(tracing=False)
    assert obs.tracer.start("x") is None
    assert obs.tracer.tail() == []
    assert obs.tracer.finish(None) is False


# -- device compile-vs-execute split --


def test_dispatch_stats_split_compile_then_execute():
    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(pad_batch_to=4, mesh=None)
    blob = b"Permission is hereby granted free of charge zqx zqy"
    clf.classify_blobs([blob + b" one"])
    d1 = clf.dispatch_stats()
    clf.classify_blobs([blob + b" two"])
    d2 = clf.dispatch_stats()
    # same padded shape: first dispatch was the compile, the second a
    # steady-state execute
    assert d1["compiles"] == 1 and d1["dispatches"] == 0
    assert d2["compiles"] == 1 and d2["dispatches"] == 1
    assert d2["shapes"] == [4]
    assert d2["compile_s"] > 0 and d2["dispatch_s"] > 0


# -- offline per-chunk traces --


def test_batch_project_run_emits_per_chunk_traces(tmp_path):
    from licensee_tpu.projects.batch_project import BatchProject
    from tests.conftest import fixture_contents

    mit = fixture_contents("mit/LICENSE.txt")
    paths = []
    for i in range(6):
        p = tmp_path / f"LICENSE_{i}"
        p.write_text(mit + f"\nzqchunk{i}\n", encoding="utf-8")
        paths.append(str(p))
    tracer = Tracer(sample_rate=1.0, slow_ms=10_000.0, capacity=16)
    project = BatchProject(
        paths, batch_size=3, mesh=None, workers=1, tracer=tracer
    )
    out = tmp_path / "out.jsonl"
    project.run(str(out), resume=False)
    tail = tracer.tail(16)
    assert len(tail) == 2  # 6 files / batch_size 3
    assert [t["id"] for t in tail] == ["chunk-1", "chunk-2"]
    for t in tail:
        names = [s["name"] for s in t["spans"]]
        assert names[:2] == ["read", "featurize"]
        assert "write" in names
        # these chunks carry Dice-bound rows, so the group device spans
        # ride along too
        assert "dispatch" in names and "score" in names
        # the trace is rebased over the worker-side produce stages:
        # every span sits at t >= 0 on the chunk's own timeline
        assert all(s["t_ms"] >= 0 for s in t["spans"])
        assert t["dur_ms"] >= t["spans"][0]["dur_ms"]


def test_exemplar_rides_the_exposition_grammar():
    """An OpenMetrics exemplar (`# {trace_id="..."} v`) on a histogram
    bucket line must both appear and still parse clean."""
    reg = MetricsRegistry()
    h = reg.histogram("rt_seconds", "rt", buckets=(0.01, 1.0))
    h.observe(0.005)
    h.observe(0.25, exemplar="deadbeefcafef00d")
    text = render_prometheus(reg)
    assert check_exposition(text) == []
    line = next(
        ln for ln in text.splitlines()
        if ln.startswith('rt_seconds_bucket{le="1.0"}')
    )
    assert '# {trace_id="deadbeefcafef00d"} 0.25' in line
    # the fast bucket saw no exemplar-carrying observation
    fast = next(
        ln for ln in text.splitlines()
        if ln.startswith('rt_seconds_bucket{le="0.01"}')
    )
    assert "trace_id" not in fast


def test_exemplar_slowest_wins_within_bucket():
    reg = MetricsRegistry()
    h = reg.histogram("rt_seconds", "rt", buckets=(1.0,))
    h.observe(0.25, exemplar="aaaa")
    h.observe(0.75, exemplar="bbbb")
    h.observe(0.10, exemplar="cccc")  # faster: must not displace
    text = render_prometheus(reg)
    assert '# {trace_id="bbbb"} 0.75' in text
    assert "aaaa" not in text and "cccc" not in text


def test_check_exposition_accepts_exemplar_and_flags_malformed():
    good = 'rt_bucket{le="+Inf"} 4 # {trace_id="ab12"} 0.5\n'
    assert check_exposition(good) == []
    # an exemplar without its value is NOT grammar
    assert check_exposition('rt_bucket{le="+Inf"} 4 # {trace_id="x"}\n')


def test_merge_preserves_exemplars():
    """The fleet merge injects worker="..." into the SAMPLE's labelset
    — the exemplar's own {...} must ride through untouched (a greedy
    label match would swallow up to the exemplar's closing brace)."""
    reg = MetricsRegistry()
    h = reg.histogram("rt_seconds", "rt", buckets=(1.0,))
    h.observe(0.25, exemplar="feedface")
    merged = merge_expositions({"w7": render_prometheus(reg)})
    assert check_exposition(merged) == []
    line = next(
        ln for ln in merged.splitlines()
        if ln.startswith("rt_seconds_bucket")
    )
    assert 'worker="w7"' in line
    assert line.endswith('# {trace_id="feedface"} 0.25')
    # the injected label landed in the sample's labelset, not the
    # exemplar's
    assert line.index('worker="w7"') < line.index("trace_id")


# -- telemetry store --


def _fill(store, name, labels, n, t0=0.0, step=1.0, per_step=1.0):
    v = 0.0
    for i in range(n):
        store.ingest(name, labels, v, ts=t0 + i * step)
        v += per_step


def test_tsdb_downsample_keeps_old_history():
    fake = [0.0]
    store = TsdbStore(
        fine_step_s=1.0, fine_len=10, coarse_step_s=5.0,
        coarse_len=20, clock=lambda: fake[0],
    )
    _fill(store, "req_total", {"worker": "w0"}, 40)
    fake[0] = 39.0
    # 40 samples through a 10-deep fine ring: the coarse fold must
    # keep enough history for a full-span rate
    rate = store.rate("req_total", {"worker": "w0"}, window_s=39.0)
    assert rate == pytest.approx(1.0, abs=0.2)
    raw = store.query({"series": "req_total", "fn": "raw", "window": 39.0})
    assert len(raw["points"]) > 10


def test_tsdb_rate_is_counter_reset_aware():
    fake = [0.0]
    store = TsdbStore(clock=lambda: fake[0])
    for i, v in enumerate([0.0, 10.0, 20.0, 2.0, 12.0]):  # reset at i=3
        store.ingest("c_total", None, v, ts=float(i))
    fake[0] = 4.0
    rate = store.rate("c_total", None, window_s=4.0)
    # increases: 10+10+(reset: +2)+10 = 32 over 4s, NOT negative
    assert rate is not None and rate > 0


def test_tsdb_windows_are_two_sided():
    """A derivation over a PAST window must not see newer samples —
    otherwise a live fault bleeds backward into every trailing
    baseline the watchdog compares against."""
    fake = [0.0]
    store = TsdbStore(fine_len=400, clock=lambda: fake[0])
    _fill(store, "c_total", None, 100)  # 1/s steady
    v = 100.0
    for i in range(100, 120):  # then a 50/s fault
        store.ingest("c_total", None, v, ts=float(i))
        v += 50.0
    fake[0] = 120.0
    past = store.rate("c_total", None, window_s=10.0, now=90.0)
    assert past == pytest.approx(1.0, abs=0.3)
    current = store.rate("c_total", None, window_s=10.0, now=120.0)
    assert current > 20.0


def test_tsdb_eviction_is_coldest_first_and_capped():
    store = TsdbStore(max_series=8, max_bytes=1_000_000)
    for i in range(8):
        store.ingest("s_total", {"lane": str(i)}, 1.0, ts=float(i))
    # lane=0 is the coldest; a 9th series must evict it, not the warm
    store.ingest("s_total", {"lane": "new"}, 1.0, ts=100.0)
    st = store.stats()
    assert st["series"] == 8
    assert st["evicted_series"] == 1
    assert store.latest("s_total", {"lane": "0"}) is None
    assert store.latest("s_total", {"lane": "7"}) is not None


def test_tsdb_query_unknown_series_is_typed():
    store = TsdbStore()
    with pytest.raises(QueryError) as exc:
        store.query({"series": "absent_total", "fn": "latest"})
    assert exc.value.code == "unknown_series"
    with pytest.raises(QueryError) as exc:
        store.query({"series": "x", "fn": "nope"})
    assert exc.value.code == "bad_request"


def test_tsdb_exposition_ingest_round_trips_exemplar():
    fake = [0.0]
    store = TsdbStore(clock=lambda: fake[0])
    reg = MetricsRegistry()
    h = reg.histogram("rt_seconds", "rt", buckets=(0.01, 0.1, 1.0))
    h.observe(0.005)
    h.observe(0.25, exemplar="deadbeef")
    store.ingest_exposition(
        render_prometheus(reg), extra_labels={"worker": "w0"}, ts=10.0
    )
    h.observe(0.5, exemplar="feedface")
    store.ingest_exposition(
        render_prometheus(reg), extra_labels={"worker": "w0"}, ts=15.0
    )
    fake[0] = 15.0
    row = store.query({
        "series": "rt_seconds", "fn": "quantile", "q": 0.99,
        "window": 10.0,
    })
    assert 0.1 < row["value"] <= 1.0
    assert row["exemplar"]["trace_id"] == "feedface"


# -- anomaly watchdog --


def test_rate_jump_fires_once_and_clears():
    fake = [0.0]
    store = TsdbStore(fine_len=400, clock=lambda: fake[0])
    v = 0.0
    for i in range(101):
        store.ingest("j_total", None, v, ts=float(i))
        v += 1.0
    rule = RateJumpRule(
        "jump", "j_total", window_s=10.0, baseline_windows=4,
        min_baseline=3, z_threshold=4.0,
    )
    wd = AnomalyWatchdog(
        store, [rule], hold_ticks=1, clear_ticks=2,
        clock=lambda: fake[0],
    )
    fake[0] = 100.0
    wd.evaluate()
    assert not wd.active()
    for i in range(101, 121):
        store.ingest("j_total", None, v, ts=float(i))
        v += 50.0
    fake[0] = 120.0
    events = wd.evaluate()
    assert [e["state"] for e in events] == ["firing"]
    assert wd.active()[0]["rule"] == "jump"
    for i in range(121, 181):
        store.ingest("j_total", None, v, ts=float(i))
        v += 1.0
    for t in (150.0, 165.0, 180.0):
        fake[0] = t
        wd.evaluate()
    assert not wd.active()
    assert wd.snapshot()["fired_total"] == 1


def test_watchdog_hold_ticks_hysteresis():
    """One breached round must NOT page when hold_ticks=2."""
    fake = [0.0]
    store = TsdbStore(fine_len=400, clock=lambda: fake[0])
    store.ingest("g", None, 0.99, ts=0.0)
    rule = SaturationRule("sat", "g", threshold=0.95)
    wd = AnomalyWatchdog(
        store, [rule], hold_ticks=2, clear_ticks=1,
        clock=lambda: fake[0],
    )
    fake[0] = 1.0
    wd.evaluate()
    assert not wd.active()  # first breach held back
    fake[0] = 2.0
    wd.evaluate()
    assert wd.active()  # second consecutive breach pages


def test_flatline_rule_fires_on_stale_heartbeat():
    fake = [0.0]
    store = TsdbStore(clock=lambda: fake[0])
    store.ingest("tsdb_scrape_up", {"worker": "w0"}, 1.0, ts=0.0)
    rule = FlatlineRule(
        "flat_w0", "tsdb_scrape_up", labels={"worker": "w0"},
        stale_after_s=5.0,
    )
    wd = AnomalyWatchdog(
        store, [rule], hold_ticks=1, clear_ticks=1,
        clock=lambda: fake[0],
    )
    fake[0] = 3.0
    wd.evaluate()
    assert not wd.active()  # fresh heartbeat
    fake[0] = 10.0
    wd.evaluate()
    assert wd.active()  # stale: the worker stopped answering
    store.ingest("tsdb_scrape_up", {"worker": "w0"}, 1.0, ts=10.5)
    fake[0] = 11.0
    wd.evaluate()
    assert not wd.active()  # heartbeat resumed


def test_tracer_concurrent_finish_is_consistent():
    tracer = Tracer(sample_rate=1.0, slow_ms=10_000.0, capacity=1024)

    def work(k):
        for i in range(50):
            t = tracer.start(request_id=f"{k}-{i}")
            t.add_span("featurize", 0.0001)
            tracer.finish(t)

    threads = [threading.Thread(target=work, args=(k,)) for k in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert tracer.started == 200
    assert tracer.retained == 200
    assert len(tracer.tail(1024)) == 200
