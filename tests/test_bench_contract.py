"""The driver-artifact contract for bench.py's stdout line.

The round driver captures only the last ~2 KB of bench stdout and
json-parses the final line into BENCH_r{N}.json.  Round 4's single fat
JSON line outgrew that window and the official round record carried no
numbers at all — so the headline line is byte-budgeted and this test
pins the budget against a fully-populated (worst-case) details dict.
Full per-row blobs go to BENCH_DETAILS.json instead (mirrors the
reference's golden-artifact discipline, spec/fixture_spec.rb:3-45).
"""

import importlib.util
import json
import os

import pytest

_BENCH_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)


@pytest.fixture(scope="module")
def bench_mod():
    spec = importlib.util.spec_from_file_location("bench", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fat_details() -> dict:
    """A details dict at least as large as any real run produces."""
    e2e = {
        "files": 1_000_000,
        "corpus": "x" * 64,
        "files_per_sec": 8_748_728.9,
        "stage_seconds": {
            k: 99999.999
            for k in ("read", "featurize", "dispatch", "score", "write", "elapsed")
        },
        "host_cores": 128,
        "featurize_files_per_core_sec": 99999.9,
        "dedupe_hits": 1_000_000,
        "matched": 1_000_000,
        "routed": {"none": 1_000_000, "license": 1_000_000,
                   "readme": 1_000_000, "package": 1_000_000},
    }
    return {
        "batch": 262_144,
        "templates": 9999,
        "template_source": "y" * 300,
        "vocab": 99_999,
        "method": "pallas-mxu",
        "rates": {m: 99_999_999.9 for m in
                  ("popcount", "matmul", "pallas", "pallas-mxu")},
        "rates_t47": {m: 99_999_999.9 for m in
                      ("popcount", "matmul", "pallas", "pallas-mxu")},
        "scalar_cpu_files_per_sec": 99999.9,
        "end_to_end": dict(e2e),
        "end_to_end_dup": dict(e2e),
        "end_to_end_readme": dict(e2e),
        "end_to_end_package": dict(e2e),
        "end_to_end_auto": dict(e2e),
        "serve_path": {
            "requests": 99_999_999,
            "uncached_rps": 99_999_999.9,
            "cached_rps": 99_999_999.9,
            "cache_hits": 99_999_999,
            "device_batches": 99_999_999,
            "bucket_counts": {str(b): 99_999_999 for b in
                              (8, 32, 128, 256)},
            "p50_ms": 99999.999,
            "p99_ms": 99999.999,
            "obs": {
                "prometheus_lines": 99_999_999,
                "prometheus_grammar_errors": 99_999_999,
                "metric_families": 99_999_999,
                "tracing": {
                    "started": 99_999_999,
                    "retained": 99_999_999,
                    "slow": 99_999_999,
                    "ring": 99_999_999,
                    "sample_rate": 0.999999,
                    "slow_ms": 99999.999,
                    "log_path": "y" * 120,
                },
                "device_dispatch": {
                    "compiles": 99_999_999,
                    "compile_s": 99999.999,
                    "dispatches": 99_999_999,
                    "dispatch_s": 99999.999,
                    "shapes": [8, 32, 128, 256],
                },
                "uptime_s": 99999.999,
                "slo": {
                    "ok": False,
                    "uptime_s": 99999.999,
                    "objectives": {
                        "availability": {
                            "target": 0.999999,
                            "description": "d" * 120,
                            "good": 99_999_999,
                            "bad": 99_999_999,
                            "windows": {"5m": 99999.9999,
                                        "30m": 99999.9999,
                                        "1h": 99999.9999,
                                        "6h": 99999.9999},
                            "max_burn": 99999.9999,
                            "fast_burn_alert": True,
                            "slow_burn_alert": True,
                            "ok": False,
                        },
                        "latency_p99": {
                            "target": 0.999999,
                            "description": "d" * 120,
                            "good": 99_999_999,
                            "bad": 99_999_999,
                            "windows": {"5m": 99999.9999,
                                        "30m": 99999.9999,
                                        "1h": 99999.9999,
                                        "6h": 99999.9999},
                            "max_burn": 88888.8888,
                            "fast_burn_alert": True,
                            "slow_burn_alert": True,
                            "ok": False,
                        },
                    },
                },
                "traces_assembled": {
                    "trees": 99_999_999,
                    "critical_within_5pct": 99_999_998,
                },
            },
        },
        "fleet": {
            "requests": 99_999_999,
            "rps_1w": 99_999_999.9,
            "errors_1w": 99_999_999,
            "rps_2w": 99_999_999.9,
            "errors_2w": 99_999_999,
            "failover_errors": 99_999_999,
            "failover_max_stall_s": 99999.999,
            "restart_recovery_s": 99999.999,
            "router_saturation": {
                "deadline_ms": 99999.9,
                "pr4_closed_loop_rps": 99999.9,
                "rounds": [{"target_rps": 99_999_999.9}] * 16,
                "max_rps": 99_999_999.9,
                "p99_ms_at_max": 99999.99,
                "x_vs_pr4_closed_loop": 99999.99,
                "loop_max_lag_ms": 99999.999,
            },
            "edge_saturation": {
                "deadline_ms": 99999.9,
                "rounds": [{"target_rps": 99_999_999.9}] * 16,
                "max_rps": 99_999_999.9,
                "p99_ms_at_max": 99999.99,
                "loop_max_lag_ms": 99999.999,
            },
        },
        "host_model": {
            "z" * 30: 9.9,
            "featurize_us_per_blob": 99_999_999.9,
            "scaling_model": {
                "serial_us_per_blob": 99999.9,
                "amdahl_ceiling_files_per_sec": 99_999_999.9,
            },
            "overlap": {
                "speedup": 99999.999,
                "identical_output": True,
                "lane_model": {"measured_over_predicted": 99999.999},
            },
            "autoscale": {
                "cores_modeled": 224,
                "best_static_stripes": 99,
                "converged_stripes": 99,
                "modeled_files_per_sec_best": 99_999_999.0,
                "modeled_files_per_sec_converged": 99_999_999.0,
                "within_10pct": True,
                "scale_events": 99,
                "flapping": False,
                "events": [
                    {"t": 99999.9, "from": 9, "to": 10,
                     "why": "pressure high", "pressure": 1.0}
                ] * 16,
            },
            "native_stage_profile": {
                "n": 99_999,
                "us_per_blob": {
                    "stage.tokenize_only": 99999.99,
                    "s2.title_strips": 99999.99,
                    "s2.fold_spell": 99999.99,
                },
            },
        },
        "stripes": {
            "files": 1_000_000,
            "host_cores": 224,
            "auto_stripes": 16,
            "stripes": 4,
            "1_stripe": {
                "rows": 1_000_000,
                "files_per_sec": 99_999_999.9,
                "wall_files_per_sec": 99_999_999.9,
                "restarts": 99,
            },
            "4_stripes": {
                "rows": 1_000_000,
                "files_per_sec": 99_999_999.9,
                "wall_files_per_sec": 99_999_999.9,
                "restarts": 99,
            },
            "identical_output": True,
            "speedup": 99.99,
            "predicted_speedup": 99.99,
        },
        "ingest": {
            "files": 1_000_000,
            "loose_files_per_sec": 99_999_999.9,
            "tar_files_per_sec": 99_999_999.9,
            "vs_loose": 99.999,
            "identical_output": True,
            "container_rows": 99_999_999,
            "container_license": "x" * 40,
            "striped": {
                "stripes": 2,
                "tar_per_stripe_files_per_sec": 99_999_999.9,
                "loose_per_stripe_files_per_sec": 99_999_999.9,
                "vs_loose_striping": 99.999,
                "identical_output": True,
                "container_rows": 99_999_999,
            },
            "remote": {
                "tar_files_per_sec": 99_999_999.9,
                "vs_local_tar": 99.999,
                "identical_output": True,
                "requests": 99_999_999,
                "latency_ms": 99_999,
                "pipelined_files_per_sec": 99_999_999.9,
                "serial_files_per_sec": 99_999_999.9,
                "pipeline_x": 99.99,
                "identical_latency": True,
            },
        },
        "jobs": {
            "files": 1_000_000,
            "stripes": 64,
            "direct_wall_s": 99999.999,
            "direct_files_per_sec": 99_999_999.9,
            "job_wall_s": 99999.999,
            "job_files_per_sec": 99_999_999.9,
            "vs_direct": 99.999,
            "edge_overhead_frac": 99.999,
            "overhead_under_10pct": True,
            "submit_to_first_progress_s": 99999.999,
            "identical_output": True,
        },
        "tsdb": {
            "requests": 99_999_999,
            "scrape_interval_s": 99.9,
            "rps_scrape_off": 99_999_999.9,
            "rps_scrape_on": 99_999_999.9,
            "scrape_round_ms": 99999.999,
            "scrape_duty_cycle_pct": 99.999,
            "scrape_rounds": 99_999,
            "store_series": 99_999,
            "store_bytes_est": 99_999_999,
            "queries": 99_999,
            "query_p99_ms": 99999.999,
            "scrape_overhead_pct": 99.999,
            "overhead_under_3pct": True,
            "cap": {"bytes_est": 99_999_999, "max_bytes": 99_999_999,
                    "evicted_series": 99_999, "ok": True},
        },
        "tenant": {
            "requests": 99_999_999,
            "single_pool_rps": 99_999_999.9,
            "single_pool_errors": 99,
            "two_pool_rps": 99_999_999.9,
            "two_pool_errors": 99,
            "routing_overhead_pct": 99.99,
            "reload_ok": True,
            "reload_p99_ms": 99999.999,
            "reload_errors": 99,
        },
        "reference_fallback": {"native_jit": True},
        "tp_width": {"conclusion": "w" * 400},
        "scalar_agreement": {
            "blobs": 99_999_999,
            "agreement": 0.999999,
            "mismatches": [["k" * 40, "dice", 99.99, "k" * 40, 99.99]] * 50,
        },
        "end_to_end_1m": {
            "files": 1_000_000,
            "distinct_files": 99_999,
            "rows_written": 1_000_000,
            "resume_ok": True,
            "killed_after_rows": 999_999,
            "phase1_sec": 99999.9,
            "resume_phase_sec": 99999.9,
            "resume_files_per_sec": 9_999_999.9,
            "dedupe_hits_resume_phase": 1_000_000,
            "stage_seconds_resume_phase": e2e["stage_seconds"],
        },
        "end_to_end_1m_auto": dict(e2e),
    }


def test_headline_line_fits_driver_capture(bench_mod):
    metric = (
        "LICENSE files/sec/chip, full-SPDX-width template corpus "
        "(T=9999, DiceXLA batch)"
    )
    headline = bench_mod.make_headline(
        metric, 99_999_999.9, 999_999.9, _fat_details()
    )
    line = json.dumps(headline, separators=(",", ":"))
    n = len(line.encode("utf-8"))
    assert n <= bench_mod.HEADLINE_BYTE_BUDGET, n
    # and near the driver's ~2 KB tail window (the BENCH_r06.json file
    # artifact is the durable copy regardless, and main() degrades an
    # over-budget line to the minimal headline); re-pinned 1700 -> 1800
    # when the streaming-ingest block joined the headline, 1800 -> 1850
    # when its striped_* keys joined (PR 15), 1850 -> 1980 when the
    # durable-jobs block joined (PR 16), 1980 -> 2080 when the
    # telemetry-store block joined (PR 18), 2080 -> 2200 when the
    # multi-tenant block joined (PR 19), 2200 -> 2290 when the
    # remote-ingest keys joined (PR 20) — this worst-case dict
    # inflates every scalar to its widest; real lines run shorter
    assert n <= 2290


def test_headline_carries_the_headline_numbers(bench_mod):
    headline = bench_mod.make_headline("m", 123.45, 6.789, _fat_details())
    assert headline["value"] == 123.4 or headline["value"] == 123.5
    assert headline["unit"] == "files/sec/chip"
    d = headline["details"]
    assert d["agreement"] == 0.999999
    assert d["at_scale_license"]["resume_ok"] is True
    assert d["at_scale_license"]["rows_written"] == 1_000_000
    assert d["at_scale_auto"]["files_per_sec"] == 8_748_728.9
    assert d["e2e_files_per_sec"]["readme"] == 8_748_728.9
    assert d["serve_path"]["cached_rps"] == 99_999_999.9
    assert d["fleet"]["rps_2w"] == 99_999_999.9
    assert d["fleet"]["failover_errors"] == 99_999_999
    assert d["fleet"]["restart_recovery_s"] == 99999.999
    # the network-edge saturation scalars (PR 13): offered HTTP rps at
    # SLO through the real edge, and its p99 at max
    assert d["fleet"]["sat_rps"] == 99_999_999.9
    assert d["fleet"]["edge_sat_rps"] == 99_999_999.9
    assert d["fleet"]["edge_sat_p99_ms"] == 99999.99
    assert d["obs"]["prom_lines"] == 99_999_999
    assert d["obs"]["traces"] == 99_999_999
    # the telemetry plane's headline scalars (PR 12): the SLO burn
    # verdict and the trace assembler's critical-path audit
    assert d["obs"]["slo"]["ok"] is False
    assert d["obs"]["slo"]["availability_burn"] == 99999.9999
    assert d["obs"]["slo"]["latency_burn"] == 88888.8888
    assert d["obs"]["traces_assembled"] == 99_999_999
    assert d["obs"]["traces_critical_within_5pct"] == 99_999_998
    assert d["host_model"]["featurize_us_per_blob"] == 99_999_999.9
    assert d["host_model"]["serial_us_per_blob"] == 99999.9
    assert (
        d["host_model"]["amdahl_ceiling_files_per_sec"] == 99_999_999.9
    )
    assert d["host_model"]["overlap_speedup"] == 99999.999
    assert d["host_model"]["overlap_identical"] is True
    assert d["host_model"]["overlap_vs_lane_model"] == 99999.999
    # the elastic autoscaler's convergence verdict (PR 17): the real
    # decider driven over the measured scaling model must land within
    # 10% of the best static stripe count and then go quiet (headline
    # keys squeezed for the byte budget; full row in details)
    assert d["host_model"]["autoscale"]["best"] == 99
    assert d["host_model"]["autoscale"]["conv"] == 99
    assert d["host_model"]["autoscale"]["ok"] is True
    assert d["host_model"]["autoscale"]["flap"] is False
    assert d["stripes"]["n"] == 4
    assert d["stripes"]["files_per_sec_1"] == 99_999_999.9
    assert d["stripes"]["files_per_sec_n"] == 99_999_999.9
    assert d["stripes"]["speedup"] == 99.99
    assert d["stripes"]["predicted_speedup"] == 99.99
    assert d["stripes"]["identical_output"] is True
    # the streaming-ingestion scalars (PR 14): tar-source rate vs the
    # loose-file path on the same blob set + the bit-identical gate
    assert d["ingest"]["tar_files_per_sec"] == 99_999_999.9
    assert d["ingest"]["vs_loose"] == 99.999
    assert d["ingest"]["identical_output"] is True
    # the expanded-count striping gate (PR 15): 2-stripe tar merge
    # identical + per-stripe rate vs loose-file striping
    assert d["ingest"]["striped_identical"] is True
    assert d["ingest"]["striped_vs_loose"] == 99.999
    # the remote-source scalars (PR 20): loopback-HTTP tar rate vs
    # local tar (sha256-identical) and the injected-latency prefetch
    # pipelining multiple (readahead=8 over readahead=1)
    assert d["ingest"]["remote_vs_local"] == 99.999
    assert d["ingest"]["remote_identical"] is True
    assert d["ingest"]["remote_pipeline_x"] == 99.99
    # the durable-jobs scalars (PR 16): edge-submitted job throughput
    # vs the direct striped run, submit->first-progress latency, and
    # the sha256-identical merged-output gate
    assert d["jobs"]["job_files_per_sec"] == 99_999_999.9
    assert d["jobs"]["vs_direct"] == 99.999
    assert d["jobs"]["first_progress_s"] == 99999.999
    assert d["jobs"]["identical_output"] is True
    # the telemetry-store scalars (PR 18): scrape+ingest overhead on
    # saturated stub-fleet rps (<3% gate), server-side query p99, and
    # the byte-cap eviction verdict
    assert d["obs"]["tsdb"]["ovh_pct"] == 99.999
    assert d["obs"]["tsdb"]["ovh_ok"] is True
    assert d["obs"]["tsdb"]["q_p99_ms"] == 99999.999
    assert d["obs"]["tsdb"]["cap_ok"] is True
    # the multi-tenant scalars (PR 19): corpus-tag routing overhead vs
    # a pool-less router over the same workers, and tenant B's p99
    # while tenant A's pool rolled mid-stream
    assert d["tenant"]["two_pool_rps"] == 99_999_999.9
    assert d["tenant"]["single_pool_rps"] == 99_999_999.9
    assert d["tenant"]["routing_overhead_pct"] == 99.99
    assert d["tenant"]["reload_p99_ms"] == 99999.999
    assert d["details_file"] == "BENCH_DETAILS.json"


def test_headline_survives_missing_rows(bench_mod):
    """run_safe() rows can be None; the headline must not crash or
    balloon."""
    details = _fat_details()
    for k in ("end_to_end_1m", "end_to_end_1m_auto", "scalar_agreement",
              "end_to_end_readme", "serve_path", "fleet", "stripes",
              "ingest", "jobs", "tsdb", "tenant"):
        details[k] = None
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    assert headline["details"]["ingest"]["tar_files_per_sec"] is None
    assert headline["details"]["jobs"]["job_files_per_sec"] is None
    assert headline["details"]["jobs"]["identical_output"] is None
    assert headline["details"]["ingest"]["identical_output"] is None
    assert headline["details"]["at_scale_license"]["resume_ok"] is None
    assert headline["details"]["e2e_files_per_sec"]["readme"] is None
    assert headline["details"]["serve_path"]["cached_rps"] is None
    assert headline["details"]["fleet"]["rps_2w"] is None
    assert headline["details"]["fleet"]["edge_sat_rps"] is None
    assert headline["details"]["stripes"]["speedup"] is None
    assert headline["details"]["stripes"]["identical_output"] is None
    # a skipped serve suite degrades the obs/slo scalars to None —
    # the keys stay, the headline never crashes
    assert headline["details"]["obs"]["slo"]["ok"] is None
    assert headline["details"]["obs"]["slo"]["availability_burn"] is None
    assert headline["details"]["obs"]["traces_assembled"] is None
    # same for a crashed tsdb suite (None != the "skipped" stamp)
    assert headline["details"]["obs"]["tsdb"]["ovh_pct"] is None
    assert headline["details"]["obs"]["tsdb"]["cap_ok"] is None
    # and a crashed tenant suite
    assert headline["details"]["tenant"]["two_pool_rps"] is None
    assert headline["details"]["tenant"]["reload_p99_ms"] is None


def test_fast_mode_fleet_keys_say_skipped(bench_mod):
    """The PR 13 satellite: a fast-mode run stamps every
    details.fleet.* headline key with the "skipped" marker — the
    driver record must distinguish "not run" from "broken" (null)."""
    details = _fat_details()
    details["fleet"] = "skipped"
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    fleet = headline["details"]["fleet"]
    assert fleet, "fleet block vanished"
    assert set(fleet) == set(bench_mod.FLEET_HEADLINE_KEYS)
    assert all(v == "skipped" for v in fleet.values()), fleet
    for key in ("edge_sat_rps", "edge_sat_p99_ms", "sat_rps"):
        assert fleet[key] == "skipped"
    # and the stamped line still fits the driver capture
    line = json.dumps(headline, separators=(",", ":"))
    assert len(line.encode()) <= bench_mod.HEADLINE_BYTE_BUDGET


def test_fast_mode_ingest_keys_say_skipped(bench_mod):
    """The PR 14 satellite: fast mode stamps the details.ingest
    headline keys "skipped" — not-run must never read as broken."""
    details = _fat_details()
    details["ingest"] = "skipped"
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    ingest = headline["details"]["ingest"]
    assert set(ingest) == set(bench_mod.INGEST_HEADLINE_KEYS)
    assert all(v == "skipped" for v in ingest.values()), ingest
    line = json.dumps(headline, separators=(",", ":"))
    assert len(line.encode()) <= bench_mod.HEADLINE_BYTE_BUDGET


def test_fast_mode_jobs_keys_say_skipped(bench_mod):
    """The PR 16 satellite: fast mode stamps the details.jobs
    headline keys "skipped" — not-run must never read as broken."""
    details = _fat_details()
    details["jobs"] = "skipped"
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    jobs = headline["details"]["jobs"]
    assert set(jobs) == set(bench_mod.JOBS_HEADLINE_KEYS)
    assert all(v == "skipped" for v in jobs.values()), jobs
    line = json.dumps(headline, separators=(",", ":"))
    assert len(line.encode()) <= bench_mod.HEADLINE_BYTE_BUDGET


def test_fast_mode_tsdb_keys_say_skipped(bench_mod):
    """The PR 18 satellite: fast mode stamps the details.obs.tsdb
    headline keys "skipped" — not-run must never read as broken."""
    details = _fat_details()
    details["tsdb"] = "skipped"
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    tsdb = headline["details"]["obs"]["tsdb"]
    assert set(tsdb) == set(bench_mod.TSDB_HEADLINE_KEYS)
    assert all(v == "skipped" for v in tsdb.values()), tsdb
    line = json.dumps(headline, separators=(",", ":"))
    assert len(line.encode()) <= bench_mod.HEADLINE_BYTE_BUDGET


def test_fast_mode_tenant_keys_say_skipped(bench_mod):
    """The PR 19 satellite: fast mode stamps the details.tenant
    headline keys "skipped" — not-run must never read as broken."""
    details = _fat_details()
    details["tenant"] = "skipped"
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    tenant = headline["details"]["tenant"]
    assert set(tenant) == set(bench_mod.TENANT_HEADLINE_KEYS)
    assert all(v == "skipped" for v in tenant.values()), tenant
    line = json.dumps(headline, separators=(",", ":"))
    assert len(line.encode()) <= bench_mod.HEADLINE_BYTE_BUDGET


def test_fast_mode_autoscale_says_skipped(bench_mod):
    """The PR 17 satellite: a fast-mode run (host_model suite not run)
    stamps the headline's autoscale verdict "skipped" — not-run must
    never read as broken, and the stamped line still fits."""
    details = _fat_details()
    details["host_model"] = {}
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    assert headline["details"]["host_model"]["autoscale"] == "skipped"
    line = json.dumps(headline, separators=(",", ":"))
    assert len(line.encode()) <= bench_mod.HEADLINE_BYTE_BUDGET


def test_headline_artifact_always_written(bench_mod, tmp_path):
    """The PR 12 satellite: the compact BENCH_r06.json headline is an
    unconditional file artifact (fast mode / skipped suites included),
    so the driver view can never come back empty."""
    assert bench_mod.HEADLINE_FILE == "BENCH_r06.json"
    details = _fat_details()
    for k in list(details):
        if k not in ("batch", "templates", "vocab", "method", "rates",
                     "scalar_cpu_files_per_sec"):
            details[k] = None  # every optional suite skipped
    headline = bench_mod.make_headline("m", 1.0, 1.0, details)
    path = bench_mod.write_headline_artifacts(
        headline, details, out_dir=str(tmp_path)
    )
    assert os.path.basename(path) == "BENCH_r06.json"
    with open(path, encoding="utf-8") as f:
        line = f.read()
    assert len(line.encode()) <= bench_mod.HEADLINE_BYTE_BUDGET
    loaded = json.loads(line)
    assert loaded["details"]["details_file"] == "BENCH_DETAILS.json"
    with open(tmp_path / "BENCH_DETAILS.json", encoding="utf-8") as f:
        full = json.load(f)
    assert full["headline"] == loaded
