"""The whole-program layer (licensee_tpu/analysis/program.py): alias
resolution across modules — the seams the cross-module rules depend
on — plus the call-graph walk, class hierarchies, and the
reverse-dependency closure behind ``script/analyze --changed``.

The alias cases are the satellite contract: ``import x as y``,
``from m import f as g``, re-exported names through ``__init__.py``,
and method references passed as callbacks must all resolve to the
defining scope.
"""

from __future__ import annotations

import pytest

from licensee_tpu.analysis.core import Module
from licensee_tpu.analysis.program import Program, summarize
from licensee_tpu.analysis.scopes import (
    ImportTable,
    rel_to_modname,
    rel_to_package,
)


def build_program(files: dict[str, str], **kwargs) -> Program:
    return Program(
        [summarize(Module(rel, src)) for rel, src in files.items()],
        **kwargs,
    )


def scope_names(program, targets):
    out = set()
    for rel, sid in targets:
        sc = program.by_rel[rel].scopes[sid]
        out.add((rel, sc.owner, sc.name))
    return out


# -- module naming -------------------------------------------------------


def test_rel_to_modname_and_package():
    assert rel_to_modname("pkg/sub/mod.py") == "pkg.sub.mod"
    assert rel_to_modname("pkg/sub/__init__.py") == "pkg.sub"
    assert rel_to_package("pkg/sub/mod.py") == "pkg.sub"
    assert rel_to_package("pkg/sub/__init__.py") == "pkg.sub"
    assert rel_to_package("mod.py") == ""


# -- import-alias resolution --------------------------------------------


@pytest.mark.parametrize(
    "importer_src,callee",
    [
        # import x as y
        ("import pkg.wire as w\n\ndef go():\n    w.probe()\n", "probe"),
        # from m import f as g
        (
            "from pkg.wire import probe as check\n\n"
            "def go():\n    check()\n",
            "probe",
        ),
        # plain dotted use
        ("import pkg.wire\n\ndef go():\n    pkg.wire.probe()\n", "probe"),
    ],
    ids=["import-as", "from-import-as", "dotted"],
)
def test_alias_forms_resolve_to_defining_scope(importer_src, callee):
    program = build_program({
        "pkg/__init__.py": "",
        "pkg/wire.py": "def probe():\n    return 1\n",
        "pkg/app.py": importer_src,
    })
    app = program.by_rel["pkg/app.py"]
    go = next(sc for sc in app.scopes if sc.name == "go")
    (call,) = go.calls
    targets = program.call_targets("pkg/app.py", go, call)
    assert ("pkg/wire.py", None, callee) in scope_names(program, targets)


def test_reexport_through_init_resolves():
    """``from pkg import probe`` where pkg/__init__.py re-exports it
    from pkg.wire — one from-import hop at a time."""
    program = build_program({
        "pkg/__init__.py": "from pkg.wire import probe\n",
        "pkg/wire.py": "def probe():\n    return 1\n",
        "app.py": (
            "from pkg import probe\n\n"
            "def go():\n    probe()\n"
        ),
    })
    app = program.by_rel["app.py"]
    go = next(sc for sc in app.scopes if sc.name == "go")
    (call,) = go.calls
    targets = program.call_targets("app.py", go, call)
    assert ("pkg/wire.py", None, "probe") in scope_names(program, targets)


def test_relative_import_canonicalizes():
    """``from .wire import probe`` inside pkg/app.py resolves against
    the enclosing package."""
    program = build_program({
        "pkg/__init__.py": "",
        "pkg/wire.py": "def probe():\n    return 1\n",
        "pkg/app.py": (
            "from .wire import probe as p\n\n"
            "def go():\n    p()\n"
        ),
    })
    app = program.by_rel["pkg/app.py"]
    assert app.imports["p"] == "pkg.wire.probe"
    go = next(sc for sc in app.scopes if sc.name == "go")
    (call,) = go.calls
    targets = program.call_targets("pkg/app.py", go, call)
    assert ("pkg/wire.py", None, "probe") in scope_names(program, targets)


def test_class_instantiation_resolves_to_init():
    program = build_program({
        "pkg/__init__.py": "",
        "pkg/conn.py": (
            "class Conn:\n"
            "    def __init__(self, path):\n"
            "        self.path = path\n"
        ),
        "pkg/app.py": (
            "from pkg.conn import Conn\n\n"
            "def go():\n    return Conn('x')\n"
        ),
    })
    app = program.by_rel["pkg/app.py"]
    go = next(sc for sc in app.scopes if sc.name == "go")
    (call,) = go.calls
    targets = program.call_targets("pkg/app.py", go, call)
    assert ("pkg/conn.py", "Conn", "__init__") in scope_names(
        program, targets
    )


def test_method_reference_passed_as_callback_is_spawned():
    """``Thread(target=wire.worker_loop)`` marks the referenced module
    function as a spawn target across the module boundary."""
    src = (
        "import threading\n"
        "import pkg.wire as wire\n\n"
        "def boot():\n"
        "    threading.Thread(target=wire.worker_loop).start()\n"
    )
    program = build_program({
        "pkg/__init__.py": "",
        "pkg/wire.py": "def worker_loop():\n    return 1\n",
        "app.py": src,
    })
    app = program.by_rel["app.py"]
    assert "pkg.wire.worker_loop" in app.spawned_qualified
    assert scope_names(
        program, program.resolve("pkg.wire.worker_loop")
    ) == {("pkg/wire.py", None, "worker_loop")}


def test_self_call_dispatches_through_hierarchy():
    """A ``self.handle()`` in the base class reaches the subclass
    override in ANOTHER module — the LoopJsonlServer/JsonlUnixServer
    shape."""
    program = build_program({
        "pkg/__init__.py": "",
        "pkg/base.py": (
            "class Server:\n"
            "    def accept(self):\n"
            "        self.handle()\n"
            "    def handle(self):\n"
            "        raise NotImplementedError\n"
        ),
        "pkg/impl.py": (
            "from pkg.base import Server\n\n"
            "class Worker(Server):\n"
            "    def handle(self):\n"
            "        return 42\n"
        ),
    })
    base = program.by_rel["pkg/base.py"]
    accept = next(sc for sc in base.scopes if sc.name == "accept")
    (call,) = accept.calls
    names = scope_names(
        program, program.call_targets("pkg/base.py", accept, call)
    )
    assert ("pkg/base.py", "Server", "handle") in names
    assert ("pkg/impl.py", "Worker", "handle") in names


# -- the reachability walk ----------------------------------------------


def test_reachable_crosses_modules_and_skip_edge_vetoes():
    program = build_program({
        "pkg/__init__.py": "",
        "pkg/helper.py": (
            "def inner():\n    return 1\n\n"
            "def outer():\n    return inner()\n"
        ),
        "app.py": (
            "import pkg.helper as helper\n\n"
            "def entry():\n    helper.outer()\n"
        ),
    })
    app = program.by_rel["app.py"]
    entry = next(sc for sc in app.scopes if sc.name == "entry")
    reached = program.reachable([("app.py", entry.sid, "test")])
    names = {
        (rel, program.by_rel[rel].scopes[sid].name)
        for (rel, sid) in reached
    }
    assert ("pkg/helper.py", "outer") in names
    assert ("pkg/helper.py", "inner") in names
    # vetoing the app->outer edge keeps the whole subtree out
    reached = program.reachable(
        [("app.py", entry.sid, "test")],
        skip_edge=lambda s, sc, call: call[1] == "outer",
    )
    names = {
        (rel, program.by_rel[rel].scopes[sid].name)
        for (rel, sid) in reached
    }
    assert ("pkg/helper.py", "outer") not in names


# -- the import graph (--changed closure) --------------------------------


def test_reverse_closure_follows_importers():
    program = build_program({
        "pkg/__init__.py": "",
        "pkg/wire.py": "def probe():\n    return 1\n",
        "pkg/router.py": "from pkg.wire import probe\n",
        "pkg/cli.py": "import pkg.router\n",
        "pkg/other.py": "X = 1\n",
    })
    closure = program.reverse_closure({"pkg/wire.py"})
    assert closure == {"pkg/wire.py", "pkg/router.py", "pkg/cli.py"}
    assert program.reverse_closure({"pkg/other.py"}) == {"pkg/other.py"}


def test_circular_reexport_resolves_to_none_not_recursion():
    """Two packages re-exporting each other's name must resolve to
    nothing (and never recurse) — both for callables and for base
    classes."""
    program = build_program({
        "a/__init__.py": "from b import Thing\n",
        "b/__init__.py": "from a import Thing\n",
        "app.py": (
            "from a import Thing\n\n"
            "class Sub(Thing):\n"
            "    pass\n\n"
            "def go():\n    Thing()\n"
        ),
    })
    assert program.resolve("a.Thing") == []
    app = program.by_rel["app.py"]
    go = next(sc for sc in app.scopes if sc.name == "go")
    (call,) = go.calls
    assert program.call_targets("app.py", go, call) == []


def test_changed_closure_keeps_program_rule_findings(tmp_path):
    """--changed narrows per-file reporting but must never drop a
    whole-program finding (a stale pragma in an unchanged file still
    fails — --changed can never pass what the full scan fails)."""
    from licensee_tpu.analysis import analyze_paths

    stale = tmp_path / "stale.py"
    stale.write_text(
        "def f():\n"
        "    return 1  # analysis: disable=wallclock-time\n",
        encoding="utf-8",
    )
    other = tmp_path / "other.py"
    other.write_text("X = 1\n", encoding="utf-8")
    findings, _ = analyze_paths(
        [str(stale), str(other)], str(tmp_path), complete=True,
        changed_rels={"other.py"},
    )
    assert [f.rule for f in findings] == ["stale-pragma"], [
        f.render() for f in findings
    ]


def test_import_table_canonicalizes_relative_levels():
    import ast

    tree = ast.parse(
        "from . import sibling\n"
        "from ..top import thing\n"
    )
    table = ImportTable(tree, package="pkg.sub")
    assert table.names["sibling"] == "pkg.sub.sibling"
    assert table.names["thing"] == "pkg.top.thing"
