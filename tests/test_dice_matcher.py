"""Dice matcher exactness oracle: the similarity floats pinned by
spec/licensee/matchers/dice_matcher_spec.rb:24-28 must match bit-for-bit —
they are the agreement contract for the batch XLA kernel too."""

from licensee_tpu.corpus.license import License
from licensee_tpu.matchers import Dice
from licensee_tpu.project_files.license_file import LicenseFile
from tests.conftest import fixture_contents, sub_copyright_info


def make_file(content, filename="LICENSE.txt"):
    return LicenseFile(content, filename)


def test_similarity_floats():
    gpl = License.find("gpl-3.0")
    file = make_file(sub_copyright_info(gpl))
    matcher = Dice(file)
    ranked = matcher.matches_by_similarity
    assert ranked[0][0] == gpl and ranked[0][1] == 100.0
    assert ranked[1][0] == License.find("agpl-3.0")
    assert ranked[1][1] == 94.56967213114754
    assert ranked[2][0] == License.find("lgpl-2.1")
    assert ranked[2][1] == 26.821370750134918


def test_match_and_confidence():
    gpl = License.find("gpl-3.0")
    matcher = Dice(make_file(sub_copyright_info(gpl)))
    assert matcher.match == gpl
    assert matcher.confidence == 100.0


def test_no_match():
    matcher = Dice(make_file("Not really a license"))
    assert matcher.match is None
    assert matcher.matches == []
    assert matcher.confidence == 0


def test_stacked_licenses_do_not_match():
    mit = License.find("mit")
    gpl = License.find("gpl-3.0")
    content = sub_copyright_info(mit) + "\n\n" + sub_copyright_info(gpl)
    matcher = Dice(make_file(content))
    assert matcher.match is None


def test_cc_false_positive_guard():
    cc_by = License.find("cc-by-4.0")
    # CC-BY's own content matches
    assert Dice(make_file(cc_by.content)).match == cc_by
    # a CC-ND file must not match CC-BY / CC-BY-SA
    content = fixture_contents("cc-by-nd/LICENSE")
    matcher = Dice(make_file(content))
    assert matcher.match is None
    assert matcher.matches == []
    assert matcher.confidence == 0
