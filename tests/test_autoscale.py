"""Elastic autoscaling (parallel/autoscale.py + the --stripes elastic
runner path + the fleet wiring).

The decider is a pure state machine, so its three production rules
(hysteresis, cooldown, bounds) and the grow payoff check are pinned as
plain unit tests over a synthetic clock.  The process mechanics run
over the deterministic stub stripes from ``selftest_autoscale`` (the
cibuild drill — saturate, grow, idle, shrink, bit-identical merge) and
a SIGKILL-the-runner-mid-rescale drill whose rerun must still merge
byte-exactly.  Fleet-side policy (queue pressure, SLO burn floors, the
static-seed floor) runs against a fake supervisor; the real
``Supervisor.add_worker``/``remove_worker`` path is covered in
tests/test_fleet.py with live stub workers.
"""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from licensee_tpu.parallel.autoscale import (
    AutoscaleConfig,
    AutoscaleDecider,
    ExpositionScraper,
    FleetAutoscaler,
    capacity_plan,
    parse_exposition_gauges,
)

pytestmark = pytest.mark.usefixtures("lock_order_sanitizer")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    kw.setdefault("min_units", 1)
    kw.setdefault("max_units", 8)
    kw.setdefault("up_at", 0.8)
    kw.setdefault("down_at", 0.3)
    kw.setdefault("confirm_ticks", 3)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("payoff_min", 0.0)
    min_units = kw.pop("min_units")
    max_units = kw.pop("max_units")
    return AutoscaleConfig(min_units, max_units, **kw)


# -- config validation --


def test_config_rejects_bad_values():
    with pytest.raises(ValueError):
        AutoscaleConfig(0, 8)
    with pytest.raises(ValueError):
        AutoscaleConfig(4, 2)
    with pytest.raises(ValueError):
        AutoscaleConfig(1, 8, up_at=0.3, down_at=0.8)  # inverted band
    with pytest.raises(ValueError):
        AutoscaleConfig(1, 8, up_at=1.5)
    with pytest.raises(ValueError):
        AutoscaleConfig(1, 8, confirm_ticks=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(1, 8, cooldown_s=-1)


def test_config_clamp():
    cfg = AutoscaleConfig(2, 5)
    assert cfg.clamp(1) == 2
    assert cfg.clamp(3) == 3
    assert cfg.clamp(99) == 5


# -- the decider: hysteresis / cooldown / bounds --


def test_hysteresis_needs_confirm_ticks():
    d = AutoscaleDecider(_cfg(confirm_ticks=3), 1)
    assert d.observe(1.0, 1.0) is None
    assert d.observe(2.0, 1.0) is None
    assert d.observe(3.0, 1.0) == 2  # third consecutive crossing
    assert d.units == 2


def test_streak_resets_in_the_hold_band():
    d = AutoscaleDecider(_cfg(confirm_ticks=2), 1)
    assert d.observe(1.0, 1.0) is None
    assert d.observe(2.0, 0.5) is None  # hold band: streak gone
    assert d.observe(3.0, 1.0) is None  # back to streak 1
    assert d.observe(4.0, 1.0) == 2


def test_stale_signal_resets_streaks():
    d = AutoscaleDecider(_cfg(confirm_ticks=2), 1)
    assert d.observe(1.0, 1.0) is None
    assert d.observe(2.0, None) is None  # every exposition was stale
    assert d.observe(3.0, 1.0) is None  # staleness never accumulates
    assert d.observe(4.0, 1.0) == 2


def test_cooldown_holds_and_resets_streaks():
    d = AutoscaleDecider(_cfg(confirm_ticks=1, cooldown_s=10.0), 1)
    assert d.observe(1.0, 1.0) == 2
    # observations inside the cooldown window: held, streaks quiet
    assert d.observe(5.0, 1.0) is None
    assert d.observe(10.9, 1.0) is None
    # first post-cooldown crossing counts from streak zero
    assert d.observe(11.5, 1.0) == 3
    assert [e["to"] for e in d.events] == [2, 3]


def test_bounds_clamp_both_directions():
    d = AutoscaleDecider(_cfg(max_units=2, confirm_ticks=1,
                              cooldown_s=0.0), 2)
    assert d.observe(1.0, 1.0) is None  # already at max
    down = AutoscaleDecider(_cfg(confirm_ticks=1, cooldown_s=0.0), 1)
    assert down.observe(1.0, 0.0) is None  # already at min
    assert down.units == 1


def test_scale_down_on_sustained_low_pressure():
    d = AutoscaleDecider(_cfg(confirm_ticks=2, cooldown_s=0.0), 3)
    assert d.observe(1.0, 0.1) is None
    assert d.observe(2.0, 0.1) == 2
    assert d.events[-1]["why"] == "pressure low"


def test_pressure_clamped_to_unit_interval():
    d = AutoscaleDecider(_cfg(confirm_ticks=1, cooldown_s=0.0), 1)
    assert d.observe(1.0, 7.5) == 2  # clamps to 1.0, still "high"
    assert d._last_pressure == 1.0


# -- the grow payoff check --


def test_grow_without_payoff_steps_back_and_pins_ceiling():
    d = AutoscaleDecider(
        _cfg(confirm_ticks=1, cooldown_s=0.0, payoff_min=0.05), 1
    )
    assert d.observe(1.0, 1.0, throughput=100.0) == 2
    # next throughput sample shows no improvement: step back, pin
    assert d.observe(2.0, 1.0, throughput=101.0) == 1
    assert d.events[-1]["why"] == "grow did not pay; stepping back"
    # pinned: sustained saturation can re-grow only up to the ceiling
    assert d.observe(3.0, 1.0, throughput=101.0) is None
    assert d.units == 1
    # low pressure says the workload changed: the ceiling unpins
    d.observe(4.0, 0.1)
    assert d._ceiling is None


def test_grow_with_payoff_keeps_climbing():
    d = AutoscaleDecider(
        _cfg(confirm_ticks=1, cooldown_s=0.0, payoff_min=0.05), 1
    )
    assert d.observe(1.0, 1.0, throughput=100.0) == 2
    assert d.observe(2.0, 1.0, throughput=200.0) == 3  # paid: climb on
    assert d.units == 3


def test_register_publishes_gauges_and_event_counter():
    from licensee_tpu.obs.registry import MetricsRegistry

    registry = MetricsRegistry()
    d = AutoscaleDecider(_cfg(confirm_ticks=1, cooldown_s=0.0), 1)
    d.register(registry)
    d.observe(1.0, 1.0)
    d.observe(2.0, 0.0)
    d.observe(3.0, 0.0)
    from licensee_tpu.obs.export import render_prometheus

    text = render_prometheus(registry)
    gauges = parse_exposition_gauges(text)
    assert gauges["autoscale_capacity_units"] == 1.0  # up then down
    assert gauges["autoscale_pressure"] == 0.0
    assert 'autoscale_scale_events_total{direction="up"} 1' in text
    assert 'autoscale_scale_events_total{direction="down"} 1' in text


# -- capacity_plan --


def test_capacity_plan_maps_units_to_stripes_then_procs():
    assert capacity_plan(1, max_stripes=4) == (1, 0)
    assert capacity_plan(4, max_stripes=4) == (4, 0)
    # spillover past the stripe cap becomes per-stripe featurize-procs
    assert capacity_plan(6, max_stripes=4) == (4, 2)
    assert capacity_plan(6, max_stripes=4, base_featurize_procs=2) == (
        4, 4
    )
    assert capacity_plan(2, max_stripes=4, base_featurize_procs=3) == (
        2, 3
    )
    with pytest.raises(ValueError):
        capacity_plan(0, max_stripes=4)


# -- exposition parsing + the freshness scraper --


def test_parse_exposition_gauges_skips_noise():
    text = (
        "# HELP x y\n"
        "# TYPE stripe_scrape_epoch gauge\n"
        "stripe_scrape_epoch 7\n"
        "pipeline_featurize_busy 0.93\n"
        "labeled_series{worker=\"w0\"} 1\n"
        "malformed line here\n"
        "pipeline_featurize_busy 0.95\n"  # last sample wins
    )
    gauges = parse_exposition_gauges(text)
    assert gauges == {
        "stripe_scrape_epoch": 7.0,
        "pipeline_featurize_busy": 0.95,
    }


def _write_prom(path, epoch, busy=0.5):
    with open(path, "w", encoding="utf-8") as f:
        f.write(f"stripe_scrape_epoch {epoch}\n")
        f.write(f"pipeline_featurize_busy {busy}\n")


def test_scraper_accepts_advancing_epoch(tmp_path):
    prom = str(tmp_path / "s.prom")
    scraper = ExpositionScraper(stale_after_s=1.0)
    _write_prom(prom, 1)
    assert scraper.sample("k", prom, now=0.0) is not None
    _write_prom(prom, 2)
    assert scraper.sample("k", prom, now=10.0) is not None


def test_scraper_rejects_frozen_epoch_after_window(tmp_path):
    prom = str(tmp_path / "s.prom")
    scraper = ExpositionScraper(stale_after_s=1.0)
    _write_prom(prom, 5, busy=1.0)
    assert scraper.sample("k", prom, now=0.0) is not None
    # same epoch inside the window: still considered live
    assert scraper.sample("k", prom, now=0.5) is not None
    # past the window with no advance: a dead stripe's last exposition
    # must never read as a live lane snapshot
    assert scraper.sample("k", prom, now=1.6) is None
    # the epoch moving again revives the key
    _write_prom(prom, 6)
    assert scraper.sample("k", prom, now=2.0) is not None


def test_scraper_forget_restarts_the_freshness_clock(tmp_path):
    prom = str(tmp_path / "s.prom")
    scraper = ExpositionScraper(stale_after_s=1.0)
    _write_prom(prom, 5)
    assert scraper.sample("k", prom, now=0.0) is not None
    assert scraper.sample("k", prom, now=2.0) is None
    scraper.forget("k")  # the worker was retired and respawned
    assert scraper.sample("k", prom, now=3.0) is not None


def test_scraper_rejects_missing_file_and_missing_epoch(tmp_path):
    scraper = ExpositionScraper(stale_after_s=1.0)
    assert scraper.sample("k", str(tmp_path / "nope.prom"), 0.0) is None
    bare = tmp_path / "bare.prom"
    bare.write_text("pipeline_featurize_busy 0.5\n")
    # a final merge-input dump has no heartbeat stamp: not scrapable
    assert scraper.sample("k", str(bare), 0.0) is None
    with pytest.raises(ValueError):
        ExpositionScraper(stale_after_s=0)


# -- fleet policy: queue pressure, SLO floors, seed floor --


class _FakeHandle:
    def __init__(self, stats):
        self.last_stats = stats


class _FakeSupervisor:
    def __init__(self, depths):
        self.workers = {
            f"w{i}": _FakeHandle(
                {"scheduler": {"queue_depth": d, "in_flight": 0}}
                if d is not None else {}
            )
            for i, d in enumerate(depths)
        }
        self.added: list = []
        self.removed: list = []

    def add_worker(self, name, socket_path):
        self.added.append((name, socket_path))
        self.workers[name] = _FakeHandle(
            {"scheduler": {"queue_depth": 0, "in_flight": 0}}
        )

    def remove_worker(self, name, **kw):
        self.removed.append(name)
        del self.workers[name]


def _fleet(depths, slo=None, **cfg_kw):
    sup = _FakeSupervisor(depths)
    auto = FleetAutoscaler(
        sup,
        _cfg(**cfg_kw),
        socket_for=lambda name: f"/tmp/{name}.sock",
        target_inflight_per_worker=8,
        slo_snapshot=(lambda: slo) if slo is not None else None,
    )
    return sup, auto


def test_fleet_pressure_is_mean_outstanding_over_target():
    _sup, auto = _fleet([8, 16])
    assert auto.pressure() == pytest.approx(1.0)  # 12/8 clamps to 1
    _sup, auto = _fleet([2, 2])
    assert auto.pressure() == pytest.approx(0.25)
    _sup, auto = _fleet([None, None])
    assert auto.pressure() is None  # no worker has probed yet


def test_fleet_slo_burn_floors_pressure():
    fast = {"objectives": {"avail": {"fast_burn_alert": True}}}
    _sup, auto = _fleet([0], slo=fast)
    assert auto.pressure() == 1.0  # page-rate burn IS saturation
    slow = {"objectives": {"avail": {"slow_burn_alert": True}}}
    _sup, auto = _fleet([0], slo=slow)
    assert auto.pressure() == pytest.approx(auto.decider.config.up_at)


def test_fleet_tick_adds_then_removes_elastic_workers():
    sup, auto = _fleet(
        [16], confirm_ticks=1, cooldown_s=0.0, max_units=3
    )
    assert auto.tick(now=1.0) == 2
    assert sup.added == [("auto0", "/tmp/auto0.sock")]
    # the new worker reports idle; mean pressure collapses below
    # down_at and the elastic worker retires newest-first
    sup.workers["w0"].last_stats = {
        "scheduler": {"queue_depth": 0, "in_flight": 0}
    }
    assert auto.tick(now=2.0) == 1
    assert sup.removed == ["auto0"]
    assert "w0" in sup.workers  # the static seed survives


def test_fleet_never_removes_static_seed_workers():
    sup, auto = _fleet(
        [0, 0], confirm_ticks=1, cooldown_s=0.0, min_units=1
    )
    # min_units floors at the seed fleet size (2), so low pressure
    # proposes nothing — and even a forced proposal below the seed
    # count would find no elastic worker to retire
    assert auto.decider.config.min_units == 2
    assert auto.tick(now=1.0) is None
    assert auto.tick(now=2.0) is None
    assert sup.removed == []
    assert set(sup.workers) == {"w0", "w1"}


# -- the stub-stripe drills: real drain/respawn/resume mechanics --


def test_elastic_stub_drill_grows_shrinks_and_merges_identically():
    """The cibuild drill, in-process: saturated stub lanes force a
    grow, the drill flips them idle, the runner shrinks back, and the
    merged output is bit-identical to a static single-stripe run with
    cooldown spacing between the scale events."""
    from licensee_tpu.parallel.stripes import selftest_autoscale

    out = io.StringIO()
    assert selftest_autoscale(stream=out) == 0, out.getvalue()
    assert "OK: scaled up then down" in out.getvalue()


_KILL_DRIVER = """
import json, os, sys
from licensee_tpu.parallel.autoscale import AutoscaleConfig
from licensee_tpu.parallel.stripes import StripeRunner, _AUTOSCALE_STUB

workdir = sys.argv[1]
n, delay = 120, 0.05
stub = os.path.join(workdir, "stub_worker.py")
with open(stub, "w", encoding="utf-8") as f:
    f.write(_AUTOSCALE_STUB)
manifest = os.path.join(workdir, "manifest.txt")
with open(manifest, "w", encoding="utf-8") as f:
    f.write("\\n".join(f"f{j:05d}" for j in range(n)) + "\\n")
pfile = os.path.join(workdir, "pressure.txt")
with open(pfile, "w", encoding="utf-8") as f:
    f.write("1.0\\n")  # pinned saturated: the runner must scale up
out = os.path.join(workdir, "out.jsonl")
pythonpath = os.environ.get("PYTHONPATH", "")
repo_root = sys.argv[2]
env = {
    **os.environ,
    "PYTHONPATH": (
        f"{repo_root}{os.pathsep}{pythonpath}" if pythonpath
        else repo_root
    ),
}

def argv_for(i, count, resume=True):
    argv = [
        sys.executable, stub, out, str(i), str(count), str(n),
        pfile, str(delay),
    ]
    if not resume:
        argv.append("--no-resume")
    return argv

def on_progress(kind, info):
    if kind == "rescale":
        print("RESCALED", flush=True)

runner = StripeRunner(
    manifest, out, 1,
    elastic=AutoscaleConfig(
        min_units=1, max_units=2, up_at=0.8, down_at=0.3,
        confirm_ticks=2, cooldown_s=0.5, payoff_min=0.0,
    ),
    elastic_interval_s=0.2,
    elastic_stale_after_s=5.0,
    poll_interval_s=0.05,
    sigterm_timeout_s=5.0,
    argv_for=argv_for,
    env_for=lambda i, chips: env,
    on_progress=on_progress,
)
summary = runner.run()
print(f"DONE {summary['rows_written']}", flush=True)
"""


def test_sigkill_mid_rescale_rerun_merges_byte_exactly(tmp_path):
    """SIGKILL the whole elastic runner (runner + stub children) just
    after a scale-out committed — mid-scale, shards split across two
    stripe counts — then rerun the same command: the resume machinery
    must finish and merge bytes identical to a static 1-stripe run."""
    driver = tmp_path / "driver.py"
    driver.write_text(_KILL_DRIVER)
    work = tmp_path / "work"
    work.mkdir()
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    argv = [sys.executable, str(driver), str(work), REPO_ROOT]

    proc = subprocess.Popen(
        argv, env=env, stdout=subprocess.PIPE, text=True,
        start_new_session=True,  # runner + stubs share the new pgid
    )
    try:
        deadline = time.perf_counter() + 60.0
        saw_rescale = False
        while time.perf_counter() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.strip() == "RESCALED":
                saw_rescale = True
                break
        assert saw_rescale, "runner never scaled out"
        time.sleep(0.3)  # let the post-rescale respawns write a little
    finally:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass
        proc.wait(timeout=10.0)
        proc.stdout.close()

    done = subprocess.run(
        argv, env=env, capture_output=True, text=True, timeout=120.0,
    )
    assert done.returncode == 0, done.stderr
    assert "DONE 120" in done.stdout, done.stdout
    expected = b"".join(
        json.dumps({"path": f"f{j:05d}", "row": j}).encode() + b"\n"
        for j in range(120)
    )
    with open(work / "out.jsonl", "rb") as f:
        assert f.read() == expected


# -- the jobs surface: typed elastic options through validate_spec --


def test_validate_spec_accepts_elastic_with_runner_options():
    from licensee_tpu.jobs.executor import validate_spec

    spec, err = validate_spec({
        "manifest": ["a", "b"],
        "stripes": "elastic",
        "options": {
            "autoscale_min": 1,
            "autoscale_max": 4,
            "autoscale_cooldown_s": 5,
        },
    })
    assert err is None
    assert spec["stripes"] == "elastic"
    assert spec["options"]["autoscale_cooldown_s"] == 5.0  # int -> float


def test_validate_spec_refuses_runner_options_without_elastic():
    from licensee_tpu.jobs.executor import validate_spec

    spec, err = validate_spec({
        "manifest": ["a"],
        "stripes": 2,
        "options": {"autoscale_min": 1},
    })
    assert spec is None
    assert "needs spec.stripes = 'elastic'" in err


def test_validate_spec_refuses_inverted_elastic_bounds():
    from licensee_tpu.jobs.executor import validate_spec

    spec, err = validate_spec({
        "manifest": ["a"],
        "stripes": "elastic",
        "options": {"autoscale_min": 5, "autoscale_max": 2},
    })
    assert spec is None
    assert "autoscale_min" in err
    spec, err = validate_spec({
        "manifest": ["a"],
        "stripes": "elastic",
        "options": {"autoscale_cooldown_s": -1.0},
    })
    assert spec is None
    assert "autoscale_cooldown_s" in err


def test_runner_options_never_reach_child_argv():
    from licensee_tpu.jobs.executor import forward_args_for

    args = forward_args_for({
        "autoscale_min": 1,
        "autoscale_max": 4,
        "autoscale_cooldown_s": 5.0,
        "confidence": 0.9,
    })
    joined = " ".join(args)
    assert "autoscale" not in joined
    assert "--confidence" in joined
