"""Integration scenarios run against both FSProject and GitProject
(parity with spec/integration_spec.rb) — same scenario table, git repos
created on the fly."""

import os

import pytest

from licensee_tpu.corpus.license import License
from licensee_tpu.projects import FSProject, GitProject
from tests.conftest import fixture_path

# fixture -> (expected key or None, project kwargs)
SCENARIOS = [
    ("license-folder", None, {}),
    ("lgpl", "lgpl-3.0", {}),
    ("multiple-license-files", "other", {}),
    ("multiple-arrs", "bsd-3-clause", {}),
    ("cc-by-nc-sa", "other", {}),
    ("cc-by-nd", "other", {}),
    ("wrk-modified-apache", "other", {}),
    ("pixar-modified-apache", "other", {}),
    ("fcpl-modified-mpl", "other", {}),
    ("mpl-without-hrs", "mpl-2.0", {}),
    ("gpl3-without-instructions", "gpl-3.0", {}),
    ("description-license", "other", {"detect_packages": True}),
    ("crlf-license", "gpl-3.0", {}),
    ("crlf-bsd", "bsd-3-clause", {}),
    ("bsd-plus-patents", "other", {}),
    ("bsl", "bsl-1.0", {}),
    ("cc0-cc", "cc0-1.0", {}),
    ("cc0-cal2013", "cc0-1.0", {}),
    ("eupl-cal2017", "eupl-1.2", {}),
    ("unlicense-noinfo", "unlicense", {}),
    ("mit-optional", "mit", {}),
    ("license-with-readme-reference", "mit", {"detect_readme": True}),
    ("apache-with-readme-notice", "apache-2.0", {"detect_readme": True}),
    ("gpl-2.0_markdown_headings", "gpl-2.0", {}),
    ("artistic-2.0_markdown", "artistic-2.0", {}),
    ("bsd-3-lists", "bsd-3-clause", {}),
    ("bsd-3-noendorseslash", "bsd-3-clause", {}),
    ("bsd-3-authorowner", "bsd-3-clause", {}),
    ("bsd-2-author", "bsd-2-clause", {}),
    ("html", "epl-1.0", {}),
    ("vim", "vim", {}),
    ("cc-by-sa-nocclicensor", "cc-by-sa-4.0", {}),
    ("cc-by-sa-mdlinks", "cc-by-sa-4.0", {}),
    ("bom", "mit", {}),
]


def build_project(project_type, fixture, kwargs, git_fixture):
    if project_type is GitProject:
        return GitProject(git_fixture(fixture), **kwargs)
    return FSProject(fixture_path(fixture), **kwargs)


@pytest.mark.parametrize("project_type", [FSProject, GitProject])
@pytest.mark.parametrize("fixture,key,kwargs", SCENARIOS)
def test_scenario(project_type, fixture, key, kwargs, git_fixture):
    project = build_project(project_type, fixture, kwargs, git_fixture)
    expected = License.find(key) if key else None
    assert project.license == expected


@pytest.mark.parametrize("project_type", [FSProject, GitProject])
def test_lgpl_license_file_path(project_type, git_fixture):
    project = build_project(project_type, "lgpl", {}, git_fixture)
    assert project.license_file.path == "COPYING.lesser"


@pytest.mark.parametrize("project_type", [FSProject, GitProject])
def test_no_license_files(project_type, tmp_path, git_fixture):
    import subprocess

    path = tmp_path / "empty-project"
    path.mkdir()
    (path / "foo.md").write_text("bar")
    if project_type is GitProject:
        for cmd in (
            ["git", "init", "-q"],
            ["git", "config", "--local", "commit.gpgsign", "false"],
            ["git", "config", "--local", "user.email", "t@e.invalid"],
            ["git", "config", "--local", "user.name", "T"],
            ["git", "add", "."],
            ["git", "commit", "-q", "-m", "init"],
        ):
            subprocess.run(cmd, cwd=path, check=True)
        project = GitProject(str(path))
    else:
        project = FSProject(str(path))
    assert project.license is None
    assert project.license_files == []
    assert project.matched_file is None
    assert project.matched_files == []


STUBBED_FILENAMES = [
    "LICENSE.md",
    "LICENSE.txt",
    "LiCeNSe.Txt",
    "LICENSE-MIT",
    "MIT-LICENSE",
    "licence",
    "unlicense",
]


@pytest.mark.parametrize("filename", STUBBED_FILENAMES)
def test_stubbed_license_filenames(filename, tmp_path):
    mit = License.find("mit")
    (tmp_path / filename).write_text(mit.content)
    project = FSProject(str(tmp_path))
    assert project.license == mit
    assert project.license_file.path == filename


def test_stubbed_package_json(tmp_path):
    (tmp_path / "package.json").write_text('{"license": "mit"}')
    project = FSProject(str(tmp_path), detect_packages=True)
    assert project.license == License.find("mit")
    assert project.package_file.path == "package.json"


def test_stubbed_readme(tmp_path):
    mit = License.find("mit")
    (tmp_path / "README").write_text("## License\n" + mit.content)
    project = FSProject(str(tmp_path), detect_readme=True)
    assert project.license == mit
    assert project.readme_file.path == "README"


def test_stubbed_description_file(tmp_path):
    (tmp_path / "DESCRIPTION").write_text("Package: test\nLicense: MIT")
    project = FSProject(str(tmp_path), detect_packages=True)
    assert project.license == License.find("mit")
    assert project.package_file.path == "DESCRIPTION"


def test_search_root(tmp_path):
    mit = License.find("mit")
    (tmp_path / "LICENSE.txt").write_text(mit.content)
    nested = tmp_path / "a" / "b"
    nested.mkdir(parents=True)
    (nested / "code.py").write_text("pass")
    project = FSProject(str(nested), search_root=str(tmp_path))
    assert project.license == mit


def test_commitless_repo_raises_invalid_repository(tmp_path):
    """`git init` with no commits is not a usable GitProject — parity
    with git_project_spec.rb's 'new git repo' context (the facade falls
    back to FSProject there; the class itself must raise).  Lives here,
    not in the native-gated module: the subprocess fallback backend must
    honor it too."""
    import subprocess

    from licensee_tpu.projects.git_project import GitProject, InvalidRepository

    d = tmp_path / "fresh"
    d.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=d, check=True)
    with pytest.raises(InvalidRepository):
        GitProject(str(d))
