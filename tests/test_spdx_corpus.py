"""Extended SPDX corpus: rendering license-list-XML templates and scoring
against them with the same device path as the vendored pool."""

import re

import numpy as np
import pytest

from licensee_tpu import vendor_paths
from licensee_tpu.corpus.spdx import SpdxTemplate, load_spdx_dir, spdx_corpus


@pytest.fixture(scope="module")
def templates():
    return load_spdx_dir(vendor_paths.SPDX_DIR)


@pytest.fixture(scope="module")
def corpus():
    return spdx_corpus()


def test_loads_all_vendored_xmls(templates):
    assert len(templates) == 47
    keys = {t.key for t in templates}
    assert "mit" in keys and "apache-2.0" in keys and "gpl-3.0" in keys


def test_mit_render(templates):
    mit = next(t for t in templates if t.key == "mit")
    assert mit.spdx_id == "MIT"
    assert mit.title == "MIT License"
    assert "Permission is hereby granted, free of charge" in mit.content
    # <alt> canonical bodies are used, markup is gone
    assert "<alt" not in mit.content and "<p>" not in mit.content
    # alt segments counted on the raw XML minus copyright/title/optional
    assert mit.spdx_alt_segments == 10


def test_cc_flag(templates):
    cc = [t for t in templates if t.creative_commons_q]
    assert {t.key for t in cc} >= {"cc-by-4.0", "cc-by-sa-4.0"}
    assert all(t.key.startswith("cc-") for t in cc)


def test_corpus_compiles(corpus):
    assert corpus.n_templates == 47
    assert corpus.vocab_size > 2000
    assert corpus.bits.shape[0] == 47


def test_self_detection_all_templates(templates, corpus):
    """Every rendered SPDX text must classify as itself against the SPDX
    corpus (exact or dice)."""
    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(corpus=corpus, pad_batch_to=64)
    results = clf.classify_blobs([t.content for t in templates], threshold=90)
    for t, r in zip(templates, results):
        assert r.key == t.key, (t.key, r.key, r.confidence)


def test_choosealicense_cross_detection(corpus):
    """choosealicense-rendered texts find the right SPDX template as top-1
    (scores vary where the XML is bilingual, so this checks ranking, not
    the threshold)."""
    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels.batch import NormalizedBlob
    from licensee_tpu.kernels.dice_xla import CorpusArrays, score_pairs

    arrays = CorpusArrays.from_compiled(corpus)
    spdx_len = {
        t.key: len(t.content)
        for t in load_spdx_dir(vendor_paths.SPDX_DIR)
    }
    for lic in License.all(hidden=True, pseudo=False):
        text = re.sub(r"\[(\w+)\]", "example", lic.content or "")
        # skip structurally different canonical texts (e.g. SPDX LGPL-3.0
        # embeds the whole GPL-3.0; bilingual CeCILL/MulanPSL) — those are
        # corpus-content differences, not scoring defects
        key = (lic.spdx_id or "").lower()
        if spdx_len.get(key, 0) > 3 * len(text):
            continue
        blob = NormalizedBlob(text)
        bits, nw, ln = corpus.file_features(blob)
        num, den = score_pairs(
            arrays,
            bits[None],
            np.array([nw], np.int32),
            np.array([ln], np.int32),
            np.zeros(1, bool),
        )
        scores = 200.0 * np.asarray(num)[0] / np.asarray(den)[0]
        top = corpus.keys[int(np.argmax(scores))]
        assert top == (lic.spdx_id or "").lower(), (lic.key, top)


def test_custom_corpus_with_nonvendored_key(tmp_path):
    """A corpus key outside the vendored License pool (e.g. AAL from the
    full ~600-license SPDX list) must not sink classifier construction:
    the Exact prefilter is built from the corpus's own template renderings,
    not from License.find lookups (ADVICE r1 high)."""
    import shutil

    from licensee_tpu.corpus.compiler import CompiledCorpus
    from licensee_tpu.kernels.batch import BatchClassifier

    src = tmp_path / "src"
    src.mkdir()
    import os

    shutil.copy(os.path.join(vendor_paths.SPDX_DIR, "MIT.xml"), src / "MIT.xml")
    (src / "AAL.xml").write_text(
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<SPDXLicenseCollection xmlns="http://www.spdx.org/license">\n'
        '  <license licenseId="AAL" name="Attribution Assurance License">\n'
        "    <text>\n"
        "      <p>Redistribution and use in source and binary forms, with or"
        " without modification, are permitted provided that attribution is"
        " preserved and the professional identification stanza is retained"
        " in every copy of this unique software.</p>\n"
        "    </text>\n"
        "  </license>\n"
        "</SPDXLicenseCollection>\n"
    )
    templates = load_spdx_dir(str(src))
    assert {t.key for t in templates} == {"aal", "mit"}
    corpus = CompiledCorpus.compile(templates)
    clf = BatchClassifier(corpus=corpus, pad_batch_to=8)

    aal = next(t for t in templates if t.key == "aal")
    results = clf.classify_blobs([aal.content], threshold=90)
    assert results[0].key == "aal"
    assert results[0].matcher == "exact"  # the corpus-built prefilter hit


def test_cli_batch_detect_spdx_corpus(tmp_path, capsys):
    import json

    from licensee_tpu.cli.main import main

    mit = next(
        t for t in load_spdx_dir(vendor_paths.SPDX_DIR) if t.key == "mit"
    )
    f = tmp_path / "LICENSE"
    f.write_text(mit.content)
    manifest = tmp_path / "manifest.txt"
    manifest.write_text(str(f) + "\n")
    rc = main(["batch-detect", str(manifest), "--corpus", "spdx"])
    assert rc == 0
    row = json.loads(capsys.readouterr().out.strip())
    assert row["key"] == "mit"


# -- the real upstream checkout layout (VERDICT r4 item 6) --
#
# github.com/spdx/license-list-XML lays out: license XMLs directly in
# src/, exception XMLs in src/exceptions/, plus non-XML repo furniture
# (schema, DOCS, .github).  The ingest contract is: compile every
# license XML in src/, and ONLY those — the exceptions subtree and the
# furniture must not leak into the template pool.

def _upstream_shaped_checkout(tmp_path):
    import os
    import shutil

    checkout = tmp_path / "license-list-XML"
    src = checkout / "src"
    src.mkdir(parents=True)
    # real license XMLs (the vendored mirror IS upstream bytes)
    for name in ("MIT.xml", "Apache-2.0.xml", "GPL-3.0.xml"):
        shutil.copy(
            os.path.join(vendor_paths.SPDX_DIR, name), src / name
        )
    # synthetic-but-schema-valid licenses fill the pool the way a full
    # checkout would (the environment has no egress for the real ~600)
    from licensee_tpu.corpus.spdx_synth import synth_spdx_dir

    synth_spdx_dir(str(tmp_path / "synth"), 12)
    for name in os.listdir(tmp_path / "synth"):
        target = src / name
        if not target.exists():
            shutil.copy(tmp_path / "synth" / name, target)
    # the exceptions subtree: same schema, must NOT be ingested
    exceptions = src / "exceptions"
    exceptions.mkdir()
    shutil.copy(
        os.path.join(vendor_paths.SPDX_DIR, "MIT.xml"),
        exceptions / "Autoconf-exception-3.0.xml",
    )
    # repo furniture around src/
    (checkout / "DOCS.md").write_text("# docs\n")
    (checkout / "schema").mkdir()
    (checkout / "schema" / "ListedLicense.xsd").write_text("<xsd/>\n")
    (src / "README.md").write_text("not xml\n")
    (src / "invalid.xml").write_text("<unclosed\n")  # malformed: skipped
    return checkout


def test_upstream_checkout_layout_compiles(tmp_path):
    checkout = _upstream_shaped_checkout(tmp_path)
    templates = load_spdx_dir(str(checkout / "src"))
    keys = {t.key for t in templates}
    assert {"mit", "apache-2.0", "gpl-3.0"} <= keys
    assert len(templates) >= 14  # 3 real + >=11 synth fill
    # the exceptions distractor and furniture stayed out
    assert not any("exception" in t.key for t in templates)

    corpus = spdx_corpus(str(checkout / "src"))
    assert corpus.n_templates == len(templates)

    # the README recipe's agreement step: every template's own rendered
    # text classifies back to its key through the batch device path
    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(
        corpus=corpus, pad_batch_to=32, mesh=None, method="popcount"
    )
    blobs = [t.content for t in templates[:16]]
    results = clf.classify_blobs(blobs, prefilter=False)
    got = [r.key for r in results]
    want = [t.key for t in templates[:16]]
    assert got == want, list(zip(got, want))


def test_spdx_corpus_cli_over_checkout(tmp_path, capsys):
    """`batch-detect --corpus <checkout>/src` — the CLI end of the
    recipe (README 'Corpus refresh')."""
    import json
    import os

    from licensee_tpu.cli.main import main

    checkout = _upstream_shaped_checkout(tmp_path)
    blob = tmp_path / "LICENSE"
    mit = next(
        t
        for t in load_spdx_dir(str(checkout / "src"))
        if t.key == "mit"
    )
    blob.write_text(
        mit.content.replace(
            "<copyright holders>", "Example Org"
        )
    )
    manifest = tmp_path / "m.txt"
    manifest.write_text(str(blob) + "\n")
    rc = main(
        [
            "batch-detect", str(manifest),
            "--corpus", str(checkout / "src"),
            "--method", "popcount", "--mesh", "none",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    row = json.loads(out.strip().splitlines()[-1])
    assert row["key"] == "mit"
