"""Extended SPDX corpus: rendering license-list-XML templates and scoring
against them with the same device path as the vendored pool."""

import re

import numpy as np
import pytest

from licensee_tpu import vendor_paths
from licensee_tpu.corpus.spdx import SpdxTemplate, load_spdx_dir, spdx_corpus


@pytest.fixture(scope="module")
def templates():
    return load_spdx_dir(vendor_paths.SPDX_DIR)


@pytest.fixture(scope="module")
def corpus():
    return spdx_corpus()


def test_loads_all_vendored_xmls(templates):
    assert len(templates) == 47
    keys = {t.key for t in templates}
    assert "mit" in keys and "apache-2.0" in keys and "gpl-3.0" in keys


def test_mit_render(templates):
    mit = next(t for t in templates if t.key == "mit")
    assert mit.spdx_id == "MIT"
    assert mit.title == "MIT License"
    assert "Permission is hereby granted, free of charge" in mit.content
    # <alt> canonical bodies are used, markup is gone
    assert "<alt" not in mit.content and "<p>" not in mit.content
    # alt segments counted on the raw XML minus copyright/title/optional
    assert mit.spdx_alt_segments == 10


def test_cc_flag(templates):
    cc = [t for t in templates if t.creative_commons_q]
    assert {t.key for t in cc} >= {"cc-by-4.0", "cc-by-sa-4.0"}
    assert all(t.key.startswith("cc-") for t in cc)


def test_corpus_compiles(corpus):
    assert corpus.n_templates == 47
    assert corpus.vocab_size > 2000
    assert corpus.bits.shape[0] == 47


def test_self_detection_all_templates(templates, corpus):
    """Every rendered SPDX text must classify as itself against the SPDX
    corpus (exact or dice)."""
    from licensee_tpu.kernels.batch import BatchClassifier

    clf = BatchClassifier(corpus=corpus, pad_batch_to=64)
    results = clf.classify_blobs([t.content for t in templates], threshold=90)
    for t, r in zip(templates, results):
        assert r.key == t.key, (t.key, r.key, r.confidence)


def test_choosealicense_cross_detection(corpus):
    """choosealicense-rendered texts find the right SPDX template as top-1
    (scores vary where the XML is bilingual, so this checks ranking, not
    the threshold)."""
    from licensee_tpu.corpus.license import License
    from licensee_tpu.kernels.batch import NormalizedBlob
    from licensee_tpu.kernels.dice_xla import CorpusArrays, score_pairs

    arrays = CorpusArrays.from_compiled(corpus)
    spdx_len = {
        t.key: len(t.content)
        for t in load_spdx_dir(vendor_paths.SPDX_DIR)
    }
    for lic in License.all(hidden=True, pseudo=False):
        text = re.sub(r"\[(\w+)\]", "example", lic.content or "")
        # skip structurally different canonical texts (e.g. SPDX LGPL-3.0
        # embeds the whole GPL-3.0; bilingual CeCILL/MulanPSL) — those are
        # corpus-content differences, not scoring defects
        key = (lic.spdx_id or "").lower()
        if spdx_len.get(key, 0) > 3 * len(text):
            continue
        blob = NormalizedBlob(text)
        bits, nw, ln = corpus.file_features(blob)
        num, den = score_pairs(
            arrays,
            bits[None],
            np.array([nw], np.int32),
            np.array([ln], np.int32),
            np.zeros(1, bool),
        )
        scores = 200.0 * np.asarray(num)[0] / np.asarray(den)[0]
        top = corpus.keys[int(np.argmax(scores))]
        assert top == (lic.spdx_id or "").lower(), (lic.key, top)


def test_custom_corpus_with_nonvendored_key(tmp_path):
    """A corpus key outside the vendored License pool (e.g. AAL from the
    full ~600-license SPDX list) must not sink classifier construction:
    the Exact prefilter is built from the corpus's own template renderings,
    not from License.find lookups (ADVICE r1 high)."""
    import shutil

    from licensee_tpu.corpus.compiler import CompiledCorpus
    from licensee_tpu.kernels.batch import BatchClassifier

    src = tmp_path / "src"
    src.mkdir()
    import os

    shutil.copy(os.path.join(vendor_paths.SPDX_DIR, "MIT.xml"), src / "MIT.xml")
    (src / "AAL.xml").write_text(
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<SPDXLicenseCollection xmlns="http://www.spdx.org/license">\n'
        '  <license licenseId="AAL" name="Attribution Assurance License">\n'
        "    <text>\n"
        "      <p>Redistribution and use in source and binary forms, with or"
        " without modification, are permitted provided that attribution is"
        " preserved and the professional identification stanza is retained"
        " in every copy of this unique software.</p>\n"
        "    </text>\n"
        "  </license>\n"
        "</SPDXLicenseCollection>\n"
    )
    templates = load_spdx_dir(str(src))
    assert {t.key for t in templates} == {"aal", "mit"}
    corpus = CompiledCorpus.compile(templates)
    clf = BatchClassifier(corpus=corpus, pad_batch_to=8)

    aal = next(t for t in templates if t.key == "aal")
    results = clf.classify_blobs([aal.content], threshold=90)
    assert results[0].key == "aal"
    assert results[0].matcher == "exact"  # the corpus-built prefilter hit


def test_cli_batch_detect_spdx_corpus(tmp_path, capsys):
    import json

    from licensee_tpu.cli.main import main

    mit = next(
        t for t in load_spdx_dir(vendor_paths.SPDX_DIR) if t.key == "mit"
    )
    f = tmp_path / "LICENSE"
    f.write_text(mit.content)
    manifest = tmp_path / "manifest.txt"
    manifest.write_text(str(f) + "\n")
    rc = main(["batch-detect", str(manifest), "--corpus", "spdx"])
    assert rc == 0
    row = json.loads(capsys.readouterr().out.strip())
    assert row["key"] == "mit"
