"""CLI behavior (parity: spec/licensee/commands/detect_spec.rb + bin_spec.rb),
run in-process against fixture projects."""

import json

import pytest
import yaml

from licensee_tpu.cli.main import main
from tests.conftest import fixture_contents, fixture_path


def run_cli(argv, capsys):
    rc = main(argv)
    out = capsys.readouterr().out
    return rc, out


def test_detect_mit(capsys):
    rc, out = run_cli(["detect", fixture_path("mit")], capsys)
    assert rc == 0
    parsed = yaml.safe_load(out)
    assert parsed["License"] == "MIT"
    assert "LICENSE.txt" in parsed["Matched files"]
    assert parsed["LICENSE.txt"]["Confidence"] == "100.00%"
    assert parsed["LICENSE.txt"]["License"] == "MIT"


def test_detect_json(capsys):
    rc, out = run_cli(["detect", "--json", fixture_path("mit")], capsys)
    assert rc == 0
    parsed = json.loads(out)
    assert parsed["licenses"][0]["key"] == "mit"
    assert parsed["licenses"][0]["spdx_id"] == "MIT"
    assert parsed["matched_files"][0]["matched_license"] == "MIT"
    assert parsed["matched_files"][0]["matcher"] == {
        "name": "exact",
        "confidence": 100,
    }


def test_detect_json_full_golden(capsys, tmp_path):
    """The reference compares ENTIRE `detect --json` output against the
    detect.json golden (spec/licensee/commands/detect_spec.rb:62-74),
    dropping only the gemspec's raw content.  The fixture embeds the
    project files' contents, so the project is reconstructed from the
    golden itself; any drift in any to_h field fails here."""
    import copy

    from tests.conftest import FIXTURES_DIR

    with open(f"{FIXTURES_DIR}/detect.json", encoding="utf-8") as f:
        fixture = json.load(f)
    (tmp_path / "LICENSE.md").write_text(
        fixture["matched_files"][0]["content"], encoding="utf-8"
    )
    (tmp_path / "licensee.gemspec").write_text(
        fixture["matched_files"][1]["content"], encoding="utf-8"
    )
    rc, out = run_cli(["detect", "--json", str(tmp_path)], capsys)
    assert rc == 0
    parsed = json.loads(out)
    expected = copy.deepcopy(fixture)
    # parity with the spec: matched_files[1] content is not compared
    expected["matched_files"][1].pop("content", None)
    parsed["matched_files"][1].pop("content", None)
    assert parsed == expected


def test_detect_no_license_exit_code(capsys, tmp_path):
    (tmp_path / "foo.md").write_text("bar")
    rc, _ = run_cli(["detect", str(tmp_path)], capsys)
    assert rc == 1


def test_detect_closest_licenses(capsys):
    rc, out = run_cli(["detect", fixture_path("bsd-2-author")], capsys)
    assert rc == 0
    assert "Closest non-matching licenses:" in out
    assert "BSD-2-Clause similarity:" in out


def test_default_command_is_detect(capsys):
    rc, out = run_cli([fixture_path("mit")], capsys)
    assert rc == 0
    assert yaml.safe_load(out)["License"] == "MIT"


def test_license_path(capsys):
    rc, out = run_cli(["license-path", fixture_path("mit")], capsys)
    assert rc == 0
    assert out.strip().endswith("LICENSE.txt")


def test_license_path_missing(capsys, tmp_path):
    (tmp_path / "foo.md").write_text("bar")
    rc, _ = run_cli(["license-path", str(tmp_path)], capsys)
    assert rc == 1


def test_version(capsys):
    import licensee_tpu

    rc, out = run_cli(["version"], capsys)
    assert rc == 0
    assert out.strip() == licensee_tpu.__version__


def test_diff_exact_match(capsys):
    rc, out = run_cli(
        ["diff", fixture_path("mit"), "--license", "mit"], capsys
    )
    assert rc == 0
    assert "Similarity:" in out


def test_diff_invalid_license(capsys):
    rc, _ = run_cli(
        ["diff", fixture_path("mit"), "--license", "not-a-license"], capsys
    )
    assert rc == 1


def test_confidence_flag(capsys):
    import licensee_tpu

    rc, out = run_cli(
        ["detect", "--confidence", "90", fixture_path("bsd-2-author")], capsys
    )
    assert rc == 0
    licensee_tpu.set_confidence_threshold(licensee_tpu.CONFIDENCE_THRESHOLD)


def test_serve_stdin_jsonl_session(capsys, monkeypatch):
    """The serve smoke: a 4-line JSONL session piped through stdin
    answers end-to-end on CPU — exact verdicts matching detect, a
    cache-hit duplicate, and the stats verb."""
    import io

    mit = fixture_contents("mit/LICENSE.txt")
    lines = [
        json.dumps({"id": 1, "content": mit, "filename": "LICENSE.txt"}),
        json.dumps({"id": 2, "content": mit + "\nzqxcli zqycli\n",
                    "filename": "LICENSE.txt"}),
        json.dumps({"id": 3, "content": mit + "\nzqxcli zqycli\n",
                    "filename": "LICENSE.txt"}),
        json.dumps({"id": 4, "op": "stats"}),
    ]
    monkeypatch.setattr(
        "sys.stdin", io.StringIO("\n".join(lines) + "\n")
    )
    rc, out = run_cli(["serve", "--max-delay-ms", "10"], capsys)
    assert rc == 0
    rows = [json.loads(line) for line in out.splitlines()]
    assert [r["id"] for r in rows] == [1, 2, 3, 4]
    # the same verdict `detect` prints for the mit fixture
    assert (rows[0]["key"], rows[0]["matcher"], rows[0]["confidence"]) == (
        "mit", "exact", 100.0
    )
    assert (rows[1]["key"], rows[1]["matcher"]) == ("mit", "dice")
    assert rows[2]["key"] == "mit" and rows[2]["cached"]
    sched = rows[3]["stats"]["scheduler"]
    assert sched["completed"] == 3
    assert sched["device_rows"] == 1  # the duplicate deduplicated


def test_stats_selftest(capsys):
    """`licensee-tpu stats --selftest` — the obs-layer CI smoke —
    passes in-process (registry, exposition grammar, tracer retention,
    profile deltas)."""
    rc = main(["stats", "--selftest"])
    err = capsys.readouterr().err
    assert rc == 0
    assert json.loads(err.splitlines()[-1])["obs_selftest"] == "ok"


def test_stats_requires_socket_or_selftest(capsys):
    rc = main(["stats"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "--socket" in err


def test_stats_scrapes_a_running_server(tmp_path, capsys):
    """The exporter client end-to-end: `licensee-tpu stats --socket`
    scrapes JSON, Prometheus exposition, and the trace tail from a live
    serve worker over its Unix socket."""
    import threading

    from licensee_tpu.obs import check_exposition
    from licensee_tpu.serve.scheduler import MicroBatcher
    from licensee_tpu.serve.server import UnixServer

    path = str(tmp_path / "serve.sock")
    with MicroBatcher(
        max_delay_ms=5.0, buckets=(4,), mesh=None, trace_sample=1.0
    ) as batcher:
        batcher.classify(
            fixture_contents("mit/LICENSE.txt") + "\nzqstats\n", "LICENSE"
        )
        server = UnixServer(path, batcher)
        thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        thread.start()
        try:
            rc, out = run_cli(["stats", "--socket", path], capsys)
            assert rc == 0
            snap = json.loads(out)
            assert snap["scheduler"]["completed"] == 1
            assert snap["uptime_s"] >= 0

            rc, out = run_cli(
                ["stats", "--socket", path, "--format", "prometheus"],
                capsys,
            )
            assert rc == 0
            assert check_exposition(out) == []
            assert 'serve_requests_total{event="submitted"} 1' in out

            rc, out = run_cli(
                ["stats", "--socket", path, "--trace", "5"], capsys
            )
            assert rc == 0
            traces = [json.loads(line) for line in out.splitlines()]
            assert traces and all("trace" in t for t in traces)
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)


def test_stats_socket_error_is_reported(tmp_path, capsys):
    rc = main(["stats", "--socket", str(tmp_path / "absent.sock")])
    err = capsys.readouterr().err
    assert rc == 1
    assert "cannot scrape" in err


def test_batch_detect_output_preflight(tmp_path, capsys):
    """The --output preflight names the actual problem: a missing parent
    directory vs an existing path component that is not a directory."""
    lic = tmp_path / "LICENSE"
    lic.write_text("not a license")
    manifest = tmp_path / "m.txt"
    manifest.write_text(f"{lic}\n")

    missing = tmp_path / "nope" / "out.jsonl"
    assert main(["batch-detect", str(manifest), "--output", str(missing)]) == 1
    assert "does not exist" in capsys.readouterr().err

    blocker = tmp_path / "blocker"
    blocker.write_text("")
    inside = blocker / "out.jsonl"
    assert main(["batch-detect", str(manifest), "--output", str(inside)]) == 1
    assert "is not a directory" in capsys.readouterr().err


def _serve_worker(tmp_path, name):
    """A live in-process serve worker on a Unix socket (for stats
    scrape tests); returns (socket_path, server, thread, batcher)."""
    import threading

    from licensee_tpu.serve.scheduler import MicroBatcher
    from licensee_tpu.serve.server import UnixServer

    path = str(tmp_path / f"{name}.sock")
    batcher = MicroBatcher(max_delay_ms=5.0, buckets=(4,), mesh=None)
    server = UnixServer(path, batcher)
    thread = threading.Thread(
        target=server.serve_forever, kwargs={"poll_interval": 0.05},
        daemon=True,
    )
    thread.start()
    return path, server, thread, batcher


def test_stats_multiple_sockets_print_one_merged_table(tmp_path, capsys):
    """The fleet operator view: two --socket flags produce ONE table
    with a row per worker."""
    mit = fixture_contents("mit/LICENSE.txt")
    workers = []
    try:
        for name in ("alpha", "beta"):
            workers.append(_serve_worker(tmp_path, name))
        workers[0][3].classify(mit, "LICENSE")  # alpha has 1 completed
        rc, out = run_cli(
            ["stats", "--socket", workers[0][0],
             "--socket", workers[1][0]],
            capsys,
        )
    finally:
        for _path, server, thread, batcher in workers:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)
            batcher.close()
    assert rc == 0
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert lines[0].split()[:4] == ["WORKER", "UP_S", "DONE", "Q"]
    rows = {ln.split()[0]: ln.split() for ln in lines[1:]}
    assert set(rows) == {"alpha.sock", "beta.sock"}
    assert rows["alpha.sock"][2] == "1"  # DONE column
    assert rows["beta.sock"][2] == "0"


def test_stats_watch_redraws_and_computes_rate(tmp_path, capsys):
    """--watch re-scrapes at the interval; the second frame carries a
    REQ_S column derived from the completed-counter delta."""
    mit = fixture_contents("mit/LICENSE.txt")
    path, server, thread, batcher = _serve_worker(tmp_path, "w")
    try:
        batcher.classify(mit, "LICENSE")
        rc, out = run_cli(
            ["stats", "--socket", path, "--watch", "0.1",
             "--watch-iterations", "2"],
            capsys,
        )
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5.0)
        batcher.close()
    assert rc == 0
    frames = [ln for ln in out.splitlines() if ln.startswith("WORKER")]
    assert len(frames) == 2  # two redraws
    data_rows = [ln for ln in out.splitlines() if ln.startswith("w.sock")]
    assert len(data_rows) == 2
    # first frame has no previous sample to difference against
    assert data_rows[0].split()[-1] == "-"
    assert data_rows[1].split()[-1] != "down"


def test_stats_down_worker_renders_as_down_row(tmp_path, capsys):
    rc, out = run_cli(
        ["stats", "--socket", str(tmp_path / "gone-a.sock"),
         "--socket", str(tmp_path / "gone-b.sock")],
        capsys,
    )
    assert rc == 0
    rows = [ln for ln in out.splitlines() if "down" in ln]
    assert len(rows) == 2


def test_stats_multi_socket_prometheus_merges_with_worker_labels(
    tmp_path, capsys
):
    from licensee_tpu.obs import check_exposition

    workers = []
    try:
        for name in ("alpha", "beta"):
            workers.append(_serve_worker(tmp_path, name))
        rc, out = run_cli(
            ["stats", "--socket", workers[0][0],
             "--socket", workers[1][0], "--format", "prometheus"],
            capsys,
        )
    finally:
        for _path, server, thread, batcher in workers:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)
            batcher.close()
    assert rc == 0
    assert check_exposition(out) == []
    assert 'worker="alpha.sock"' in out
    assert 'worker="beta.sock"' in out
    assert out.count("# TYPE serve_queue_depth gauge") == 1


def test_stats_table_rows_unit():
    from licensee_tpu.cli.main import stats_table_rows

    snaps = {
        "w0": {
            "uptime_s": 12.3,
            "scheduler": {"completed": 30, "queue_depth": 2,
                          "in_flight": 1},
            "cache": {"hit_rate": 0.25},
            "latency_ms": {"total": {"p50_ms": 1.5, "p99_ms": 9.0}},
        },
        "w1": None,  # unreachable
    }
    prev = {
        "w0": {"scheduler": {"completed": 10}},
    }
    rows = stats_table_rows(snaps, prev, dt=2.0)
    assert rows[0][0] == "WORKER"
    w0 = rows[1]
    assert w0[0] == "w0" and w0[2] == "30" and w0[5] == "25.0"
    assert w0[-1] == "10.0"  # (30-10)/2s
    assert rows[2][0] == "w1" and rows[2][-1] == "down"


def test_fleet_selftest_flag_parses():
    from licensee_tpu.cli.main import build_parser

    args = build_parser().parse_args(["fleet", "--selftest", "--stub"])
    assert args.selftest and args.stub
    args = build_parser().parse_args(
        ["fleet", "--workers", "4", "--chips-per-worker", "2",
         "--socket", "/tmp/f.sock", "--hedge-ms", "auto"]
    )
    assert args.workers == 4
    assert args.chips_per_worker == 2
    assert args.hedge_ms == "auto"


def test_fleet_requires_socket_or_selftest(capsys):
    rc = main(["fleet"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "--socket" in err


def test_diff_socket_speaks_the_wire_verb(capsys, monkeypatch):
    """`diff --socket` sends one {"op": "diff"} round trip and renders
    the worker's payload (the wire itself is covered in test_serve)."""
    import importlib

    cli = importlib.import_module("licensee_tpu.cli.main")

    sent = {}

    def fake_scrape(socket_path, request, timeout):
        sent.update(socket=socket_path, request=request)
        return {
            "id": None,
            "diff": {
                "key": "mit", "spdx_id": "MIT", "similarity": 98.4,
                "identical": False, "input_length": 10,
                "license_length": 11, "diff": "shared [-old-]{+new+}",
            },
        }

    monkeypatch.setattr(cli, "_scrape_row", fake_scrape)
    rc, out = run_cli(
        ["diff", fixture_path("mit"), "--socket", "/tmp/w.sock"], capsys
    )
    assert rc == 0
    assert sent["socket"] == "/tmp/w.sock"
    assert sent["request"]["op"] == "diff"
    assert "content" in sent["request"]
    assert "Comparing to MIT:" in out
    assert "{+new+}" in out


def test_diff_socket_surfaces_unknown_license(capsys, monkeypatch):
    import importlib

    cli = importlib.import_module("licensee_tpu.cli.main")

    monkeypatch.setattr(
        cli, "_scrape_row",
        lambda *_a: {"id": None, "error": "unknown_license: nope"},
    )
    rc = main([
        "diff", fixture_path("mit"), "--socket", "/tmp/w.sock",
        "--license", "nope",
    ])
    err = capsys.readouterr().err
    assert rc == 1
    assert "unknown_license" in err
