"""Multi-tenant serving (licensee_tpu/tenancy/ + the router's corpus
routing): registry round-trips and token resolution, the TenantPools
supervisor facade, per-request corpus-tag routing with untagged
default-pool fallback, the per-pool fingerprint fence (a row stamping
the wrong corpus must never reach a client), and the edge's
POST /corpus auth tiers (401/403/400).

Workers are the protocol-faithful stub from fleet/faults.py — real
subprocesses on real Unix sockets, booting in ~0.3 s — so routing and
fencing are drilled over the real wire, not mocks."""

from __future__ import annotations

import base64
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from licensee_tpu.fleet.http_edge import HttpEdgeServer
from licensee_tpu.fleet.router import Router
from licensee_tpu.fleet.supervisor import Supervisor, worker_env
from licensee_tpu.fleet.wire import WireError, oneshot
from licensee_tpu.tenancy import (
    CorpusOnboarder,
    OnboardError,
    RegistryError,
    Tenant,
    TenantPools,
    TenantRegistry,
)

pytestmark = pytest.mark.usefixtures("lock_order_sanitizer")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB_ENV = {**os.environ, "PYTHONPATH": REPO_ROOT}


def stub_argv(sock: str, name: str, *extra: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.fleet.faults",
        "--socket", sock, "--name", name, *extra,
    ]


def wait_answering(sock: str, timeout: float = 15.0) -> None:
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            oneshot(sock, {"op": "stats"}, 1.0)
            return
        except WireError:
            time.sleep(0.02)
    raise AssertionError(f"stub on {sock} never answered")


class StubPools:
    """Spawn fingerprint-stamping stubs per pool; kill what survives."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.procs: dict[str, subprocess.Popen] = {}

    def spawn(self, name: str, fingerprint: str) -> str:
        sock = str(self.tmp_path / f"{name}.sock")
        self.procs[name] = subprocess.Popen(
            stub_argv(sock, name, "--fingerprint", fingerprint),
            env=STUB_ENV,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        wait_answering(sock)
        return sock

    def cleanup(self) -> None:
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait()


@pytest.fixture()
def stub_pools(tmp_path):
    pools = StubPools(tmp_path)
    yield pools
    pools.cleanup()


# -- the tenant registry -----------------------------------------------


def test_registry_round_trip_and_token_resolution(tmp_path):
    path = str(tmp_path / "tenants.json")
    reg = TenantRegistry(path, create=True)
    reg.set_tenant(Tenant("acme", "tok-acme", "vendored"), save=False)
    reg.set_tenant(Tenant("beta", "tok-beta", "spdx", pool="shared"))
    reg.close()

    loaded = TenantRegistry(path)
    try:
        # pool defaults to the tenant's own name; explicit pool sticks
        assert loaded.get("acme").pool == "acme"
        assert loaded.get("beta").pool == "shared"
        assert loaded.tokens() == {"tok-acme": "acme", "tok-beta": "beta"}
        assert loaded.by_token("tok-beta").name == "beta"
        assert loaded.by_token("tok-nobody") is None
        assert loaded.pools() == {"acme": ["acme"], "shared": ["beta"]}
    finally:
        loaded.close()


def test_registry_rejects_bad_configs(tmp_path):
    colliding = tmp_path / "collide.json"
    colliding.write_text(json.dumps({
        "version": 1,
        "tenants": {
            "a": {"token": "tok", "corpus": "vendored"},
            "b": {"token": "tok", "corpus": "spdx"},
        },
    }))
    with pytest.raises(RegistryError, match="token collision"):
        TenantRegistry(str(colliding))
    bad_default = tmp_path / "default.json"
    bad_default.write_text(json.dumps({
        "version": 1,
        "default_pool": "nope",
        "tenants": {"a": {"token": "tok", "corpus": "vendored"}},
    }))
    with pytest.raises(RegistryError, match="default_pool"):
        TenantRegistry(str(bad_default))
    from licensee_tpu.tenancy.registry import _parse_tenant

    with pytest.raises(RegistryError, match="missing 'token'"):
        _parse_tenant("x", {"corpus": "vendored"})


def test_registry_journal_pending_rolls(tmp_path):
    path = str(tmp_path / "tenants.json")
    reg = TenantRegistry(path, create=True)
    try:
        reg.set_tenant(Tenant("acme", "tok", "vendored"))
        reg.record_roll("roll_start", "acme", corpus="c1",
                        fingerprint="f1")
        reg.record_roll("roll_done", "acme", fingerprint="f1")
        reg.record_roll("roll_start", "acme", corpus="c2",
                        fingerprint="f2")
        pending = reg.pending_rolls()
        assert [row["fingerprint"] for row in pending] == ["f2"]
    finally:
        reg.close()


# -- the TenantPools facade --------------------------------------------


class _FakeHandle:
    def __init__(self, sock):
        self.socket_path = sock


class _FakeSupervisor:
    def __init__(self, workers):
        self.workers = {n: _FakeHandle(s) for n, s in workers.items()}
        self.router = None
        self.reloads: list = []

    def reload_fleet(self, corpus, **kwargs):
        self.reloads.append(corpus)
        return {"ok": True, "corpus": corpus, "workers": {}}


def test_tenant_pools_facade_merges_and_routes():
    a = _FakeSupervisor({"a0": "/tmp/a0.sock"})
    b = _FakeSupervisor({"b0": "/tmp/b0.sock"})
    pools = TenantPools({"A": a, "B": b}, default_pool="A")
    assert pools.workers == {"a0": "/tmp/a0.sock", "b0": "/tmp/b0.sock"}
    assert pools.worker_pools() == {"a0": "A", "b0": "B"}
    assert pools.pool_of("b0") == "B"
    # a named roll lands on that pool only; default goes to default_pool
    result = pools.reload_fleet("new-corpus", pool="B")
    assert result["ok"] and result["pool"] == "B"
    assert b.reloads == ["new-corpus"] and a.reloads == []
    pools.reload_fleet("other")
    assert a.reloads == ["other"]
    refused = pools.reload_fleet("x", pool="nope")
    assert not refused["ok"]
    assert refused["error"].startswith("unknown_pool")


def test_tenant_pools_rejects_colliding_worker_names():
    a = _FakeSupervisor({"w0": "/tmp/a.sock"})
    b = _FakeSupervisor({"w0": "/tmp/b.sock"})
    with pytest.raises(ValueError, match="fleet-unique"):
        TenantPools({"A": a, "B": b})


# -- router: corpus-tag routing + the fingerprint fence ----------------


def _two_pool_router(stub_pools, **kwargs):
    sockets = {
        "a0": stub_pools.spawn("a0", "fp-a-1"),
        "b0": stub_pools.spawn("b0", "fp-b-1"),
    }
    router = Router(
        sockets,
        probe_interval_s=0.05,
        request_timeout_s=5.0,
        dispatch_wait_s=5.0,
        pools={"a0": "A", "b0": "B"},
        default_pool="A",
        **kwargs,
    )
    router.set_corpus_route("A", "A")
    router.set_corpus_route("B", "B")
    router.set_corpus_route("fp-a-1", "A")
    router.set_corpus_route("fp-b-1", "B")
    return router


def test_router_routes_tagged_rows_and_defaults_untagged(stub_pools):
    with _two_pool_router(stub_pools) as router:
        tagged_b = router.dispatch(
            {"id": 1, "content": "x", "corpus": "B"}
        )
        assert tagged_b["worker"] == "b0"
        assert tagged_b["corpus"] == "fp-b-1"
        by_fp = router.dispatch(
            {"id": 2, "content": "x", "corpus": "fp-a-1"}
        )
        assert by_fp["worker"] == "a0"
        # untagged rows fall back to the default pool, never pool B
        for i in range(4):
            row = router.dispatch({"id": 10 + i, "content": "x"})
            assert row["worker"] == "a0", row
        unknown = router.dispatch(
            {"id": 99, "content": "x", "corpus": "ghost"}
        )
        assert str(unknown.get("error", "")).startswith("unknown_corpus")


def test_router_fingerprint_fence_blocks_wrong_corpus_rows(stub_pools):
    """The cross-pool cache-fencing regression: arm pool A's fence
    with a fingerprint its workers do NOT serve and every answer must
    be withheld from the client (failed over until no_backend_available)
    rather than delivered from the wrong corpus; disarming the fence
    (the mid-roll window) readmits the pool."""
    with _two_pool_router(stub_pools) as router:
        router.set_pool_fingerprint("A", "fp-a-1")
        router.set_pool_fingerprint("B", "fp-b-1")
        ok = router.dispatch({"id": 1, "content": "x", "corpus": "A"})
        assert ok["corpus"] == "fp-a-1"
        # the pool "serves" a fingerprint its workers don't stamp:
        # the stale row must never reach the client
        router.set_pool_fingerprint("A", "fp-a-NEXT")
        fenced = router.dispatch({"id": 2, "content": "x", "corpus": "A"})
        assert "error" in fenced, fenced
        assert "corpus fingerprint mismatch" in fenced["error"]
        # pool B is untouched by A's fence
        other = router.dispatch({"id": 3, "content": "x", "corpus": "B"})
        assert other["corpus"] == "fp-b-1"
        # disarm = the roll window: either fingerprint is admissible
        router.set_pool_fingerprint("A", None)
        rolled = router.dispatch({"id": 4, "content": "x", "corpus": "A"})
        assert rolled["corpus"] == "fp-a-1"
        assert router.pool_fingerprints().get("B") == "fp-b-1"


# -- the edge's POST /corpus auth tiers --------------------------------


def _read_response(reader):
    status_line = reader.readline()
    if not status_line:
        return None
    code = int(status_line.split(b" ")[1])
    headers = {}
    while True:
        line = reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        name, _, value = line.decode().partition(":")
        headers[name.strip().lower()] = value.strip()
    n = int(headers.get("content-length", "0"))
    body = reader.read(n) if n else b""
    return code, headers, body


def _post_corpus(port, token, payload: dict):
    body = json.dumps(payload).encode()
    lines = ["POST /corpus HTTP/1.1", "Host: edge"]
    if token:
        lines.append(f"Authorization: Bearer {token}")
    lines.append(f"Content-Length: {len(body)}")
    raw = ("\r\n".join(lines) + "\r\n\r\n").encode() + body
    sock = socket.create_connection(("127.0.0.1", port), timeout=10.0)
    try:
        sock.sendall(raw)
        reader = sock.makefile("rb")
        resp = _read_response(reader)
        reader.close()
        return resp
    finally:
        sock.close()


def test_edge_corpus_auth_tiers(tmp_path):
    sockets = {"a0": str(tmp_path / "a0.sock")}

    def argv_for(name, sock):
        return stub_argv(sock, name, "--fingerprint", "fp-a-1")

    supervisor = Supervisor(
        sockets, argv_for=argv_for,
        env_for=lambda name, chips: worker_env(None, None),
        probe_interval_s=0.1, backoff_base_s=0.1, backoff_max_s=1.0,
    )
    supervisor.start()
    assert supervisor.wait_healthy(30.0)
    router = Router(
        sockets, supervisor=supervisor, probe_interval_s=0.1,
        request_timeout_s=10.0, dispatch_wait_s=5.0, trace_sample=0.0,
        pools={"a0": "acme"}, default_pool="acme",
    )
    router.start()
    registry = TenantRegistry(str(tmp_path / "tenants.json"), create=True)
    registry.set_tenant(Tenant("acme", "tok-acme", "fp-a-1"))

    def validator(path):
        raise ValueError("not a corpus artifact")

    onboarder = CorpusOnboarder(
        registry, TenantPools({"acme": supervisor}), router,
        staging_dir=str(tmp_path / "staging"), validator=validator,
    )
    tokens = dict(registry.tokens())
    tokens["tok-anon"] = "anon"
    edge = HttpEdgeServer(
        "127.0.0.1:0", router, tokens=tokens, tenancy=onboarder,
        rate_per_client=10000.0, stall_timeout_s=1.0,
    )
    thread = threading.Thread(
        target=edge.serve_forever, kwargs={"poll_interval": 0.05},
        daemon=True,
    )
    thread.start()
    blob = base64.b64encode(b"garbage").decode("ascii")
    try:
        code, _, body = _post_corpus(
            edge.bound_port, "tok-wrong", {"artifact_b64": blob}
        )
        assert code == 401
        # a VALID token bound to no registry tenant: authenticated but
        # not a tenant — 403, not 401
        code, _, body = _post_corpus(
            edge.bound_port, "tok-anon", {"artifact_b64": blob}
        )
        assert code == 403
        assert json.loads(body)["error"].startswith("unknown_tenant")
        # the tenant's own token with a garbage artifact: the validator
        # rejects it before any fleet roll
        code, _, body = _post_corpus(
            edge.bound_port, "tok-acme", {"artifact_b64": blob}
        )
        assert code == 400
        assert json.loads(body)["error"].startswith("corpus_invalid")
        # token -> tenant -> pool resolution, the classify path's key
        assert onboarder.pool_for_client("acme") == "acme"
        assert onboarder.pool_for_client("anon") is None
    finally:
        edge.shutdown()
        edge.server_close()
        thread.join(timeout=5.0)
        router.close()
        supervisor.stop()
        registry.close()


def test_onboarder_rejects_unknown_tenant_upload(tmp_path):
    registry = TenantRegistry(str(tmp_path / "tenants.json"), create=True)
    try:
        onboarder = CorpusOnboarder(
            registry,
            TenantPools({"p": _FakeSupervisor({"w0": "/tmp/w0.sock"})}),
            router=None,
            staging_dir=str(tmp_path / "staging"),
        )
        with pytest.raises(OnboardError) as exc:
            onboarder.upload("ghost", b"bytes")
        assert exc.value.code == "unknown_tenant"
    finally:
        registry.close()
