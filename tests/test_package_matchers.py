"""Package-manager matcher edge cases — ports of the reference's
per-matcher specs (spec/licensee/matchers/*_matcher_spec.rb): quote and
whitespace variants, unknown-license -> `other`, license expressions ->
`other`, UNLICENSED -> `no-license`, and the format conversions
(CRAN GPL (>=2), DistZilla Mozilla_2_0, Cabal GPL-3, NuGet URLs)."""

from __future__ import annotations

import pytest

from licensee_tpu import matchers
from licensee_tpu.corpus.license import License
from licensee_tpu.project_files.license_file import LicenseFile


def match_key(matcher_cls, content, filename="LICENSE.txt"):
    m = matcher_cls(LicenseFile(content, filename))
    lic = m.match
    return lic.key if lic is not None else None


# -- NpmBower (npm_bower_matcher_spec.rb) --

@pytest.mark.parametrize("content", [
    '"license": "mit"',
    "'license': 'mit'",
    "'license': \"mit\"",
    "'license' : 'mit'",
    "'license':'mit'",
    " 'license':'mit'",
])
def test_npm_quote_variants(content):
    assert match_key(matchers.NpmBower, content) == "mit"


def test_npm_no_field_unknown_expression_unlicensed():
    assert match_key(matchers.NpmBower, "foo: bar") is None
    assert match_key(matchers.NpmBower, "'license': 'foo'") == "other"
    assert (
        match_key(
            matchers.NpmBower, "'license': '(MIT OR Apache-2.0 OR AGPL-3.0+)'"
        )
        == "other"
    )
    assert (
        match_key(matchers.NpmBower, "'license': 'UNLICENSED'")
        == "no-license"
    )


def test_npm_confidence():
    m = matchers.NpmBower(LicenseFile('"license": "mit"', "package.json"))
    assert m.confidence == 90


# -- Gemspec (gemspec_matcher_spec.rb) --

@pytest.mark.parametrize("content", [
    "s.license = 'mit'",
    "spec.license = 'mit'",
    's.license = "mit"',
    "s.license='mit'",
    "s.license = 'MIT'",
    "s.licenses = ['mit']",
    "s.license = 'mit'.freeze",
])
def test_gemspec_declaration_variants(content):
    assert match_key(matchers.Gemspec, content, "project.gemspec") == "mit"


def test_gemspec_edge_cases():
    assert match_key(matchers.Gemspec, "s.foo = 'bar'") is None
    assert match_key(matchers.Gemspec, "s.license = 'foo'") == "other"
    # multiple licenses in the array form -> other
    assert (
        match_key(matchers.Gemspec, "s.licenses = ['mit', 'bsd-3-clause']")
        == "other"
    )


# -- Cran (cran_matcher_spec.rb) --

@pytest.mark.parametrize("declaration,key", [
    ("MIT", "mit"),
    ("MIT + file LICENSE", "mit"),
    ("GPL (>=2)", "gpl-2.0"),
    ("GPL( >= 2 )", "gpl-2.0"),
    ("GPL (>=2) + file LICENSE", "gpl-2.0"),
    ("GPL (>=3)", "gpl-3.0"),
    ("GPL-2", "gpl-2.0"),
    ("GPL-3", "gpl-3.0"),
    ("Foo", "other"),
])
def test_cran_declarations(declaration, key):
    content = f"Package: test\nLicense: {declaration}"
    assert match_key(matchers.Cran, content, "DESCRIPTION") == key


def test_cran_no_field():
    assert match_key(matchers.Cran, "Package: test", "DESCRIPTION") is None


# -- Cargo (cargo_matcher_spec.rb) --

@pytest.mark.parametrize("content,key", [
    ('license = "MIT"', "mit"),
    ("license = 'mit'", "mit"),
    ("'license' = 'mit'", "mit"),
    ('"license"="mit"', "mit"),
    ("license='mit'", "mit"),
    (" license = 'mit'", "mit"),
    ('license = "Foo"', "other"),
    ('license = "Apache-2.0/MIT"', "other"),
    ('license = "Apache-2.0 OR MIT"', "other"),
    ('license = "(Apache-2.0 OR MIT)"', "other"),
])
def test_cargo_declarations(content, key):
    assert match_key(matchers.Cargo, content, "Cargo.toml") == key


def test_cargo_no_field():
    assert match_key(matchers.Cargo, 'foo = "bar"', "Cargo.toml") is None


# -- DistZilla (dist_zilla_matcher_spec.rb) --

@pytest.mark.parametrize("content,key", [
    ("license = MIT", "mit"),
    ("license = Mozilla_2_0", "mpl-2.0"),
    ("license = Foo", "other"),
])
def test_distzilla_declarations(content, key):
    assert match_key(matchers.DistZilla, content, "dist.ini") == key


def test_distzilla_no_field():
    assert match_key(matchers.DistZilla, "foo = bar", "dist.ini") is None


# -- Spdx (spdx_matcher_spec.rb) --

def test_spdx_declarations():
    assert (
        match_key(matchers.Spdx, "PackageLicenseDeclared: MIT") == "mit"
    )
    assert match_key(matchers.Spdx, "foo: bar") is None
    assert (
        match_key(matchers.Spdx, "PackageLicenseDeclared: xyz") == "other"
    )
    assert (
        match_key(matchers.Spdx, "PackageLicenseDeclared: (MIT OR Apache-2.0)")
        == "other"
    )


# -- Cabal (cabal_matcher_spec.rb) --

@pytest.mark.parametrize("content", [
    "license: mit",
    "license : mit",
    "license:mit",
    " license:mit",
])
def test_cabal_declaration_variants(content):
    assert match_key(matchers.Cabal, content) == "mit"


@pytest.mark.parametrize("declared,key", [
    ("GPL-3", "gpl-3.0"),
    ("GPL-2", "gpl-2.0"),
    ("LGPL-2.1", "lgpl-2.1"),
    ("LGPL-3", "lgpl-3.0"),
    ("AGPL-3", "agpl-3.0"),
    ("BSD2", "bsd-2-clause"),
    ("BSD3", "bsd-3-clause"),
])
def test_cabal_conversions(declared, key):
    assert match_key(matchers.Cabal, f"license: {declared}") == key


# -- NuGet (nu_get_matcher_spec.rb) --

@pytest.mark.parametrize("content", [
    '<license type="expression">mit</license>',
    "<license type='expression'>mit</license>",
    '<license  type = "expression" >mit</license >',
    ' <license type="expression">mit</license>',
])
def test_nuget_expression_variants(content):
    assert match_key(matchers.NuGet, content, "foo.nuspec") == "mit"


def test_nuget_edge_cases():
    assert (
        match_key(matchers.NuGet, "<file>wrongelement</file>", "foo.nuspec")
        is None
    )
    assert (
        match_key(
            matchers.NuGet,
            '<license type="expression">foo</license>',
            "foo.nuspec",
        )
        == "other"
    )
    assert (
        match_key(
            matchers.NuGet,
            '<license type="expression">BSD-2-Clause OR MIT</license>',
            "foo.nuspec",
        )
        == "other"
    )


@pytest.mark.parametrize("content", [
    "<licenseUrl>https://licenses.nuget.org/Apache-2.0</licenseUrl>",
    "<licenseUrl>http://licenses.nuget.org/Apache-2.0</licenseUrl>",
    "<licenseUrl>https://opensource.org/licenses/Apache-2.0</licenseUrl>",
    "<licenseUrl>http://www.opensource.org/licenses/Apache-2.0</licenseUrl>",
    "<licenseUrl>https://spdx.org/licenses/Apache-2.0</licenseUrl>",
    "<licenseUrl>http://www.spdx.org/licenses/Apache-2.0</licenseUrl>",
    "<licenseUrl>https://spdx.org/licenses/Apache-2.0.html</licenseUrl>",
    "<licenseUrl>https://spdx.org/licenses/Apache-2.0.txt</licenseUrl>",
    "<licenseUrl>https://apache.org/licenses/LICENSE-2.0</licenseUrl>",
    "<licenseUrl>http://www.apache.org/licenses/LICENSE-2.0</licenseUrl>",
    "<licenseUrl>https://apache.org/licenses/LICENSE-2.0.txt</licenseUrl>",
])
def test_nuget_license_urls(content):
    assert match_key(matchers.NuGet, content, "foo.nuspec") == "apache-2.0"


# -- base matcher contract (matcher_spec.rb) --

def test_matcher_name_and_potential_matches():
    m = matchers.NpmBower(LicenseFile('"license": "mit"', "package.json"))
    assert m.name == "npmbower"
    pool = m.potential_matches
    assert License.find("mit") in pool
    assert all(not lic.pseudo_license for lic in pool)
