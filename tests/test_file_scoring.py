"""Filename-scoring and content-extraction tables — ports of the
reference's `license_file_spec.rb`, `readme_file_spec.rb`, and
`package_manager_file_spec.rb` parametrized pins."""

from __future__ import annotations

import pytest

from licensee_tpu import matchers
from licensee_tpu.project_files.license_file import LicenseFile
from licensee_tpu.project_files.package_manager_file import PackageManagerFile
from licensee_tpu.project_files.readme_file import ReadmeFile

# license_file_spec.rb "filename scoring": the full 32-entry table
LICENSE_SCORES = {
    "license": 1.00,
    "LICENCE": 1.00,
    "unLICENSE": 1.00,
    "unlicence": 1.00,
    "license.md": 0.95,
    "LICENSE.md": 0.95,
    "license.txt": 0.95,
    "COPYING": 0.90,
    "copyRIGHT": 0.35,
    "COPYRIGHT.txt": 0.30,
    "copying.txt": 0.85,
    "LICENSE.MPL-2.0": 0.80,
    "LICENSE.php": 0.80,
    "LICENCE.docs": 0.80,
    "license.xml": 0.80,
    "copying.image": 0.75,
    "LICENSE-MIT": 0.70,
    "LICENSE_1_0.txt": 0.70,
    "COPYING-GPL": 0.65,
    "COPYRIGHT-BSD": 0.20,
    "MIT-LICENSE.txt": 0.60,
    "mit-license-foo.md": 0.60,
    "OFL.md": 0.50,
    "ofl.textile": 0.45,
    "ofl": 0.40,
    "not-the-ofl": 0.00,
    "README.txt": 0.00,
    ".pip-license-ignore": 0.00,
    "license-checks.xml": 0.00,
    "license_test.go": 0.00,
    "licensee.gemspec": 0.00,
    "LICENSE.spdx": 0.00,
}


@pytest.mark.parametrize(
    "filename,score", LICENSE_SCORES.items(), ids=list(LICENSE_SCORES)
)
def test_license_filename_score(filename, score):
    assert LicenseFile.name_score(filename) == score


@pytest.mark.parametrize("filename,score", [
    ("COPYING.lesser", 1),
    ("copying.lesser", 1),
    ("license.lesser", 0),
    ("LICENSE.md", 0),
    ("FOO.md", 0),
])
def test_lesser_gpl_score(filename, score):
    assert LicenseFile.lesser_gpl_score(filename) == score


# readme_file_spec.rb name scoring + license_content extraction

@pytest.mark.parametrize("filename,score", [
    ("readme", 1.0),
    ("README", 1.0),
    ("readme.md", 0.9),
    ("README.md", 0.9),
    ("readme.txt", 0.9),
    ("readme.mdown", 0.9),
    ("readme.rdoc", 0.9),
    ("readme.rst", 0.9),
    ("LICENSE", 0.0),
])
def test_readme_name_score(filename, score):
    assert ReadmeFile.name_score(filename) == score


EXTRACTIONS = {
    "no license": ("There is no License in this README", None),
    "after an H1": ("# License\n\nhello world", "hello world"),
    "after an H2": ("## License\n\nhello world", "hello world"),
    "underlined header": ("License\n-------\n\nhello world", "hello world"),
    "strange case": ("## LICENSE\n\nhello world", "hello world"),
    "british spelling": ("## Licence\n\nhello world", "hello world"),
    "trailing content": (
        "## License\n\nhello world\n\n# Contributing",
        "hello world",
    ),
    "trailing underlined": (
        "# License\n\nhello world\n\nContributing\n====",
        "hello world",
    ),
    "trailing colon": ("## License:\n\nhello world", "hello world"),
    "trailing hashes": ("## License ##\n\nhello world", "hello world"),
    "rdoc": ("== License:\n\nhello world", "hello world"),
}


@pytest.mark.parametrize(
    "content,expected", EXTRACTIONS.values(), ids=list(EXTRACTIONS)
)
def test_readme_license_content(content, expected):
    assert ReadmeFile.license_content(content) == expected


def test_readme_reference_match():
    file = ReadmeFile("The MIT License", "README.md")
    assert file.license is not None and file.license.key == "mit"


# package_manager_file_spec.rb

@pytest.mark.parametrize("filename,score", [
    ("licensee.gemspec", 1.0),
    ("test.cabal", 1.0),
    ("package.json", 1.0),
    ("Cargo.toml", 1.0),
    ("DESCRIPTION", 0.9),
    ("dist.ini", 0.8),
    ("bower.json", 0.75),
    ("elm-package.json", 0.70),
    ("README.md", 0.0),
])
def test_package_manager_name_score(filename, score):
    assert PackageManagerFile.name_score(filename) == score


@pytest.mark.parametrize("filename,expected", [
    ("project.gemspec", [matchers.Gemspec]),
    ("test.cabal", [matchers.Cabal]),
    ("package.json", [matchers.NpmBower]),
    ("Cargo.toml", [matchers.Cargo]),
    ("DESCRIPTION", [matchers.Cran]),
    ("dist.ini", [matchers.DistZilla]),
    ("LICENSE.spdx", [matchers.Spdx]),
    ("foo.nuspec", [matchers.NuGet]),
    ("README.md", []),
])
def test_package_manager_matcher_dispatch(filename, expected):
    pf = PackageManagerFile("", filename)
    assert pf.possible_matchers == expected


# license_file_spec.rb attribution + CC-false-positive behaviors

def test_attribution_cases():
    from tests.conftest import sub_copyright_info
    from licensee_tpu.corpus.license import License

    mit = License.find("mit")
    file = LicenseFile(sub_copyright_info(mit), "LICENSE.txt")
    assert file.attribution == "Copyright (c) 2018 Ben Balter"

    # a random mid-file copyright-like line doesn't count
    assert (
        LicenseFile("Foo\nCopyright 2016 Ben Balter\nBar", "LICENSE.txt")
        .attribution
        is None
    )
    # a non-templated license has no attribution
    gpl = License.find("gpl-3.0")
    assert LicenseFile(sub_copyright_info(gpl), "LICENSE.txt").attribution is None
    # a COPYRIGHT file whose whole content is the notice
    f = LicenseFile("Copyright (C) 2015 Ben Balter", "COPYRIGHT")
    assert f.attribution == "Copyright (C) 2015 Ben Balter"


def test_cc_false_positive_regex():
    from tests.conftest import sub_copyright_info
    from licensee_tpu.corpus.license import License

    mit_file = LicenseFile(
        sub_copyright_info(License.find("mit")), "LICENSE.txt"
    )
    assert not mit_file.potential_false_positive
    cc = LicenseFile(
        "Creative Commons Attribution-NonCommercial 4.0", "LICENSE.txt"
    )
    assert cc.potential_false_positive


def test_readme_license_content_matches_one_shot_regex():
    """license_content runs CONTENT_REGEX's halves as two linear scans
    (plus a `licen` substring pre-check) for speed; this differential
    pins it byte-equal to the one-shot regex over adversarial header
    shapes (readme_file.rb:6-16 is the semantic source)."""
    import random

    from licensee_tpu.project_files.readme_file import CONTENT_REGEX
    from licensee_tpu.rubytext import ruby_strip

    def one_shot(content):
        m = CONTENT_REGEX.search(content)
        return ruby_strip(m.group(1)) if m else None

    shapes = [
        "# T\n\n## License\n\nMIT.\n\n## Usage\n\nrun\n",
        "License\n-------\nbody here\nNext\n====\nx\n",
        "= License =\nrdoc body\n= Next\n",
        "## LICENCE:\ntext",
        "## License",
        "## License\n",
        "no section at all\n",
        "#License\nnot a heading (no space)\n",
        "underlined\n--\n## license ##\ntail\nMore\n==\n",
        "## License\n\n" + "word " * 3000 + "\n## End\n",
        "licence:\n-\nbody\n",
        "\n\n## license\n\n\n\n",
        "## License\ntail with no terminator",
        "intro\nLicense\n=\nA\nB\n--\nC\n",
    ]
    rng = random.Random(7)
    toks = [
        "## License\n", "License\n---\n", "body text\n", "# H\n",
        "====\n", "word word\n", "\n", "x\n--\n", "= license\n",
        "licence:?\n", "## L ##\n", "LiCeNsE\n===\n",
    ]
    shapes += [
        "".join(rng.choice(toks) for _ in range(rng.randint(0, 12)))
        for _ in range(500)
    ]
    for s in shapes:
        assert ReadmeFile.license_content(s) == one_shot(s), repr(s[:80])
