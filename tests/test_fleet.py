"""The fleet tier (licensee_tpu/fleet/): supervisor restart/backoff/
drain, router least-loaded dispatch, failover under SIGKILL, hedged
requests, backpressure failover, trace-ID propagation, and the merged
Prometheus exposition.

All CPU-only (JAX_PLATFORMS=cpu via conftest) and fast: workers are
REAL subprocesses speaking the real JSONL protocol over real Unix
sockets — but they are the protocol-faithful stub from fleet/faults.py,
so a worker boots in ~0.3 s instead of a JAX import, and SIGKILL is a
real SIGKILL."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

from licensee_tpu.fleet import faults
from licensee_tpu.fleet.router import FrontServer, Router
from licensee_tpu.fleet.supervisor import Supervisor, worker_env
from licensee_tpu.fleet.wire import WireError, oneshot

# every test in this module runs under the lock-order sanitizer
# (tests/lock_sanitizer.py): router/supervisor/session locks must keep
# a consistent global acquisition order or the test fails with both
# stacks
pytestmark = pytest.mark.usefixtures("lock_order_sanitizer")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB_ENV = {**os.environ, "PYTHONPATH": REPO_ROOT}


def stub_argv(sock: str, name: str = "stub", *extra: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.fleet.faults",
        "--socket", sock, "--name", name, *extra,
    ]


def wait_answering(sock: str, timeout: float = 15.0) -> None:
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        try:
            oneshot(sock, {"op": "stats"}, 1.0)
            return
        except WireError:
            time.sleep(0.02)
    raise AssertionError(f"stub on {sock} never answered")


class StubFleet:
    """Spawn stub workers on demand; kill whatever survives the test."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.procs: dict[str, subprocess.Popen] = {}

    def spawn(self, name: str, *extra: str) -> str:
        sock = str(self.tmp_path / f"{name}.sock")
        self.procs[name] = subprocess.Popen(
            stub_argv(sock, name, *extra), env=STUB_ENV,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        wait_answering(sock)
        return sock

    def cleanup(self) -> None:
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait()


@pytest.fixture()
def stub_fleet(tmp_path):
    fleet = StubFleet(tmp_path)
    yield fleet
    fleet.cleanup()


# -- router: routing, failover, hedging, backpressure --


def test_router_dispatches_to_least_loaded(stub_fleet):
    # w_idle reports queue_depth 0, w_busy a standing queue of 50: every
    # request must land on the idle worker
    sockets = {
        "w_busy": stub_fleet.spawn("w_busy", "--report-load", "50"),
        "w_idle": stub_fleet.spawn("w_idle"),
    }
    with Router(sockets, probe_interval_s=0.05) as router:
        rows = [
            router.dispatch({"id": i, "content": f"b{i}"})
            for i in range(6)
        ]
    assert all(r.get("key") == "stub-mit" for r in rows)
    assert {r["worker"] for r in rows} == {"w_idle"}


def test_router_relays_the_diff_verb(stub_fleet):
    """The diff verb is stateless and idempotent, so the front door
    relays it to a worker like a content row (with the spliced trace
    echoed back through the pipelining cross-check)."""
    sockets = {"w0": stub_fleet.spawn("w0")}
    with Router(sockets, probe_interval_s=0.05) as router:
        row = router.dispatch(
            {"id": 7, "op": "diff", "content": "some license text"}
        )
    assert row["id"] == 7
    assert row["diff"]["key"] == "stub-mit"


def test_router_failover_on_worker_sigkill(stub_fleet):
    """Continuous load, one worker SIGKILLed mid-stream: zero client-
    visible errors — the dead worker's in-flight requests retry on the
    survivor."""
    sockets = {
        name: stub_fleet.spawn(name, "--service-ms", "20")
        for name in ("w0", "w1")
    }
    with Router(
        sockets, probe_interval_s=0.05, request_timeout_s=10.0,
        dispatch_wait_s=15.0,
    ) as router:
        rows: list[dict] = []
        lock = threading.Lock()

        def send(k: int) -> None:
            for i in range(k):
                row = router.dispatch({"id": i, "content": f"c{i}"})
                with lock:
                    rows.append(row)

        threads = [
            threading.Thread(target=send, args=(25,)) for _ in range(4)
        ]
        for t in threads:
            t.start()
        time.sleep(0.15)  # several requests in flight on each worker
        faults.kill(stub_fleet.procs["w0"].pid)
        for t in threads:
            t.join(timeout=60.0)
        assert len(rows) == 100
        errors = [r for r in rows if r.get("error")]
        assert errors == []
        stats = router.stats()
        assert stats["router"]["failovers"] >= 1
        assert stats["backends"]["w0"]["healthy"] is False


def test_router_fails_over_on_queue_full(stub_fleet):
    sockets = {
        "w_full": stub_fleet.spawn("w_full", "--queue-full"),
        "w_ok": stub_fleet.spawn("w_ok", "--report-load", "10"),
    }
    # w_full reports load 0 so it is picked FIRST; its queue_full must
    # fail over to w_ok rather than reach the client
    with Router(sockets, probe_interval_s=0.05) as router:
        row = router.dispatch({"id": 1, "content": "x"})
        assert row.get("key") == "stub-mit"
        assert row["worker"] == "w_ok"
        stats = router.stats()["router"]
        assert stats["queue_full_failovers"] >= 1


def test_router_surfaces_queue_full_when_every_replica_sheds(stub_fleet):
    sockets = {
        "a": stub_fleet.spawn("a", "--queue-full"),
        "b": stub_fleet.spawn("b", "--queue-full"),
    }
    with Router(sockets, probe_interval_s=0.05) as router:
        row = router.dispatch({"id": 9, "content": "x"})
    assert row["error"] == "queue_full"
    assert row["retry_after"] > 0
    assert row["id"] == 9


def test_hedged_request_winner_and_loser_accounting(stub_fleet):
    """Slow primary + fixed 50 ms hedge: the duplicate on the fast twin
    answers first (hedges_won); with the slow/fast roles flipped the
    primary answers first (hedges_lost)."""
    sockets = {
        "w_slow": stub_fleet.spawn("w_slow", "--service-ms", "800"),
        "w_fast": stub_fleet.spawn("w_fast", "--report-load", "5"),
    }
    # load 0 vs 5: the slow worker is picked first, the fast one hedges
    with Router(
        sockets, probe_interval_s=0.05, hedge_ms=50.0,
        request_timeout_s=10.0,
    ) as router:
        t0 = time.perf_counter()
        row = router.dispatch({"id": 1, "content": "hedge-me"})
        dt = time.perf_counter() - t0
        assert row.get("key") == "stub-mit"
        assert row["worker"] == "w_fast"  # the hedge won
        assert dt < 5.0  # nowhere near the slow worker's 800 ms
        stats = router.stats()["router"]
        assert stats["hedges_started"] == 1
        assert stats["hedges_won"] == 1
        assert stats["hedges_lost"] == 0

    # flipped roles: primary answers at 200 ms — after the 50 ms hedge
    # fires (so a hedge definitely starts) but long before the hedge
    # target's 800 ms service — the primary wins, the hedge loses
    sockets_flipped = {
        "w_mid": stub_fleet.spawn("w_mid", "--service-ms", "200"),
        "w_slow2": stub_fleet.spawn("w_slow2", "--service-ms", "800",
                                    "--report-load", "5"),
    }
    with Router(
        sockets_flipped, probe_interval_s=0.05, hedge_ms=50.0,
        request_timeout_s=10.0,
    ) as router:
        row = router.dispatch({"id": 2, "content": "hedge-me-2"})
        assert row["worker"] == "w_mid"  # the primary won
        stats = router.stats()["router"]
        assert stats["hedges_started"] == 1
        assert stats["hedges_lost"] == 1
        assert stats["hedges_won"] == 0


def test_hedge_rescues_a_hung_worker(stub_fleet):
    """A worker that goes silent AFTER its health probe looks fine is
    exactly what hedging exists for (health checks cannot see it)."""
    sockets = {
        "w_wedge": stub_fleet.spawn("w_wedge", "--hang-after", "1"),
        "w_live": stub_fleet.spawn("w_live", "--report-load", "5"),
    }
    with Router(
        sockets, probe_interval_s=0.05, hedge_ms=50.0,
        request_timeout_s=20.0,
    ) as router:
        first = router.dispatch({"id": 1, "content": "warm"})
        assert first["worker"] == "w_wedge"  # answer #1, then silence
        t0 = time.perf_counter()
        row = router.dispatch({"id": 2, "content": "now-hangs"})
        dt = time.perf_counter() - t0
    assert row.get("key") == "stub-mit"
    assert row["worker"] == "w_live"
    assert dt < 10.0  # hedge delay + service, not the request timeout


def test_trace_id_propagates_router_to_worker(stub_fleet):
    """The router-minted 16-hex ID must appear on the client row, in
    the router's trace tail (with a route span), and in the WORKER's
    own trace tail — the cross-process join."""
    sockets = {"w0": stub_fleet.spawn("w0")}
    with Router(sockets, probe_interval_s=0.05, trace_sample=1.0) as router:
        rows = [
            router.dispatch({"id": i, "content": f"t{i}"})
            for i in range(3)
        ]
        router_tail = router.trace_tail(10)
    client_ids = [r.get("trace") for r in rows]
    assert all(
        isinstance(t, str) and len(t) == 16 for t in client_ids
    )
    assert len(set(client_ids)) == 3
    routed = {
        t["trace"]: [s["name"] for s in t["spans"]] for t in router_tail
    }
    for trace_id in client_ids:
        assert "route" in routed[trace_id]
    worker_tail = oneshot(sockets["w0"], {"op": "trace", "n": 10}, 2.0)
    worker_ids = {t["trace"] for t in worker_tail["traces"]}
    assert set(client_ids) <= worker_ids


def test_front_socket_session_end_to_end(stub_fleet, tmp_path):
    """A client session through the FrontServer: ordered responses,
    fleet stats verb, merged prometheus verb, trace verb, bad lines."""
    sockets = {"w0": stub_fleet.spawn("w0")}
    front = str(tmp_path / "front.sock")
    with Router(sockets, probe_interval_s=0.05, trace_sample=1.0) as router:
        server = FrontServer(front, router)
        thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        thread.start()
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.connect(front)
                s.settimeout(10.0)
                f = s.makefile("rwb")
                for row in (
                    {"id": 1, "content": "one"},
                    {"id": 2, "content": "two"},
                    {"id": 3, "op": "stats"},
                    {"id": 4, "op": "stats", "format": "prometheus"},
                    {"id": 5, "op": "trace", "n": 5},
                    {"id": 6, "op": "nope"},
                    # the word-diff verb relays through the front door
                    # like a content row (stateless, any worker)
                    {"id": 7, "op": "diff", "content": "blob"},
                ):
                    f.write(json.dumps(row).encode() + b"\n")
                f.flush()
                rows = [json.loads(f.readline()) for _ in range(7)]
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)
    assert [r["id"] for r in rows] == [1, 2, 3, 4, 5, 6, 7]
    assert rows[0]["key"] == "stub-mit" and rows[1]["key"] == "stub-mit"
    assert rows[6]["diff"]["key"] == "stub-mit"
    fleet_stats = rows[2]["stats"]
    assert fleet_stats["router"]["ok"] >= 2
    assert fleet_stats["backends"]["w0"]["healthy"] is True
    from licensee_tpu.obs import check_exposition

    merged = rows[3]["prometheus"]
    assert check_exposition(merged) == []
    assert 'worker="w0"' in merged and 'worker="router"' in merged
    # the router's per-backend series use a "backend" label so the
    # merge's injected worker label is never duplicated
    assert 'fleet_backend_requests_total{worker="router",backend="w0"' in (
        merged
    )
    for line in merged.splitlines():
        assert line.count('worker="') <= 1, line
    assert rows[4]["traces"]
    assert rows[5]["error"].startswith("bad_request")


# -- supervisor: restart, backoff, wedge, drain --


def test_supervisor_restarts_crashed_worker(tmp_path):
    sockets = {"w0": str(tmp_path / "w0.sock")}
    with Supervisor(
        sockets,
        argv_for=lambda name, sock: stub_argv(sock, name),
        env_for=lambda name, chips: dict(STUB_ENV),
        probe_interval_s=0.05, backoff_base_s=0.1, backoff_max_s=1.0,
        startup_grace_s=15.0,
    ) as supervisor:
        assert supervisor.wait_healthy(15.0)
        first_pid = supervisor.workers["w0"].pid
        faults.kill(first_pid)
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            handle = supervisor.workers["w0"]
            if (
                handle.restarts >= 1
                and handle.pid not in (None, first_pid)
                and supervisor.probe("w0") is not None
            ):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"w0 never restarted: {supervisor.status()}"
            )
        assert supervisor.workers["w0"].exit_codes[-1] == -9


def test_supervisor_backoff_schedule_is_exponential_and_capped():
    sup = Supervisor(
        {"w0": "/nonexistent.sock"},
        argv_for=lambda name, sock: ["true"],
        backoff_base_s=0.25, backoff_max_s=10.0,
    )
    delays = [sup.backoff_delay_s(n) for n in range(8)]
    assert delays[:4] == [0.25, 0.5, 1.0, 2.0]
    assert delays[-1] == 10.0  # capped
    assert all(b >= a for a, b in zip(delays, delays[1:]))


def test_supervisor_kills_wedged_worker(tmp_path):
    """SIGSTOP: the process is alive, probes time out — the supervisor
    must declare it wedged, SIGKILL it, and bring up a replacement."""
    sockets = {"w0": str(tmp_path / "w0.sock")}
    with Supervisor(
        sockets,
        argv_for=lambda name, sock: stub_argv(sock, name),
        env_for=lambda name, chips: dict(STUB_ENV),
        probe_interval_s=0.05, probe_timeout_s=0.3, wedged_after=2,
        backoff_base_s=0.1, backoff_max_s=1.0, startup_grace_s=15.0,
    ) as supervisor:
        assert supervisor.wait_healthy(15.0)
        frozen_pid = supervisor.workers["w0"].pid
        faults.hang(frozen_pid)
        deadline = time.perf_counter() + 20.0
        while time.perf_counter() < deadline:
            handle = supervisor.workers["w0"]
            if handle.pid not in (None, frozen_pid) and (
                supervisor.probe("w0") is not None
            ):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"wedged w0 never replaced: {supervisor.status()}"
            )
        assert supervisor.workers["w0"].restarts >= 1


def test_drain_completes_in_flight_before_sigterm(tmp_path):
    """Drain must (1) stop the router dispatching to the worker,
    (2) wait for the in-flight request to answer, and only then
    (3) SIGTERM — the client sees a verdict, never a reset."""
    sockets = {"w0": str(tmp_path / "w0.sock")}
    with Supervisor(
        sockets,
        argv_for=lambda name, sock: stub_argv(
            sock, name, "--service-ms", "400"
        ),
        env_for=lambda name, chips: dict(STUB_ENV),
        probe_interval_s=0.05, startup_grace_s=15.0,
    ) as supervisor:
        assert supervisor.wait_healthy(15.0)
        with Router(
            sockets, supervisor=supervisor, probe_interval_s=0.05,
        ) as router:
            result: dict = {}

            def slow_request() -> None:
                result.update(router.dispatch(
                    {"id": 1, "content": "slow"}
                ))

            t = threading.Thread(target=slow_request)
            t.start()
            # the request is mid-service (400 ms) when drain begins
            time.sleep(0.1)
            t_drain = time.perf_counter()
            clean = supervisor.drain("w0", timeout_s=10.0, restart=False)
            drain_s = time.perf_counter() - t_drain
            t.join(timeout=10.0)
            assert clean is True
            assert result.get("key") == "stub-mit"  # in-flight answered
            assert drain_s >= 0.2  # drain WAITED for the in-flight work
            assert supervisor.workers["w0"].state == "stopped"
            assert supervisor.workers["w0"].exit_codes[-1] == -15  # SIGTERM
            # a drained (stopped) worker must never be picked again
            assert router.pick() is None


def test_worker_env_exports_chip_subset_via_apply_visible_chips():
    """The fleet worker env contract IS the offline co-located
    contract: LICENSEE_TPU_VISIBLE_CHIPS -> TPU_VISIBLE_DEVICES +
    the CPU-rehearsal XLA flag, derived in the CHILD env dict."""
    env = worker_env({"PATH": "/bin"}, ["4", "5"])
    assert env["LICENSEE_TPU_VISIBLE_CHIPS"] == "4,5"
    assert env["TPU_VISIBLE_DEVICES"] == "4,5"
    assert "--xla_force_host_platform_device_count=2" in env["XLA_FLAGS"]
    assert "PYTHONPATH" in env
    # and the translation never leaked into THIS process
    assert os.environ.get("TPU_VISIBLE_DEVICES") != "4,5"


def test_supervisor_assigns_disjoint_chip_ranges(tmp_path):
    sup = Supervisor(
        {
            "w0": str(tmp_path / "w0.sock"),
            "w1": str(tmp_path / "w1.sock"),
        },
        argv_for=lambda name, sock: ["true"],
        chips_per_worker=2,
    )
    chips = [
        sup.workers[w].env["LICENSEE_TPU_VISIBLE_CHIPS"]
        for w in ("w0", "w1")
    ]
    assert chips == ["0,1", "2,3"]
    devices = [
        sup.workers[w].env["TPU_VISIBLE_DEVICES"] for w in ("w0", "w1")
    ]
    assert devices == ["0,1", "2,3"]


def test_supervisor_add_and_remove_worker_live(tmp_path):
    """The fleet autoscaler's mechanics: a runtime-added worker is
    spawned from the same argv/env ingredients as the seed fleet,
    joins health probing, and a removal drains it cleanly without
    disturbing the seed workers."""
    sockets = {"w0": str(tmp_path / "w0.sock")}
    with Supervisor(
        sockets,
        argv_for=lambda name, sock: stub_argv(sock, name),
        env_for=lambda name, chips: dict(STUB_ENV),
        probe_interval_s=0.05, backoff_base_s=0.1, backoff_max_s=1.0,
        startup_grace_s=15.0,
    ) as supervisor:
        assert supervisor.wait_healthy(15.0)
        handle = supervisor.add_worker(
            "auto0", str(tmp_path / "auto0.sock")
        )
        assert handle.name == "auto0"
        with pytest.raises(ValueError):
            supervisor.add_worker("auto0", str(tmp_path / "dup.sock"))
        assert supervisor.wait_healthy(15.0)  # the add joins probing
        assert supervisor.probe("auto0") is not None
        assert supervisor.remove_worker("auto0") is True
        assert "auto0" not in supervisor.workers
        # a probe raced against the removal answers None, never raises
        assert supervisor.probe("auto0") is None
        # the seed worker is untouched
        assert supervisor.probe("w0") is not None


# -- merged exposition (obs/export.py merge) --


def test_merge_expositions_labels_and_grammar():
    from licensee_tpu.obs import (
        MetricsRegistry,
        check_exposition,
        merge_expositions,
        render_prometheus,
    )

    per = {}
    for worker in ("w0", "w1"):
        reg = MetricsRegistry()
        reg.counter("serve_requests_total", "Reqs", labels=("event",)) \
            .labels(event="submitted").inc(3)
        reg.gauge("serve_queue_depth", "Depth").set(2)
        reg.histogram("serve_stage_seconds", "Lat", labels=("stage",)) \
            .labels(stage="total").observe(0.01)
        per[worker] = render_prometheus(reg)
    merged = merge_expositions(per)
    assert check_exposition(merged) == []
    assert (
        'serve_requests_total{worker="w0",event="submitted"} 3' in merged
    )
    assert (
        'serve_requests_total{worker="w1",event="submitted"} 3' in merged
    )
    assert 'serve_queue_depth{worker="w1"} 2' in merged
    # histogram children land under their family with the label injected
    assert 'serve_stage_seconds_bucket{worker="w0",stage="total",' in merged
    assert 'serve_stage_seconds_count{worker="w0",stage="total"} 1' in merged
    # HELP/TYPE emitted once per family, not once per source
    assert merged.count("# TYPE serve_requests_total counter") == 1


def test_merge_expositions_never_duplicates_the_merge_label():
    """A source already exporting series WITH the merge label (the
    router's own per-backend families once did) must not gain a second
    'worker' label — Prometheus rejects duplicate label names
    scrape-wide."""
    from licensee_tpu.obs import check_exposition, merge_expositions

    merged = merge_expositions({
        "router": (
            "# TYPE x counter\n"
            'x{worker="w0",outcome="ok"} 3\n'
            'x{outcome="failed"} 1\n'
        ),
    })
    assert check_exposition(merged) == []
    assert 'x{worker="w0",outcome="ok"} 3' in merged  # kept as-is
    assert 'x{worker="router",outcome="failed"} 1' in merged  # injected
    assert 'worker="router",worker=' not in merged


def test_merge_expositions_handles_empty_and_unlabeled_sources():
    from licensee_tpu.obs import check_exposition, merge_expositions

    merged = merge_expositions({
        "a": "# HELP x X.\n# TYPE x counter\nx 1\n",
        "b": "",
        "c": "bare_metric 7\n",  # no comments: still merged + labeled
    })
    assert check_exposition(merged) == []
    assert 'x{worker="a"} 1' in merged
    assert 'bare_metric{worker="c"} 7' in merged
    assert merge_expositions({}) == ""


# -- the full story, in one go --


def test_fleet_selftest_stub_mode_passes():
    from licensee_tpu.fleet.selftest import selftest

    assert selftest(verbose=False, stub=True) == 0


# -- corpus lifecycle: rolling reload, rollback, argv patching --


def _reload_supervisor(tmp_path, extra_for=None):
    """A 2-stub supervisor for the reload drills; ``extra_for`` maps a
    worker name to extra stub argv (e.g. a --reload-deny script)."""
    sockets = {
        "w0": str(tmp_path / "w0.sock"),
        "w1": str(tmp_path / "w1.sock"),
    }
    extra_for = extra_for or {}

    def argv(name, sock):
        return stub_argv(
            sock, name, "--fingerprint", "fp-old",
            *extra_for.get(name, ()),
        )

    return Supervisor(
        sockets,
        argv_for=argv,
        env_for=lambda name, chips: dict(STUB_ENV),
        probe_interval_s=0.05, backoff_base_s=0.1, backoff_max_s=1.0,
        startup_grace_s=15.0,
    )


def _stub_patch(argv, corpus):
    out = list(argv)
    out[out.index("--fingerprint") + 1] = corpus
    return out


def _worker_fps(supervisor):
    return {
        name: ((supervisor.probe(name) or {}).get("corpus") or {}).get(
            "fingerprint"
        )
        for name in supervisor.workers
    }


def test_reload_fleet_rolls_every_worker_and_patches_argv(tmp_path):
    with _reload_supervisor(tmp_path) as supervisor:
        assert supervisor.wait_healthy(15.0)
        out = supervisor.reload_fleet(
            "fp-new", timeout_s=10.0, health_timeout_s=10.0,
            argv_patch=_stub_patch,
        )
        assert out["ok"] and not out["rolled_back"]
        assert out["fingerprint"] == "fp-new"
        assert _worker_fps(supervisor) == {"w0": "fp-new", "w1": "fp-new"}
        # a crash-restarted worker must rejoin on the ROLLED corpus,
        # not its launch-time one: the roll patched its respawn argv
        first_pid = supervisor.workers["w0"].pid
        faults.kill(first_pid)
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            if (
                supervisor.workers["w0"].pid not in (None, first_pid)
                and supervisor.probe("w0") is not None
            ):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(f"w0 never respawned: {supervisor.status()}")
        assert _worker_fps(supervisor)["w0"] == "fp-new"


def test_reload_fleet_rolls_back_on_mid_roll_refusal(tmp_path):
    # w1 refuses any "deny-*" corpus (the injected validation failure):
    # w0 swaps first, w1 refuses, and the budget-exceeded roll must
    # return w0 to the old corpus — fleet healthy on the OLD fingerprint
    with _reload_supervisor(
        tmp_path, extra_for={"w1": ("--reload-deny", "deny-")}
    ) as supervisor:
        assert supervisor.wait_healthy(15.0)
        out = supervisor.reload_fleet(
            "deny-fp", timeout_s=10.0, health_timeout_s=10.0,
            argv_patch=_stub_patch,
        )
        assert not out["ok"]
        assert out["rolled_back"]
        assert out["fingerprint"] is None
        assert out["workers"]["w0"]["ok"]
        assert out["workers"]["w0"]["rolled_back"]
        assert not out["workers"]["w1"]["ok"]
        assert _worker_fps(supervisor) == {"w0": "fp-old", "w1": "fp-old"}
        # the rollback also restored w0's respawn argv
        assert "deny-fp" not in supervisor.workers["w0"].argv


def test_reload_fleet_corrupt_source_fails_closed(tmp_path):
    with _reload_supervisor(tmp_path) as supervisor:
        assert supervisor.wait_healthy(15.0)
        out = supervisor.reload_fleet(
            "corrupt:artifact", timeout_s=10.0, health_timeout_s=10.0,
            argv_patch=_stub_patch,
        )
        assert not out["ok"] and not out["rolled_back"]
        assert "injected refusal" in out["workers"]["w0"]["error"]
        assert _worker_fps(supervisor) == {"w0": "fp-old", "w1": "fp-old"}


def test_reload_fleet_dead_worker_mid_swap_rolls_back(tmp_path):
    # SIGKILL w0 while it sleeps inside a slow reload verb: the roll
    # fails on the transport, nothing was swapped, the supervisor
    # respawns w0 on the old corpus
    with _reload_supervisor(tmp_path) as supervisor:
        assert supervisor.wait_healthy(15.0)
        results = {}

        def roll():
            results["out"] = supervisor.reload_fleet(
                "slow:1500:fp-mid", timeout_s=10.0,
                health_timeout_s=10.0, argv_patch=_stub_patch,
            )

        t = threading.Thread(target=roll)
        t.start()
        time.sleep(0.4)  # w0 is sleeping mid-swap
        faults.kill(supervisor.workers["w0"].pid)
        t.join(timeout=30.0)
        assert not results["out"]["ok"]
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            if supervisor.probe("w0") is not None:
                break
            time.sleep(0.05)
        assert _worker_fps(supervisor) == {"w0": "fp-old", "w1": "fp-old"}


def test_reload_fleet_concurrent_roll_refused(tmp_path):
    # the fleet-level mutex: a second reload_fleet while one is rolling
    # is refused deterministically — two interleaved rolls would leave
    # the fleet on mixed fingerprints with clobbered respawn argv
    with _reload_supervisor(tmp_path) as supervisor:
        assert supervisor.wait_healthy(15.0)
        results = {}

        def roll():
            results["out"] = supervisor.reload_fleet(
                "slow:800:fp-a", timeout_s=10.0,
                health_timeout_s=10.0, argv_patch=_stub_patch,
            )

        t = threading.Thread(target=roll)
        t.start()
        time.sleep(0.3)  # w0 is mid-swap inside the first roll
        second = supervisor.reload_fleet(
            "fp-b", timeout_s=10.0, health_timeout_s=10.0,
            argv_patch=_stub_patch,
        )
        t.join(timeout=30.0)
        assert second == {
            "ok": False,
            "corpus": "fp-b",
            "fingerprint": None,
            "rolled_back": False,
            "error": "fleet_reload_in_progress",
            "workers": {},
        }
        assert results["out"]["ok"]
        assert _worker_fps(supervisor) == {"w0": "fp-a", "w1": "fp-a"}


def test_stub_concurrent_reload_rejected(stub_fleet):
    # the worker-side guarantee satellite: a second reload while one is
    # mid-swap answers reload_in_progress, deterministically
    sock = stub_fleet.spawn("w0", "--fingerprint", "fp-old")
    rows = []

    def slow():
        rows.append(oneshot(
            sock, {"op": "reload", "corpus": "slow:800:fp-a"}, 10.0
        ))

    t = threading.Thread(target=slow)
    t.start()
    time.sleep(0.2)
    fast = oneshot(sock, {"op": "reload", "corpus": "fp-b"}, 10.0)
    t.join(timeout=15.0)
    assert fast.get("error") == "reload_in_progress"
    assert rows and rows[0]["reload"]["ok"]
    stats = oneshot(sock, {"op": "stats"}, 5.0)["stats"]
    assert stats["corpus"]["fingerprint"] == "fp-a"


def test_front_socket_reload_verb_delegates_to_supervisor(tmp_path):
    with _reload_supervisor(tmp_path) as supervisor:
        assert supervisor.wait_healthy(15.0)
        sockets = {
            name: h.socket_path for name, h in supervisor.workers.items()
        }
        with Router(
            sockets, supervisor=supervisor, probe_interval_s=0.05
        ) as router:
            front = str(tmp_path / "front.sock")
            server = FrontServer(front, router)
            st = threading.Thread(
                target=server.serve_forever,
                kwargs={"poll_interval": 0.05}, daemon=True,
            )
            st.start()
            try:
                row = oneshot(
                    front, {"id": 9, "op": "reload", "corpus": "fp-front"},
                    30.0,
                )
                assert row["reload"]["ok"], row
                assert row["reload"]["fingerprint"] == "fp-front"
                assert _worker_fps(supervisor) == {
                    "w0": "fp-front", "w1": "fp-front"
                }
                bad = oneshot(front, {"id": 10, "op": "reload"}, 10.0)
                assert "bad_request" in bad["error"]
            finally:
                server.shutdown()
                server.server_close()
                st.join(timeout=5.0)


def test_reload_fleet_selftest_stub_mode_passes():
    from licensee_tpu.fleet.selftest import selftest_reload

    assert selftest_reload(verbose=False, stub=True) == 0


# -- pipelined multiplexing: interleaving, correlation, failover --


class ScriptedWorker:
    """A test-local worker speaking raw JSONL over a Unix socket with
    per-connection scripting — the knife for pipelined-multiplexing
    edge cases the protocol-faithful stub cannot reach: a wrong trace
    echo, death with requests in flight, per-request service delays.
    Probes (``{"op": "stats"}``) always answer healthy; content rows
    go to ``on_content(ctx, msg, write_row)`` where ``ctx`` carries
    the connection socket and every content msg it has received."""

    def __init__(self, tmp_path, name: str, on_content):
        self.path = str(tmp_path / f"{name}.sock")
        self.on_content = on_content
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.path)
        self._listener.listen(16)
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn) -> None:
        ctx = {"conn": conn, "msgs": []}
        f = conn.makefile("rwb")

        def write_row(row: dict) -> None:
            try:
                f.write(json.dumps(row).encode("utf-8") + b"\n")
                f.flush()
            except (OSError, ValueError):
                pass

        try:
            while True:
                raw = f.readline()
                if not raw:
                    return
                try:
                    msg = json.loads(raw)
                except ValueError:
                    continue
                if msg.get("op") == "stats":
                    write_row({
                        "id": msg.get("id"),
                        "stats": {"scheduler": {
                            "queue_depth": 0, "in_flight": 0,
                        }},
                    })
                    continue
                ctx["msgs"].append(msg)
                self.on_content(ctx, msg, write_row)
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


def test_pipelined_requests_interleave_on_one_connection(tmp_path):
    """Three clients' requests pipeline onto ONE backend connection
    (pool bound 1); the worker holds every response until all three
    lines have arrived, then answers — each response must resolve to
    ITS client, cross-checked by the echoed trace ID."""

    def on_content(ctx, msg, write_row):
        if len(ctx["msgs"]) < 3:
            return
        for m in ctx["msgs"]:  # answer in request order: the contract
            write_row({
                "id": m["id"], "key": "stub-mit", "matcher": "scripted",
                "confidence": 99.0, "cached": False,
                "echo": m["content"], "trace": m.get("trace"),
            })
        ctx["msgs"].clear()

    worker = ScriptedWorker(tmp_path, "wscript", on_content)
    rows: dict[int, dict] = {}
    try:
        with Router(
            {"wscript": worker.path}, probe_interval_s=0.05,
            pool_per_worker=1, trace_sample=1.0,
        ) as router:

            def send(i: int) -> None:
                rows[i] = router.dispatch(
                    {"id": i, "content": f"blob-{i}"}
                )

            threads = [
                threading.Thread(target=send, args=(i,))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            stats = router.stats()
    finally:
        worker.close()
    assert set(rows) == {0, 1, 2}
    traces = set()
    for i, row in rows.items():
        assert not row.get("error"), row
        assert row["echo"] == f"blob-{i}", row
        traces.add(row["trace"])
    assert len(traces) == 3  # three distinct minted trace IDs
    # everything rode one pipelined connection
    assert stats["backends"]["wscript"]["pool_conns"] <= 1


def test_out_of_order_completion_across_pool_connections(tmp_path):
    """Submission order slow-then-fast; completion order fast-then-slow
    — the pool (bound 2) must not head-of-line block the fast request
    behind the slow one, and each answer resolves to its own client."""

    def on_content(ctx, msg, write_row):
        time.sleep(float(msg["content"].split(":")[1]) / 1000.0)
        write_row({
            "id": msg["id"], "key": "stub-mit", "matcher": "scripted",
            "confidence": 99.0, "cached": False,
            "echo": msg["content"], "trace": msg.get("trace"),
        })

    worker = ScriptedWorker(tmp_path, "wpool", on_content)
    done_order: list[tuple[str, dict]] = []
    try:
        with Router(
            {"wpool": worker.path}, probe_interval_s=0.05,
            pool_per_worker=2,
        ) as router:

            def send(tag: str, delay_ms: int) -> None:
                row = router.dispatch(
                    {"id": tag, "content": f"sleep:{delay_ms}"}
                )
                done_order.append((tag, row))

            slow = threading.Thread(target=send, args=("slow", 600))
            fast = threading.Thread(target=send, args=("fast", 10))
            slow.start()
            time.sleep(0.15)  # the slow request is in flight first
            fast.start()
            slow.join(timeout=30.0)
            fast.join(timeout=30.0)
    finally:
        worker.close()
    assert [tag for tag, _ in done_order] == ["fast", "slow"]
    by_tag = dict(done_order)
    assert by_tag["slow"]["echo"] == "sleep:600"
    assert by_tag["fast"]["echo"] == "sleep:10"


def test_trace_mismatch_burns_connection_and_fails_over(
    tmp_path, stub_fleet
):
    """A response echoing the WRONG trace ID is a protocol violation:
    the router must never deliver the mis-correlated verdict — the
    attempt fails over to the healthy twin and the poisoned connection
    is closed."""

    def on_content(ctx, msg, write_row):
        write_row({
            "id": msg["id"], "key": "evil", "matcher": "scripted",
            "confidence": 0.0, "cached": False,
            "trace": "beefbeefbeefbeef",
        })

    worker = ScriptedWorker(tmp_path, "wbad", on_content)
    good = stub_fleet.spawn("wgood")
    try:
        with Router(
            {"wbad": worker.path, "wgood": good},
            probe_interval_s=0.05, trace_sample=1.0,
        ) as router:
            rows = [
                router.dispatch({"id": i, "content": f"x{i}"})
                for i in range(4)
            ]
            stats = router.stats()
    finally:
        worker.close()
    assert all(not r.get("error") for r in rows), rows
    # the poisoned verdict never reached a client
    assert all(r.get("key") == "stub-mit" for r in rows), rows
    assert all(r.get("worker") == "wgood" for r in rows), rows
    assert stats["router"]["failovers"] >= 1


def test_backend_death_with_three_in_flight_fails_all_over(
    tmp_path, stub_fleet
):
    """The backend dies with 3 requests pipelined and unanswered on one
    connection: all 3 fail over to the surviving replica with zero
    client-visible errors."""
    died = threading.Event()

    def on_content(ctx, msg, write_row):
        if len(ctx["msgs"]) >= 3:
            died.set()
            # die: 3 in flight, none answered.  shutdown, not close —
            # the makefile wrapper holds the fd open past close()
            ctx["conn"].shutdown(socket.SHUT_RDWR)

    worker = ScriptedWorker(tmp_path, "wdead", on_content)
    # the survivor reports a standing queue so all 3 first land on the
    # (idle-looking) scripted worker
    survivor = stub_fleet.spawn("wsurvivor", "--report-load", "50")
    rows: list[dict] = []
    lock = threading.Lock()
    try:
        with Router(
            {"wdead": worker.path, "wsurvivor": survivor},
            probe_interval_s=0.05, pool_per_worker=1,
        ) as router:

            def send(i: int) -> None:
                row = router.dispatch({"id": i, "content": f"c{i}"})
                with lock:
                    rows.append(row)

            threads = [
                threading.Thread(target=send, args=(i,))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            stats = router.stats()
    finally:
        worker.close()
    assert died.is_set()
    assert len(rows) == 3
    assert all(not r.get("error") for r in rows), rows
    assert all(r.get("worker") == "wsurvivor" for r in rows), rows
    assert stats["router"]["failovers"] >= 3


# -- slowloris: slow/partial writers are reaped, never hold a slot --


def test_slowloris_dribble_reaped_while_traffic_flows(
    stub_fleet, tmp_path
):
    """A client dribbling bytes of a never-finished line is reaped by
    the stall sweep while normal traffic on other connections keeps
    answering — the attack holds no session, thread, or pool slot."""
    sockets = {"w0": stub_fleet.spawn("w0")}
    front = str(tmp_path / "front.sock")
    with Router(sockets, probe_interval_s=0.05) as router:
        server = FrontServer(front, router, stall_timeout_s=1.0)
        thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        thread.start()
        try:
            box: dict = {}
            loris = faults.Slowloris(
                front, mode="dribble", byte_interval_s=0.1,
                give_up_s=20.0,
            )
            lt = threading.Thread(target=lambda: box.update(loris.run()))
            lt.start()
            rows = []
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.connect(front)
                s.settimeout(10.0)
                f = s.makefile("rwb")
                for i in range(10):
                    f.write(
                        json.dumps({"id": i, "content": f"c{i}"}).encode()
                        + b"\n"
                    )
                    f.flush()
                    rows.append(json.loads(f.readline()))
            lt.join(timeout=25.0)
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)
    assert all(r.get("key") == "stub-mit" for r in rows)
    assert box.get("reaped") is True, box
    # reaped by the stall sweep, well before the client gave up
    assert box["elapsed_s"] < 10.0, box


def test_slowloris_half_close_reaped(stub_fleet, tmp_path):
    """A client that half-closes mid-line is reaped immediately (EOF
    with a partial line can never complete a request)."""
    sockets = {"w0": stub_fleet.spawn("w0")}
    front = str(tmp_path / "front.sock")
    with Router(sockets, probe_interval_s=0.05) as router:
        server = FrontServer(front, router, stall_timeout_s=5.0)
        thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        thread.start()
        try:
            result = faults.Slowloris(
                front, mode="half_close", give_up_s=10.0
            ).run()
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=5.0)
    assert result["reaped"] is True, result
    # the EOF-mid-line path reaps at once — no stall timeout needed
    assert result["elapsed_s"] < 3.0, result


# -- shutdown under load: every waiting client gets an answer --


def test_router_close_answers_queued_and_repick_parked_clients(tmp_path):
    """close() with every backend down must answer EVERY waiting
    client: requests parked on a repick timer (admitted, no healthy
    backend) and requests still in the admission queue would otherwise
    hang until the dispatch-stall budget once loop.stop() drops their
    timers."""
    dead = str(tmp_path / "never-booted.sock")
    rows: list[dict] = []
    lock = threading.Lock()
    router = Router(
        {"w0": dead}, probe_interval_s=0.05,
        dispatch_wait_s=60.0, max_concurrency=2,
    )
    router.start()

    def send(i: int) -> None:
        row = router.dispatch({"id": i, "content": f"c{i}"})
        with lock:
            rows.append(row)

    threads = [
        threading.Thread(target=send, args=(i,)) for i in range(5)
    ]
    for t in threads:
        t.start()
    # let 2 requests admit + park on repick and 3 queue in admission
    deadline = time.perf_counter() + 5.0
    while time.perf_counter() < deadline:
        snap = router.stats()["router"]
        if snap["active"] == 2 and snap["admission_queued"] == 3:
            break
        time.sleep(0.02)
    else:
        raise AssertionError(f"load never parked: {router.stats()}")
    t0 = time.perf_counter()
    router.close()
    for t in threads:
        t.join(timeout=10.0)
    assert not any(t.is_alive() for t in threads)
    # answered at close, not after the 60 s dispatch window
    assert time.perf_counter() - t0 < 5.0
    assert len(rows) == 5
    assert all(r["error"] == "router_closed" for r in rows), rows
