"""The whole-program analyzer (licensee_tpu/analysis/ + script/analyze).

Four layers of coverage:

* **fixture corpus** — tests/fixtures/analysis/<rule>/ holds >=2
  seeded true-positive (``tp_*``) and >=2 clean (``ok_*``) cases per
  rule.  A ``.py`` case is a one-file program (``analyze_source``); a
  DIRECTORY case is a multi-file program analyzed as its own root
  (``analyze_project``) — the cross-module rules' native habitat.
  Offending lines carry a ``# BAD`` marker (``<!-- BAD -->`` in
  markdown); a TP case's findings for its rule must hit EXACTLY the
  marked (file, line) pairs, and an OK case must produce none — both
  directions of each rule are pinned, not just "it fires".
* **engine semantics** — pragma suppression (inline, above-line, and
  def-scope), the stale-pragma ledger, path-component dir gating (the
  ``stripes_util.py`` prefix bug), and aliased-import resolution.
* **the protocol inventory** — the contract checker must enumerate
  the real wire ops (reload/stats/trace/content/queue_full/
  router_closed among >= 8) from the product tree, and a seeded
  stub-divergence fixture must fail ``script/analyze``.
* **the repo gate** — the real product tree analyzes clean, exactly
  what ``script/analyze`` asserts in script/cibuild (the analyzer's
  own package is part of that tree: the self-check), and the
  incremental cache is finding-identical warm vs cold.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from licensee_tpu.analysis import (
    PROGRAM_RULES,
    RULES,
    analyze_paths,
    analyze_project,
    analyze_source,
    iter_python_files,
)
from licensee_tpu.analysis.core import gate_matches

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "fixtures", "analysis"
)

# fixture directory -> rule id ("pragmas" exercises the engine, not a
# single rule)
DIR_TO_RULE = {
    "lock_discipline": "lock-discipline",
    "blocking_call": "blocking-call",
    "blocking_device_call": "blocking-device-call",
    "event_ring_purity": "event-ring-purity",
    "resource_leak": "resource-leak",
    "tracer_purity": "tracer-purity",
    "wallclock_time": "wallclock-time",
    "no_print": "no-print",
    "per_blob_featurize": "per-blob-featurize",
    "stale_pragma": "stale-pragma",
    "protocol_drift": "protocol-drift",
    "protocol_stub": "protocol-stub-divergence",
    "protocol_http": "protocol-http-drift",
    "metrics_doc": "metrics-doc",
}


def _fixture_files():
    cases = []
    for dirname, rule_id in sorted(DIR_TO_RULE.items()):
        dirpath = os.path.join(CORPUS, dirname)
        for name in sorted(os.listdir(dirpath)):
            if name.endswith(".py") or os.path.isdir(
                os.path.join(dirpath, name)
            ):
                cases.append(
                    (rule_id, os.path.join(dirpath, name), name)
                )
    return cases


def _marked_lines(text: str) -> set[int]:
    return {
        i
        for i, line in enumerate(text.splitlines(), 1)
        if line.rstrip().endswith(("# BAD", "<!-- BAD -->"))
    }


def _marked_in_dir(dirpath: str) -> set[tuple[str, int]]:
    marked = set()
    for walk_dir, _dirs, names in os.walk(dirpath):
        for name in sorted(names):
            path = os.path.join(walk_dir, name)
            rel = os.path.relpath(path, dirpath)
            with open(path, encoding="utf-8") as f:
                for line in _marked_lines(f.read()):
                    marked.add((rel, line))
    return marked


@pytest.mark.parametrize(
    "rule_id,path,name",
    [
        pytest.param(r, p, n, id=f"{r}/{n}")
        for r, p, n in _fixture_files()
    ],
)
def test_fixture_corpus(rule_id, path, name):
    if os.path.isdir(path):
        findings, _checked = analyze_project(path)
        hits = {(f.path, f.line) for f in findings if f.rule == rule_id}
        marked = _marked_in_dir(path)
    else:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        findings = analyze_source(text, rel=name, force_all=True)
        hits = {f.line for f in findings if f.rule == rule_id}
        marked = _marked_lines(text)
    if name.startswith("tp_"):
        assert marked, f"{name}: a TP fixture must mark its lines # BAD"
        assert hits == marked, (
            f"{name}: {rule_id} flagged {sorted(hits)}, "
            f"fixture marks {sorted(marked)}; findings: "
            f"{[f.render() for f in findings]}"
        )
    else:
        assert not hits, (
            f"{name}: clean fixture tripped {rule_id}: "
            f"{[f.render() for f in findings if f.rule == rule_id]}"
        )


def test_every_rule_has_tp_and_ok_fixtures():
    """>=2 seeded true-positive and >=2 clean cases per rule (files or
    multi-module program directories)."""
    for dirname in DIR_TO_RULE:
        names = os.listdir(os.path.join(CORPUS, dirname))
        tps = [n for n in names if n.startswith("tp_")]
        oks = [n for n in names if n.startswith("ok_")]
        assert len(tps) >= 2, f"{dirname}: wants >=2 tp_ fixtures"
        assert len(oks) >= 2, f"{dirname}: wants >=2 ok_ fixtures"


def test_rule_registry_complete():
    assert set(DIR_TO_RULE.values()) <= (
        set(RULES) | set(PROGRAM_RULES)
    ), "fixture corpus names a rule no registry defines"


# -- pragmas ------------------------------------------------------------


def test_pragma_fixtures_are_clean():
    dirpath = os.path.join(CORPUS, "pragmas")
    for name in sorted(os.listdir(dirpath)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(dirpath, name), encoding="utf-8") as f:
            findings = analyze_source(f.read(), rel=name)
        assert findings == [], (
            f"{name}: pragma failed to suppress: "
            f"{[f.render() for f in findings]}"
        )


def test_pragma_requires_matching_rule_id():
    src = (
        "import time\n"
        "\n"
        "\n"
        "def probe():\n"
        "    return time.time()  # analysis: disable=no-print\n"
    )
    findings = analyze_source(src)
    # the mismatched pragma must not suppress the wallclock finding —
    # and, suppressing nothing, it is itself reported stale
    assert [f.rule for f in findings] == ["stale-pragma", "wallclock-time"], [
        f.render() for f in findings
    ]


def test_pragma_above_decorated_def_covers_body():
    """'directly above a def' must keep working when a decorator sits
    between the pragma and the def line."""
    src = (
        "import time\n"
        "\n"
        "import jax\n"
        "\n"
        "\n"
        "# trace-time stamp on purpose (fixture)\n"
        "# analysis: disable=tracer-purity\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x + time.time()\n"
    )
    findings = analyze_source(src)
    assert not any(f.rule == "tracer-purity" for f in findings), [
        f.render() for f in findings
    ]


def test_guarded_attr_named_done_is_not_exempt():
    """Sync-hint exemptions must stay narrow: a guarded counter that
    happens to be called 'done' is still a race when read lock-free."""
    src = (
        "import threading\n"
        "\n"
        "\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.done = 0\n"
        "\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "\n"
        "    def _loop(self):\n"
        "        while self.done < 10:\n"
        "            self.bump()\n"
        "\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.done += 1\n"
    )
    findings = analyze_source(src)
    assert any(f.rule == "lock-discipline" for f in findings)


def test_tracer_taint_through_nested_assignment():
    """Taint must propagate in source order: a tracer-derived binding
    inside an earlier block taints a later same-level branch."""
    src = (
        "import jax\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x.ndim:\n"      # line 6: shielded static read — clean
        "        y = x\n"       # line 7: taints y, nested one block deep
        "    while y:\n"        # line 8: MUST flag (y is tracer-derived)
        "        y = y - 1\n"
        "    return y\n"
    )
    findings = analyze_source(src)
    branch_lines = {
        f.line
        for f in findings
        if f.rule == "tracer-purity" and "branches" in f.message
    }
    assert branch_lines == {8}, [f.render() for f in findings]


def test_nul_byte_file_reports_parse_error(tmp_path):
    """ast.parse raises a bare ValueError on NUL bytes; the driver must
    report a parse-error finding, never crash."""
    bad = tmp_path / "nul.py"
    bad.write_bytes(b"x = 1\x00\n")
    findings, checked = analyze_paths([str(bad)], str(tmp_path))
    assert checked == 0
    assert [f.rule for f in findings] == ["parse-error"]


def test_pragma_in_string_is_inert():
    src = (
        "import time\n"
        "\n"
        'NOTE = "# analysis: disable=wallclock-time"\n'
        "\n"
        "\n"
        "def probe():\n"
        "    return time.time()\n"
    )
    findings = analyze_source(src)
    assert [f.rule for f in findings] == ["wallclock-time"], (
        "a pragma inside a string literal must not suppress anything"
    )


# -- dir gating ---------------------------------------------------------


def test_gate_matches_on_components_not_prefixes():
    gate = ("licensee_tpu", "parallel", "stripes")
    # the module file and a submodule of a future package both match
    assert gate_matches(("licensee_tpu", "parallel", "stripes.py"), gate)
    assert gate_matches(
        ("licensee_tpu", "parallel", "stripes", "runner.py"), gate
    )
    # the string-prefix sibling must NOT match (the script/lint bug)
    assert not gate_matches(
        ("licensee_tpu", "parallel", "stripes_util.py"), gate
    )
    assert not gate_matches(("licensee_tpu", "parallel"), gate)


def test_house_rules_gated_to_their_dirs():
    src = "import time\n\n\ndef probe():\n    return time.time()\n"
    # ungated path: rule does not apply without force_all
    from licensee_tpu.analysis.core import Module, analyze_module

    outside = analyze_module(
        Module("licensee_tpu/corpus/license.py", src), force_all=False
    )
    assert not any(f.rule == "wallclock-time" for f in outside)
    inside = analyze_module(
        Module("licensee_tpu/serve/clock_util.py", src), force_all=False
    )
    assert [f.rule for f in inside] == ["wallclock-time"]


# -- the repo gate ------------------------------------------------------


def test_product_tree_is_clean():
    """The zero-findings assertion over the real licensee_tpu/ tree —
    every violation the rules surfaced was fixed or pragma'd with a
    justification in this PR; regressions fail here before cibuild."""
    findings, checked = analyze_paths(
        iter_python_files(REPO_ROOT), REPO_ROOT
    )
    assert checked > 50, "the scan should cover the product tree"
    assert findings == [], "\n".join(f.render() for f in findings)


def test_script_analyze_cli():
    """script/analyze exits 0 on the clean tree and prints the rule
    catalog with --list-rules."""
    script = os.path.join(REPO_ROOT, "script", "analyze")
    run = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        cwd=REPO_ROOT,
    )
    assert run.returncode == 0, run.stdout + run.stderr
    listing = subprocess.run(
        [sys.executable, script, "--list-rules"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert listing.returncode == 0
    for rule_id in DIR_TO_RULE.values():
        assert rule_id in listing.stdout


def test_protocol_inventory_enumerates_real_wire_ops():
    """The contract checker must see the REAL protocol: >= 8 wire ops
    extracted from product code, the load-bearing ones by name."""
    from licensee_tpu.analysis.core import Module
    from licensee_tpu.analysis.program import Program, summarize
    from licensee_tpu.analysis.rules_protocol import protocol_inventory

    summaries = []
    for path in iter_python_files(REPO_ROOT):
        rel = os.path.relpath(path, REPO_ROOT)
        with open(path, encoding="utf-8") as f:
            try:
                summaries.append(summarize(Module(rel, f.read())))
            except SyntaxError:  # pragma: no cover - tree is clean
                pass
    program = Program(summaries, root=REPO_ROOT, complete=True)
    ops = protocol_inventory(program)
    assert len(ops) >= 8, sorted(ops)
    for required in (
        "reload", "stats", "trace", "content",
        "queue_full", "router_closed",
    ):
        assert required in ops, f"{required} missing from {sorted(ops)}"
    # the verbs must have both directions of evidence in real code
    for verb in ("reload", "stats", "trace", "content"):
        assert ops[verb]["sent"], f"{verb}: no sender found"
        assert ops[verb]["handled"], f"{verb}: no handler found"


def test_stub_divergence_fixture_fails_script_analyze():
    """The acceptance drill: an op handled by the real worker but
    dropped from the stub fails script/analyze on that program dir."""
    script = os.path.join(REPO_ROOT, "script", "analyze")
    fixture = os.path.join(CORPUS, "protocol_stub", "tp_stub_drops_reload")
    run = subprocess.run(
        [sys.executable, script, fixture],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert run.returncode == 1, run.stdout + run.stderr
    assert "protocol-stub-divergence" in run.stdout
    assert "reload" in run.stdout


def test_cross_module_blocking_fixture_fails_script_analyze():
    script = os.path.join(REPO_ROOT, "script", "analyze")
    fixture = os.path.join(CORPUS, "blocking_call", "tp_cross_module_recv")
    run = subprocess.run(
        [sys.executable, script, fixture],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert run.returncode == 1, run.stdout + run.stderr
    assert "blocking-call" in run.stdout
    assert "wire_helpers.py" in run.stdout


# -- the incremental cache ----------------------------------------------


def test_cache_warm_run_is_finding_identical_and_parse_free(tmp_path):
    """Cold run fills the cache; the warm run must miss nothing and
    reproduce the exact findings (the --cache-ab CI gate's substance,
    minus the timing assertion)."""
    from licensee_tpu.analysis.program import AnalysisCache, engine_salt

    files = [
        p
        for p in iter_python_files(REPO_ROOT)
        if os.sep + "analysis" + os.sep in p or p.endswith("wire.py")
    ]
    assert len(files) > 5
    salt = engine_salt()
    cache_path = str(tmp_path / "analyze.json")
    cold_cache = AnalysisCache(cache_path, salt)
    cold, n_cold = analyze_paths(
        files, REPO_ROOT, complete=False, cache=cold_cache
    )
    assert cold_cache.misses == n_cold and cold_cache.hits == 0
    cold_cache.save()
    warm_cache = AnalysisCache(cache_path, salt)
    warm, n_warm = analyze_paths(
        files, REPO_ROOT, complete=False, cache=warm_cache
    )
    assert warm_cache.hits == n_warm and warm_cache.misses == 0
    assert [f.render() for f in cold] == [f.render() for f in warm]


def test_cache_invalidated_by_content_and_salt(tmp_path):
    from licensee_tpu.analysis.program import AnalysisCache

    src = tmp_path / "leaky.py"
    src.write_text(
        "def read(path):\n"
        "    text = open(path).read()\n"
        "    return text\n",
        encoding="utf-8",
    )
    cache_path = str(tmp_path / "cache.json")
    cache = AnalysisCache(cache_path, "salt-1")
    first, _ = analyze_paths([str(src)], str(tmp_path), cache=cache)
    assert [f.rule for f in first] == ["resource-leak"]
    cache.save()
    # same salt + same content: a hit
    cache2 = AnalysisCache(cache_path, "salt-1")
    again, _ = analyze_paths([str(src)], str(tmp_path), cache=cache2)
    assert cache2.hits == 1 and [f.rule for f in again] == ["resource-leak"]
    # the fix changes the content hash: the entry must not be reused
    src.write_text(
        "def read(path):\n"
        "    with open(path) as f:\n"
        "        return f.read()\n",
        encoding="utf-8",
    )
    fixed, _ = analyze_paths([str(src)], str(tmp_path), cache=cache2)
    assert fixed == []
    # an engine edit (new salt) drops the whole cache
    cache3 = AnalysisCache(cache_path, "salt-2")
    assert cache3.get("leaky.py", "anything") is None


def test_script_analyze_cache_ab_gate():
    """The CI flag itself: cold vs warmed over a fresh cache must be
    finding-identical and faster."""
    import json as jsonlib

    script = os.path.join(REPO_ROOT, "script", "analyze")
    run = subprocess.run(
        [sys.executable, script, "--cache-ab"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert run.returncode == 0, run.stdout + run.stderr
    out = jsonlib.loads(run.stdout)
    assert out["cache_ab"] == "ok"
    assert out["finding_identical"] is True
    assert out["warm_s"] < out["cold_s"]
    assert out["warm_cache_misses"] == 0


def test_script_analyze_stats_flag():
    script = os.path.join(REPO_ROOT, "script", "analyze")
    run = subprocess.run(
        [sys.executable, script, "--stats", "--no-cache"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert run.returncode == 0, run.stdout + run.stderr
    assert "analyze --stats:" in run.stderr
    for rule_id in ("blocking-call", "protocol-drift", "resource-leak"):
        assert rule_id in run.stderr, run.stderr


def test_script_analyze_flags_a_violation(tmp_path):
    """The CLI path end to end: an explicit file with a violation
    exits 1 and prints file:line: rule-id."""
    bad = tmp_path / "bad_clock.py"
    bad.write_text(
        "import time\n\n\ndef probe():\n    return time.time()\n",
        encoding="utf-8",
    )
    script = os.path.join(REPO_ROOT, "script", "analyze")
    gated = subprocess.run(
        [sys.executable, script, str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    # dir gating holds through the CLI: the wallclock rule is scoped to
    # serve/obs/fleet/stripes, and a tmp-path file is outside them
    assert gated.returncode == 0, gated.stdout + gated.stderr
    # a file outside the gated dirs still runs the ungated rules, so
    # use one of those for the violation exit-code check
    leak = tmp_path / "bad_leak.py"
    leak.write_text(
        "def read(path):\n"
        "    text = open(path).read()\n"
        "    return text\n",
        encoding="utf-8",
    )
    run = subprocess.run(
        [sys.executable, script, str(leak)],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert run.returncode == 1, run.stdout + run.stderr
    assert "resource-leak" in run.stdout
