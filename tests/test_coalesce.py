"""Cross-batch device coalescing (kernels/batch.py merge_prepared +
the gather buffer in BatchProject.run).

A dedupe-heavy manifest leaves each produced batch a handful of device
(``todo``) rows; round 4 measured the per-batch padded dispatch at 78%
of elapsed on the 1M dup-heavy run.  The coalescer merges those sparse
tails across batches into full ``pad_batch_to`` chunks while preserving
the in-order write / resume invariant.  These tests pin the merge
round-trip, the ordering invariant, and the dispatch-count reduction.
"""

import json

import numpy as np
import pytest

from licensee_tpu.kernels.batch import BatchClassifier, PreparedBatch
from licensee_tpu.projects.batch_project import BatchProject

from conftest import fixture_contents, fixture_path


@pytest.fixture(scope="module")
def clf():
    return BatchClassifier(pad_batch_to=64)


def _prepare(clf, contents, **kw):
    return clf.prepare_batch(contents, **kw)


def test_merge_scatter_roundtrip_matches_per_batch(clf):
    """Merging N prepared batches, scoring once, and scattering back
    produces exactly the per-batch results."""
    mit = fixture_contents("mit/LICENSE.txt")
    isc = fixture_contents("gpl-3.0_markdown/LICENSE.md")
    junk = "not a license at all, just words " * 40
    batches = [
        [mit + "\nnoise one", junk, isc + "\nmore"],
        [isc, "x" * 10],
        [junk + " tail", mit + " altered slightly"],
    ]
    # reference: classify each batch separately
    want = [
        [(r.key, r.matcher, round(r.confidence, 6)) for r in
         clf.classify_blobs(b, prefilter=False)]
        for b in batches
    ]

    prepared = [_prepare(clf, b, prefilter=False) for b in batches]
    merged = clf.merge_prepared(prepared)
    assert len(merged.todo) == sum(len(p.todo) for p in prepared)
    outs = clf.dispatch_chunks(merged)
    clf.finish_chunks(merged, outs, 98.0)
    BatchClassifier.scatter_merged(prepared, merged)
    got = [
        [(r.key, r.matcher, round(r.confidence, 6)) for r in p.results]
        for p in prepared
    ]
    assert got == want


def test_merge_handles_compacted_and_preset_mix(clf):
    """Compacted batches (feature rows sliced to todo) and batches with
    preset rows merge into one correct device batch."""
    mit = fixture_contents("mit/LICENSE.txt")
    junk = "plainly unlicensed prose " * 30
    from licensee_tpu.kernels.batch import BlobResult

    preset_row = BlobResult("cached", "dice", 99.0)
    p1 = _prepare(
        clf,
        [junk, mit + " v1", junk + "!"],
        prefilter=False,
        preset=[None, None, preset_row],
    )
    assert p1.todo == [0, 1]
    p1.compact_features()
    assert p1.bits.shape[0] == 2  # sliced to the todo rows
    p2 = _prepare(clf, [mit + " v2"], prefilter=False)
    merged = clf.merge_prepared([p1, p2])
    outs = clf.dispatch_chunks(merged)
    clf.finish_chunks(merged, outs, 98.0)
    BatchClassifier.scatter_merged([p1, p2], merged)
    assert p1.results[2] is preset_row  # untouched
    assert p1.results[1].key == "mit"
    assert p2.results[0].key == "mit"
    assert p1.results[0].key is None


def test_merge_carries_readme_sections(clf_readme=None):
    """The readme Reference fallback rides the merged batch: a section
    Dice can't match but Reference can still matches at 90."""
    clf = BatchClassifier(pad_batch_to=32, mode="readme")
    body = "# Proj\n\n## License\n\nLicensed under the MIT license.\n"
    p1 = clf.prepare_batch([body], filenames=["README.md"])
    p2 = clf.prepare_batch(
        ["# Other\n\n## License\n\nsome unrecognizable words\n"],
        filenames=["README.md"],
    )
    merged = clf.merge_prepared([p1, p2])
    assert merged.sections is not None
    outs = clf.dispatch_chunks(merged)
    clf.finish_chunks(merged, outs, 98.0)
    BatchClassifier.scatter_merged([p1, p2], merged)
    assert (p1.results[0].key, p1.results[0].matcher) == ("mit", "reference")
    assert p1.results[0].confidence == 90.0
    assert p2.results[0].key is None


def test_coalesced_run_output_order_and_dispatch_count(tmp_path):
    """A dup-heavy manifest writes every row in manifest order while the
    coalescer collapses many sparse batches into few device dispatches."""
    mit = fixture_contents("mit/LICENSE.txt")
    paths = []
    for i in range(12):
        d = tmp_path / f"r{i}"
        d.mkdir()
        p = d / "LICENSE"
        if i == 0 or i == 7:
            # unique rows: only these should reach the device after the
            # cache warms
            p.write_text(mit + f"\nunique tail {i}")
        else:
            p.write_text(mit + "\nshared tail")
        paths.append(str(p))

    project = BatchProject(
        paths, batch_size=2, workers=1, inflight=1, coalesce_batches=4
    )
    calls = []
    # the pipeline's device seam is the ASYNC submit (run() never calls
    # the sync wrapper -- the blocking-device-call analysis rule)
    orig = project.classifier.dispatch_chunks_async

    def counting(prepared):
        calls.append(len(prepared.todo))
        return orig(prepared)

    project.classifier.dispatch_chunks_async = counting
    out = tmp_path / "out.jsonl"
    stats = project.run(str(out), resume=False)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths  # manifest order exactly
    assert all(r["key"] == "mit" for r in rows)
    assert stats.total == 12
    # far fewer dispatches than batches (6 batches of 2): the shared-tail
    # rows dedupe away and the rest coalesce
    assert len(calls) <= 3, calls


def test_coalesce_cap_bounds_group_size(tmp_path):
    """coalesce_batches=1 must behave exactly like the uncoalesced
    pipeline (one dispatch per batch that has device rows)."""
    mit = fixture_contents("mit/LICENSE.txt")
    paths = []
    for i in range(4):
        p = tmp_path / f"f{i}"
        p.write_text(mit + f"\ntail {i}")  # all unique -> all todo
        paths.append(str(p))
    project = BatchProject(
        paths, batch_size=2, workers=1, inflight=1, coalesce_batches=1
    )
    calls = []
    # the pipeline's device seam is the ASYNC submit (run() never calls
    # the sync wrapper -- the blocking-device-call analysis rule)
    orig = project.classifier.dispatch_chunks_async

    def counting(prepared):
        calls.append(len(prepared.todo))
        return orig(prepared)

    project.classifier.dispatch_chunks_async = counting
    out = tmp_path / "out.jsonl"
    project.run(str(out), resume=False)
    assert calls == [2, 2]
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths


def test_coalesce_batches_validation():
    with pytest.raises(ValueError):
        BatchProject(["x"], coalesce_batches=0)


def test_resume_mid_group_boundary(tmp_path):
    """Resume lands on a batch boundary inside what WOULD be one
    coalesced group: rows must neither repeat nor skip."""
    mit = fixture_contents("mit/LICENSE.txt")
    paths = []
    for i in range(10):
        p = tmp_path / f"g{i}"
        p.write_text(mit + "\nsame tail")
        paths.append(str(p))
    out = tmp_path / "out.jsonl"
    p1 = BatchProject(paths[:4], batch_size=2, workers=1, coalesce_batches=8)
    p1.run(str(out), resume=False)
    # torn tail: partial row without newline
    with open(out, "a", encoding="utf-8") as f:
        f.write('{"path": "torn"')
    p2 = BatchProject(paths, batch_size=2, workers=1, coalesce_batches=8)
    p2.run(str(out), resume=True)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths
    assert all(r["key"] == "mit" for r in rows)


def test_merge_prepared_empty_and_singleton(clf):
    mit = fixture_contents("mit/LICENSE.txt")
    p = _prepare(clf, [mit], prefilter=False)
    # singleton, uncompacted: merge is the identity (no copy)
    assert clf.merge_prepared([p]) is p
    # all-preset group: merged batch has zero rows
    from licensee_tpu.kernels.batch import BlobResult

    row = BlobResult("k", "dice", 99.0)
    q = _prepare(clf, ["x"], prefilter=False, preset=[row])
    merged = clf.merge_prepared([q, q])
    assert merged.todo == [] and merged.bits.shape[0] == 0


def test_attribution_rides_coalesced_device_rows(tmp_path):
    """--attribution on rows that reach the device (dice-matched, not
    prefiltered) and finish through a merged multi-batch group: the
    write loop must still find each row's raw content for the regex."""
    mit = fixture_contents("mit/LICENSE.txt")
    paths = []
    for i in range(12):
        d = tmp_path / f"r{i}"
        d.mkdir()
        p = d / "LICENSE"
        if i % 4 == 0:
            # one device row per 4-row batch: unique one-word tail ->
            # no dedupe, no exact prefilter, still >= 98% dice
            p.write_text(mit + f"\nzyxtail{i}")
        else:
            # exact-prefiltered on host: keeps each batch's todo sparse
            # so the gather buffer accumulates MULTIPLE batches
            p.write_text(mit)
        paths.append(str(p))
    out = tmp_path / "out.jsonl"
    project = BatchProject(
        paths, batch_size=4, workers=1, inflight=1,
        attribution=True, coalesce_batches=3,
    )
    group_sizes = []
    orig = project.classifier.merge_prepared

    def spying(group):
        group_sizes.append(len(group))
        return orig(group)

    project.classifier.merge_prepared = spying
    project.run(str(out), resume=False)
    # the scenario under test really happened: a merged MULTI-batch group
    assert any(g >= 2 for g in group_sizes), group_sizes
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths
    dice_rows = [r for i, r in enumerate(rows) if i % 4 == 0]
    assert all(
        r["key"] == "mit" and r["matcher"] == "dice" for r in dice_rows
    )
    assert all(r["key"] == "mit" for r in rows)
    assert all(
        r["attribution"] == "Copyright (c) 2016 Ben Balter" for r in rows
    )


@pytest.mark.slow
def test_coalesced_pipeline_differential_random_manifests(tmp_path):
    """Property: for ANY manifest, run() with coalescing (cap 8) writes
    byte-identical JSONL to run() with coalescing disabled (cap 1).
    Random mix of modes, duplicate densities, readmes/packages/sources,
    unreadable paths, and attribution."""
    import random

    rng = random.Random(20260730)
    mit = fixture_contents("mit/LICENSE.txt")
    gpl = fixture_contents("gpl-3.0_markdown/LICENSE.md")

    # a pool of on-disk files covering every route
    pool = []
    pooldir = tmp_path / "pool"
    pooldir.mkdir()
    for i in range(40):
        kind = rng.randrange(6)
        d = pooldir / f"d{i}"
        d.mkdir()
        if kind == 0:
            p = d / "LICENSE"
            p.write_text(mit + (f"\nzz{i}" if rng.random() < 0.5 else ""))
        elif kind == 1:
            p = d / "LICENSE.md"
            p.write_text(gpl if rng.random() < 0.7 else f"no license {i}")
        elif kind == 2:
            p = d / "README.md"
            body = (
                "## License\n\nReleased under the MIT License.\n"
                if rng.random() < 0.5
                else "## Usage\n\nnothing here\n"
            )
            p.write_text(f"# P{i}\n\n" + body)
        elif kind == 3:
            p = d / "package.json"
            p.write_text('{"license": "Apache-2.0"}')
        elif kind == 4:
            p = d / f"mod{i}.c"
            p.write_text(f"int f{i}(void);\n")
        else:
            p = d / "LICENSE"  # never created -> read_error row
        pool.append(str(p))

    for trial, mode in enumerate(("license", "auto", "readme")):
        entries = [rng.choice(pool) for _ in range(120)]
        outs = []
        for cap in (1, 8):
            out = tmp_path / f"out_{mode}_{cap}.jsonl"
            project = BatchProject(
                entries,
                batch_size=8,
                workers=2,
                mode=mode,
                attribution=(mode != "readme"),
                coalesce_batches=cap,
                dedupe=(trial != 1),
            )
            project.run(str(out), resume=False)
            outs.append(out.read_text())
        assert outs[0] == outs[1], f"mode={mode}: coalesced diverged"


def test_cli_coalesce_batches_flag(tmp_path):
    from licensee_tpu.cli.main import main

    mit = fixture_contents("mit/LICENSE.txt")
    for i in range(3):
        d = tmp_path / f"c{i}"
        d.mkdir()
        (d / "LICENSE").write_text(mit)
    manifest = tmp_path / "m.txt"
    manifest.write_text(
        "\n".join(str(tmp_path / f"c{i}" / "LICENSE") for i in range(3)) + "\n"
    )
    out = tmp_path / "out.jsonl"
    rc = main([
        "batch-detect", str(manifest), "--output", str(out),
        "--coalesce-batches", "4", "--mesh", "none", "--no-resume",
    ])
    assert rc == 0
    assert len(out.read_text().splitlines()) == 3
    # validation at the argparse layer, before any manifest loads
    with pytest.raises(SystemExit):
        main([
            "batch-detect", str(manifest), "--coalesce-batches", "0",
        ])


def test_merged_group_spanning_multiple_device_chunks():
    """A coalesced group whose todo rows exceed pad_batch_to must split
    into several padded chunks and still scatter correctly."""
    clf = BatchClassifier(pad_batch_to=4)
    mit = fixture_contents("mit/LICENSE.txt")
    gpl = fixture_contents("gpl-3.0_markdown/LICENSE.md")
    batches = [
        [mit + f" a{i}", gpl + f" b{i}", f"plain words {i} " * 30]
        for i in range(3)
    ]  # 9 todo rows -> 3 chunks of pad 4
    want = [
        [r.key for r in clf.classify_blobs(b, prefilter=False)]
        for b in batches
    ]
    prepared = [
        clf.prepare_batch(b, prefilter=False) for b in batches
    ]
    for p in prepared:
        p.compact_features()
    merged = clf.merge_prepared(prepared)
    assert len(merged.todo) == 9 > clf.pad_batch_to
    outs = clf.dispatch_chunks(merged)
    assert len(outs) == 3  # ceil(9 / 4) padded chunks
    clf.finish_chunks(merged, outs, 98.0)
    BatchClassifier.scatter_merged(prepared, merged)
    got = [[r.key for r in p.results] for p in prepared]
    assert got == want
    assert want[0][0] == "mit" and want[0][1] == "gpl-3.0"
