"""--featurize-procs: worker-PROCESS featurization (GIL insurance).

The process path must be bit-identical to the thread path — same rows,
same order, same resume behavior — with the cross-batch dedupe cache
applied in the parent and no jax backend ever initialized in a worker
(device=False classifier).
"""

from __future__ import annotations

import json
import os

import pytest

from licensee_tpu.kernels.batch import BatchClassifier
from licensee_tpu.projects.batch_project import BatchProject
from tests.conftest import fixture_path


def fixture_bytes(name: str) -> bytes:
    with open(fixture_path(name), "rb") as f:
        return f.read()


def _mixed_corpus(tmp_path, n_repos: int = 6):
    """A small mixed tree with dups (dedupe), a near-miss (Dice+closest),
    a package manifest, and an unrecognized file (auto routing)."""
    mit = fixture_bytes("mit/LICENSE.txt")
    paths = []
    for i in range(n_repos):
        d = tmp_path / f"repo{i}"
        d.mkdir()
        (d / "LICENSE").write_bytes(
            mit if i % 2 == 0 else mit + b"\nnudged off exact\n"
        )
        (d / "package.json").write_text('{"license": "Apache-2.0"}\n')
        (d / "main.c").write_text(f"int f(void) {{ return {i}; }}\n")
        paths += [
            str(d / "LICENSE"),
            str(d / "package.json"),
            str(d / "main.c"),
        ]
    return paths


def _run(paths, out, **kwargs):
    project = BatchProject(
        paths,
        batch_size=4,
        workers=2,
        inflight=2,
        mode="auto",
        closest=2,
        threshold=90,
        attribution=True,
        **kwargs,
    )
    stats = project.run(str(out), resume=False)
    return stats, out.read_text()


@pytest.mark.slow
def test_process_path_bit_identical_to_threads(tmp_path):
    paths = _mixed_corpus(tmp_path)
    _, want = _run(paths, tmp_path / "threads.jsonl")
    stats, got = _run(paths, tmp_path / "procs.jsonl", featurize_procs=2)
    assert got == want  # byte-identical JSONL
    # the parent-side cache fired for the repeated contents
    assert stats.dedupe_hits >= 1


@pytest.mark.slow
def test_process_path_resume(tmp_path):
    paths = _mixed_corpus(tmp_path, n_repos=4)
    out = tmp_path / "out.jsonl"
    # phase 1: first half only, then a torn tail simulating a crash
    p1 = BatchProject(
        paths[: len(paths) // 2], batch_size=4, featurize_procs=2,
        mode="auto",
    )
    p1.run(str(out), resume=False)
    with open(out, "a", encoding="utf-8") as f:
        f.write('{"path": "torn"')
    # phase 2: resume over the full manifest, still in process mode
    p2 = BatchProject(paths, batch_size=4, featurize_procs=2, mode="auto")
    p2.run(str(out), resume=True)
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert [r["path"] for r in rows] == paths
    # ground truth: one thread-path pass over everything
    ref = tmp_path / "ref.jsonl"
    BatchProject(paths, batch_size=4, mode="auto").run(
        str(ref), resume=False
    )
    assert out.read_text() == ref.read_text()


def test_device_false_classifier_prepares_but_cannot_dispatch():
    clf = BatchClassifier(pad_batch_to=8, device=False)
    assert clf._fn is None and clf.arrays is None
    prepared = clf.prepare_batch(
        [fixture_bytes("mit/LICENSE.txt"), b"some random words"],
        filenames=["LICENSE", "LICENSE"],
    )
    # the exact prefilter still fires host-side
    assert prepared.results[0].matcher == "exact"
    assert prepared.todo == [1]
    with pytest.raises(RuntimeError):
        clf.dispatch_chunks(prepared)


def test_worker_state_roundtrip():
    """_mp_init + _mp_produce run in-process too (what each spawned
    worker executes): the corpus object pickles, the host-only
    classifier builds, and a produced batch carries featurized rows."""
    import pickle

    from licensee_tpu.projects import batch_project as bp

    corpus = BatchClassifier(pad_batch_to=8).corpus
    corpus = pickle.loads(pickle.dumps(corpus))  # the spawn crossing
    bp._mp_init(corpus, "license", 8)
    try:
        chunk = [fixture_path("mit/LICENSE.txt")]
        (paths, read_errs, keys, preset, dup_of, routes, prepared,
         contents, pre_rows,
         _times) = bp._mp_produce(chunk, "license", True, False)
        assert paths == chunk
        assert read_errs == [None]  # clean reads carry no error code
        assert keys[0] is not None
        assert prepared.results[0].matcher == "exact"
    finally:
        bp._MP_STATE.clear()
